"""Independence solver — partition constraints into variable-disjoint
buckets and solve each bucket separately (reference
laser/smt/solver/independence_solver.py:38, rebuilt on the Term DAG
instead of z3 expression trees).

Two constraints are dependent iff they share a free symbol (bitvector
symbol, array, or uninterpreted function); dependence buckets are the
connected components of that relation, maintained incrementally as
conditions arrive. check() solves every bucket with its own Solver — one
UNSAT bucket proves the whole set UNSAT; all-SAT merges the per-bucket
assignments into one Model (model completion covers untouched symbols).

Like the reference's, this solver is opt-in (the batched device fan-out in
support/model.py is the production path); it pays off on queries whose
constraint sets contain large independent clusters, e.g. multi-contract
world states."""

from typing import Dict, List, Optional, Set

from mythril_tpu.smt import terms
from mythril_tpu.smt.model import Model
from mythril_tpu.smt.solver.frontend import SAT, UNSAT, UNKNOWN, Solver


def _condition_symbols(raw: terms.Term) -> Set[str]:
    names = set()
    for node in terms.walk_terms([raw]):
        if node.op in ("sym", "array"):
            names.add(node.params[0])
        elif node.op == "apply":
            names.add(node.params[0].name)
    return names


class DependenceBucket:
    """Conditions that transitively share symbols."""

    def __init__(self):
        self.variables: Set[str] = set()
        self.conditions: List[terms.Term] = []


class DependenceMap:
    """Incrementally-maintained connected components over shared symbols
    (reference independence_solver.py:38-101)."""

    def __init__(self):
        self.buckets: List[DependenceBucket] = []
        self.variable_map: Dict[str, DependenceBucket] = {}

    def add_condition(self, raw: terms.Term) -> None:
        symbols = _condition_symbols(raw)
        relevant = []
        seen = set()
        for name in symbols:
            bucket = self.variable_map.get(name)
            if bucket is not None and id(bucket) not in seen:
                seen.add(id(bucket))
                relevant.append(bucket)
        if relevant:
            target = relevant[0]
            for other in relevant[1:]:
                target.variables |= other.variables
                target.conditions += other.conditions
                self.buckets.remove(other)
        else:
            target = DependenceBucket()
            self.buckets.append(target)
        target.variables |= symbols
        target.conditions.append(raw)
        for name in target.variables:
            self.variable_map[name] = target


class IndependenceSolver:
    """Drop-in Solver variant: same add/check/model surface."""

    def __init__(self, timeout: Optional[float] = None):
        self.timeout = timeout
        self.raw_constraints: List[terms.Term] = []
        self._models: List[Model] = []
        self._last_status: Optional[str] = None

    def set_timeout(self, timeout_ms: int) -> None:
        self.timeout = timeout_ms / 1000.0

    def add(self, *constraints) -> None:
        for constraint in constraints:
            if isinstance(constraint, (list, tuple)):
                self.add(*constraint)
                continue
            raw = getattr(constraint, "raw", constraint)
            self.raw_constraints.append(raw)

    append = add

    def check(self, *extra) -> str:
        dep_map = DependenceMap()
        for raw in self.raw_constraints:
            dep_map.add_condition(raw)
        for constraint in extra:
            dep_map.add_condition(getattr(constraint, "raw", constraint))
        self._models = []
        self._last_status = None
        unknown = False
        for bucket in dep_map.buckets:
            sub = Solver(timeout=self.timeout)
            sub.add(bucket.conditions)
            status = sub.check()
            if status == UNSAT:
                self._last_status = UNSAT
                return UNSAT  # one impossible bucket sinks the whole set
            if status != SAT:
                # keep scanning: a later bucket may still prove UNSAT (a
                # timeout on one cluster must not hide a provable verdict)
                unknown = True
                continue
            self._models.append(sub.model())
        self._last_status = UNKNOWN if unknown else SAT
        return self._last_status

    def model(self) -> Model:
        if self._last_status != SAT:
            raise ValueError("no model available (last check was not sat)")
        return Model(sub_models=self._models)

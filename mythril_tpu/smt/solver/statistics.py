"""Query-count/time singleton (reference laser/smt/solver/solver_statistics.py)."""

import time
from functools import wraps


class SolverStatistics:
    _instance = None

    # every counter the singleton tracks; used by reset/as_dict/absorb so a
    # new counter only has to be added in one place
    _COUNTERS = (
        "query_count",
        "batch_query_count",
        "device_batch_queries",
        "device_batch_hits",
        "device_ineligible",
        "cap_rejects",
        "cap_rejects_floor",
        "router_host_direct",
        "router_slot_overflow",
        "device_dispatches",
        "device_dispatched_queries",
        "device_slots",
        "crosscheck_runs",
        "crosscheck_cap_skips",
        # solve-service tiers (mythril_tpu/service/): where each query's
        # verdict actually came from
        "memory_hits",
        "quick_sat_hits",
        "persistent_hits",
        "persistent_misses",
        "persistent_stores",
        "persistent_verify_rejects",
        # coalescing scheduler windows
        "window_flushes",
        "coalesced_queries",
        # real host-CDCL solver invocations (counted at the sat_backend
        # terminal solve — the number every cache tier exists to shrink)
        "cdcl_settles",
        # clause volume those terminal settles actually processed: the
        # work numerator of the settle stage's roofline row
        # (observe/roofline.py — attained clauses/s = cdcl_clauses /
        # settle_wall, against the calibrated CDCL rate ceiling)
        "cdcl_clauses",
        # static pre-analysis (mythril_tpu/preanalysis/): solver traffic
        # proven unnecessary before any solve — the SOLAR-style
        # "speed-of-light" denominator
        "modules_gated",
        "queries_avoided",
        "cnf_units_propagated",
        "cnf_pure_literals",
        "cnf_clauses_removed",
        "cnf_components_split",
        "router_dispatched_clauses",
        # AIG structural analysis & rewriting (preanalysis/aig_opt.py):
        # per-instance cone sizes before/after the strash+sweep rewrite,
        # what each pass removed, and how the partition projected onto the
        # device path (preanalysis/aig_partition.py + tpu/router.py)
        "aig_nodes_before",
        "aig_nodes_after",
        "aig_strash_merges",
        "aig_const_folds",
        "aig_trivial_unsat",
        "aig_components",
        "aig_device_components",
        # ragged paged device dispatch (tpu/router.py + tpu/circuit.py
        # RaggedStream): whole coalescing windows packed into flat gate
        # streams with per-cone offset tables, the cones they carried,
        # the assembled stream bytes (the ragged roofline stage's work
        # unit), and the cube-and-conquer second pass — cubes shipped as
        # assumption-pinned replicas and cubes that came back modelless
        # (candidate refutations; only the host CDCL can confirm UNSAT)
        "ragged_windows",
        "ragged_cones_packed",
        "paged_stream_bytes",
        "cubes_dispatched",
        "cube_device_refutes",
        # device-kernel backend (tpu/pallas_kernel.py): shape-polymorphic
        # Pallas round launches, the block-aligned real-gate cells they
        # stepped (the pallas_cells_s rate unit — a strict subset of the
        # window rectangle the XLA rounds pay for), and device-kernel
        # recompiles — every DISTINCT compile signature after the
        # process's first. The XLA rounds key on the full window
        # rectangle so fresh shapes keep counting; the Pallas round keys
        # only on its fixed capacity tuple, which is the zero-recompile
        # property the bench kernel_backend leg pins.
        "pallas_launches",
        "pallas_cells_stepped",
        "kernel_recompiles",
        # cross-contract ragged packing (service/interleave.py driver +
        # tpu/router.py origin-tagged windows): ragged streams that
        # carried cones from >= 2 DISTINCT contracts in one launch, the
        # cones those mixed streams packed, and persistent-tier entries
        # (whole instances or FINGERPRINT SCHEMA 3 component sub-models)
        # stored by one contract's analysis and reused by another's —
        # the cross-contract dedup the content-addressed disk tier buys
        "xcontract_windows",
        "xcontract_cones_packed",
        "xcontract_dedup_hits",
        # incremental cross-query preparation (smt/solver/incremental.py):
        # word-level work reused from sibling queries' prepares — memoized
        # simplify hits, prefix-snapshot resumes (suffix-only pipelines),
        # guarded full-pipeline fallbacks, and cross-query strash reuse in
        # the session rewrite table (preanalysis/aig_opt.py)
        "prepare_incremental_hits",
        "prepare_prefix_resumes",
        "prepare_prefix_fallbacks",
        "prepare_suffix_terms",
        "strash_xquery_merges",
        # vmapped symbolic-execution frontier (laser/frontier/): batched
        # device steps over sibling machine states, how many states each
        # step actually carried (occupancy denominator is the padded slot
        # count), and how many states exited a batch back to the per-state
        # interpreter mid-run
        "frontier_vmap_steps",
        "frontier_states_stepped",
        # states handed back to the per-state interpreter at a
        # batch-capable site: mid-run bails (frontier_batch_bails, a
        # subset) plus rows whose run CUT at an unforked JUMPI /
        # unpromoted RETURN/STOP/CALLDATALOAD and per-state handoffs at
        # lane-capable sites the configuration left unbatched — the
        # branch_fusion / symlane on/off comparator. Always the sum of
        # the per-reason breakdown below.
        "frontier_fallback_exits",
        # per-reason breakdown of frontier_fallback_exits, so the next
        # promotion target is named by counter instead of by re-running
        # the opcode histogram by hand:
        #   dialect   the batch dialect simply ends here — cut-at-JUMPI
        #             completions with forking off, cut-at-RETURN/STOP
        #             completions with the symbolic lane off, and
        #             per-state handoffs at minimal fork sites the
        #             configuration left unbatched
        #   dynamic   mid-run dynamic bails (memory access beyond the
        #             dense window, gas exhaustion) and encodability
        #             refusals at minimal sites for non-symbolic causes
        #   hook      rows bailed so a conditionally-transparent hook
        #             could fire per-state (tripped value guard, or a
        #             guarded store about to write a symbolic word the
        #             predicate cannot judge)
        #   symbolic  symbolic-operand exits — a consumed slot, memory
        #             offset, jump destination, or RETURN operand was
        #             opaque where the configuration (or the kernel)
        #             requires a dynamically-concrete value, including
        #             cut-at-CALLDATALOAD completions with the lane off
        "frontier_fallback_dialect",
        "frontier_fallback_dynamic",
        "frontier_fallback_hook",
        "frontier_fallback_symbolic",
        # mid-run bails only (slot-occupying rows that exited the batch
        # before completing) — the occupancy numerator's second half
        "frontier_batch_bails",
        "frontier_batch_slots",
        # symbolic-value lane (laser/frontier/symlane.py): rows whose
        # decode replayed the structural op log into the original BitVec
        # terms (at least one opaque lane) instead of the kernel's
        # concrete limbs — the in-batch symbolic traffic the lane admits
        "frontier_symlane_rows",
        # device-side branching (laser/frontier/stepper.py): batched
        # symbolic-JUMPI forks — fork events (batch steps that forked),
        # the rows that split into taken/fall-through cohorts, sides
        # masked dead after a solver-confirmed (host-CDCL) infeasibility
        # verdict, and ragged stream launches that carried fork-side
        # feasibility cones (tpu/router.py fork lane)
        "frontier_forks",
        "frontier_fork_rows",
        # materialized fork successors beyond one per forked row (the
        # fall-through clones): a forked slot leaves the step as TWO
        # live dense rows, so occupancy credits the extra cohort row —
        # without it a fork-heavy batch under-reports how much live
        # state its slots actually produced
        "frontier_fork_cohort_rows",
        "frontier_fork_infeasible_pruned",
        "fork_stream_dispatches",
        # shared-cone fork-pair packing (tpu/router.py _pack_fork_pair):
        # pairs the ragged fork lane TRIED to pack as one shared cone
        # with per-side extra assumption roots, and pairs that actually
        # packed shared and rode the stream that way — the hit rate the
        # root-forcing-deferred aig_opt sweep exists to raise (a forced
        # per-side constant sweep diverges the shared base roots)
        "fork_pair_pack_attempts",
        "fork_pair_pack_hits",
        # fault containment (mythril_tpu/resilience/): every degradation
        # a registered fault site took — retries with jittered backoff,
        # per-stage breaker trips and half-open re-probes, quarantined
        # cache entries, degraded-to-oracle events, hard-deadline trips
        # at the device seam, --jobs worker requeues, stale lock breaks,
        # and deterministically injected faults (the chaos harness).
        # The per-site breakdown lives in resilience_events (emitted as
        # the stats JSON "resilience" section).
        # serve daemon (mythril_tpu/serve/): request admission outcomes,
        # cross-request batches (how many requests shared one
        # interleaved batch and how many distinct tenants they came
        # from — the per-tenant window share behind the
        # serve_tenant_window_share gauge), deadline-killed requests
        # requeued / answered incomplete, and completed requests
        "serve_requests_admitted",
        "serve_requests_rejected",
        "serve_requests_requeued",
        "serve_requests_incomplete",
        "serve_requests_completed",
        "serve_batches",
        "serve_batch_requests",
        "serve_batch_tenants",
        # sharded serve fleet (mythril_tpu/fleet/): digest-keyed shard
        # routing decisions, requests re-routed to a surviving shard
        # after a shard fault, crash-only shard restarts by the
        # supervisor, and the shared NETWORK result tier — entries
        # served across processes (replay-verified on every hit),
        # entries stored into it, and shared entries that failed
        # replay/provenance verification and were quarantined as safe
        # misses on the reading shard
        "fleet_shard_routes",
        "fleet_requeues",
        "fleet_shard_restarts",
        "net_tier_hits",
        "net_tier_stores",
        "net_tier_verify_rejects",
        # autotune loop (mythril_tpu/tune/): search candidates measured,
        # candidates rejected by the findings-parity guard / by measuring
        # no better than the default config, tuned knobs actually live
        # this process (profile applied, not shadowed by explicit env),
        # and corrupt/stale tuned profiles ignored at apply time
        "autotune_candidates_tried",
        "autotune_rejected_parity",
        "autotune_rejected_regression",
        "tuned_knobs_applied",
        "tuned_profile_rejects",
        "resilience_retries",
        "resilience_breaker_trips",
        "resilience_breaker_probes",
        "resilience_quarantines",
        "resilience_degraded",
        "resilience_deadline_trips",
        "resilience_worker_requeues",
        "resilience_stale_lock_breaks",
        "resilience_faults_injected",
    )

    # resilience event name -> the scalar counter it rolls up into
    _RESILIENCE_EVENT_COUNTERS = {
        "retry": "resilience_retries",
        "breaker_trip": "resilience_breaker_trips",
        "breaker_probe": "resilience_breaker_probes",
        "quarantine": "resilience_quarantines",
        "degraded": "resilience_degraded",
        "deadline": "resilience_deadline_trips",
        "worker_requeue": "resilience_worker_requeues",
        "stale_break": "resilience_stale_lock_breaks",
        "injected": "resilience_faults_injected",
    }
    _TIMERS = (
        "solver_time",
        "route_device_seconds",
        "route_host_seconds",
        # solver wall attribution: prepare (simplify/lower/blast/rewrite)
        # vs host settle (route_host_seconds) vs device dispatch
        # (route_device_seconds) — so future rounds can see where the wall
        # goes without re-profiling by hand
        "prepare_wall",
        # wall spent stepping states in LaserEVM.exec (per-state
        # execute_state calls + batched frontier steps), with solver
        # seconds spent INSIDE instruction handlers (concretization,
        # tx-end confirmations) subtracted out — they are already
        # attributed to solver_time, and leaving them in would bury the
        # stepping cost the frontier targets under solver noise. The
        # interpreter-side counterpart of prepare_wall in the wall split.
        "interp_wall",
        # wall spent INSIDE terminal host-CDCL solves (session probes,
        # native and python solvers alike) — the settle component of the
        # roofline wall decomposition (observe/roofline.py). A subset of
        # solver_time by construction.
        "settle_wall",
        # wall spent re-proving detection UNSATs on permuted instances
        # (sat_backend._crosscheck_unsat) — soundness-net overhead,
        # reported separately so it can never masquerade as settle cost
        "crosscheck_wall",
        # wall spent in the frontier's batched fork epilogue (pending-
        # condition rebuild, sibling feasibility bundle, cohort
        # materialization) — busy denominator of the frontier.fork
        # roofline stage (work = frontier_fork_rows). Feasibility solver
        # seconds are INCLUDED: the fused step→solve round trip is
        # exactly what this stage times
        "frontier_fork_wall",
        # serve daemon walls: summed queue wait of admitted requests
        # (admission latency — the soak harness derives its p99 from
        # per-request samples; this is the roll-up mean's numerator)
        # and the SIGTERM drain (stop-admitting -> last in-flight
        # request resolved -> final reconciled heartbeat written)
        "serve_admission_wall",
        "serve_drain_wall",
    )

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enabled = False
            for name in cls._COUNTERS:
                setattr(cls._instance, name, 0)
            for name in cls._TIMERS:
                setattr(cls._instance, name, 0.0)
            # suffix-length histogram of prefix resumes (not a scalar, so
            # it lives outside _COUNTERS; reset/as_dict/absorb handle it
            # explicitly)
            cls._instance.prepare_suffix_hist = {}
            # opcode -> [count, seconds] over the per-state interpreter
            # path (the frontier's fallback oracle); as_dict emits the
            # top-10 by cumulative wall so each bench round names the
            # opcodes worth promoting into the frontier fast set next
            cls._instance.interp_opcode_wall = {}
            # fault site -> {event name: count} (resilience/registry.py
            # sites); the per-site view behind the scalar resilience_*
            # counters, emitted as the stats JSON "resilience" section
            cls._instance.resilience_events = {}
        return cls._instance

    def add_query(self, seconds: float) -> None:
        if self.enabled:
            self.query_count += 1
            self.solver_time += seconds

    def add_batch(self, num_queries: int, seconds: float) -> None:
        """One get_models_batch call covering num_queries sibling queries."""
        if self.enabled:
            self.batch_query_count += num_queries
            self.solver_time += seconds

    def add_device_batch_query(self, hit: bool) -> None:
        """A query that reached the batched device solver (hit = model
        found on device; miss = CDCL settled it)."""
        if self.enabled:
            self.device_batch_queries += 1
            if hit:
                self.device_batch_hits += 1

    def add_device_ineligible(self) -> None:
        """A query that could not take the device path (dense-cap/empty)."""
        if self.enabled:
            self.device_ineligible += 1

    def add_cap_reject(self, count: int = 1,
                       under_floor: bool = False) -> None:
        """A circuit the size caps (or the router cost model) turned away
        from the device. Counted here (not just on the backend) so the
        analyze stats line and bench can report silently-dropped device work
        (round-5 verdict: 100% of eligible analyze cones were cap-rejected
        with no trace). `under_floor` marks a reject of a cone at or under
        the router's level floor — the class the routing layer GUARANTEES
        admission for; `cap_rejects_floor` staying 0 is the regression
        invariant."""
        if self.enabled:
            self.cap_rejects += count
            if under_floor:
                self.cap_rejects_floor += count

    def add_host_direct(self, count: int = 1) -> None:
        """Queries the router's cost model sent straight to the host CDCL
        (too small to amortize a device dispatch)."""
        if self.enabled:
            self.router_host_direct += count

    def add_slot_overflow(self, count: int = 1) -> None:
        """Device-worthy queries trimmed from a dispatch by the
        evidence-mode slot cap (a different decision than host_direct:
        these cones were big enough, the evidence budget was not)."""
        if self.enabled:
            self.router_slot_overflow += count

    def add_device_dispatch(self, queries: int, slots: int,
                            seconds: float) -> None:
        """One bucketed device fan-out: `queries` live queries padded to
        `slots` device slots. occupancy = queries/slots aggregated."""
        if self.enabled:
            self.device_dispatches += 1
            self.device_dispatched_queries += queries
            self.device_slots += slots
            self.route_device_seconds += seconds

    def add_host_route_seconds(self, seconds: float) -> None:
        if self.enabled:
            self.route_host_seconds += seconds

    def add_crosscheck(self, skipped: bool) -> None:
        """A detection-path UNSAT verdict's second opinion: ran, or was
        skipped by CROSSCHECK_CLAUSE_CAP. The ratio is the fraction of
        detection UNSATs that actually got a second opinion."""
        if self.enabled:
            if skipped:
                self.crosscheck_cap_skips += 1
            else:
                self.crosscheck_runs += 1

    def add_memory_hit(self) -> None:
        """A query settled by the in-memory term-keyed result tier."""
        if self.enabled:
            self.memory_hits += 1

    def add_quick_sat_hit(self) -> None:
        """A query settled by the recent-model quick-sat probe."""
        if self.enabled:
            self.quick_sat_hits += 1

    def add_persistent_lookup(self, hit: bool) -> None:
        """A disk-tier probe of a blasted instance fingerprint. A
        verify-rejected or provenance-rejected entry counts as a miss
        (the caller also records the reject reason)."""
        if self.enabled:
            if hit:
                self.persistent_hits += 1
            else:
                self.persistent_misses += 1

    def add_persistent_store(self) -> None:
        if self.enabled:
            self.persistent_stores += 1

    def add_persistent_verify_reject(self) -> None:
        """A disk-tier SAT entry whose replayed assignment failed model
        validation against the original constraints (fingerprint collision
        or corrupted file) — degraded to a safe miss, never a verdict."""
        if self.enabled:
            self.persistent_verify_rejects += 1

    def add_window_flush(self, queries: int) -> None:
        """One coalescing-scheduler flush covering `queries` buffered
        queries (service/scheduler.py)."""
        if self.enabled:
            self.window_flushes += 1
            self.coalesced_queries += queries

    def add_cdcl_settle(self, clauses: int = 0,
                        seconds: float = 0.0) -> None:
        """One real host-CDCL solver invocation (sat_backend terminal
        solve). Every cache tier exists to shrink this number; warm runs
        must show strictly fewer than cold runs. `clauses` and `seconds`
        feed the settle stage of the roofline (work and busy wall)."""
        if self.enabled:
            self.cdcl_settles += 1
            self.cdcl_clauses += clauses
            self.settle_wall += seconds

    def add_crosscheck_seconds(self, seconds: float) -> None:
        """Wall of one permuted-instance UNSAT re-solve (the detection
        soundness net) — kept out of settle_wall so the roofline's settle
        rate reflects verdict-producing work only."""
        if self.enabled:
            self.crosscheck_wall += seconds

    def add_module_gated(self, count: int = 1) -> None:
        """A detection module the static reachability gate skipped
        attaching — its hooks, predicate solves, and confirmations never
        happen this run (preanalysis module gating)."""
        if self.enabled:
            self.modules_gated += count

    def add_queries_avoided(self, count: int = 1) -> None:
        """Fork-pruning feasibility solves skipped because static
        pre-analysis proved the state's remaining cone inert — queries
        the engine would otherwise have paid for."""
        if self.enabled:
            self.queries_avoided += count

    def add_cnf_preprocess(self, units: int, pures: int,
                           removed_clauses: int) -> None:
        """One blasted instance simplified by the static CNF preprocessor
        before fingerprinting/dispatch (preanalysis/cnf_prep.py)."""
        if self.enabled:
            self.cnf_units_propagated += units
            self.cnf_pure_literals += pures
            self.cnf_clauses_removed += removed_clauses

    def add_cnf_split(self, components: int) -> None:
        """One instance the CDCL settled as `components` variable-disjoint
        sub-instances instead of a single monolithic solve."""
        if self.enabled:
            self.cnf_components_split += components

    def add_aig_opt(self, nodes_before: int, nodes_after: int,
                    strash_merges: int, const_folds: int,
                    trivial_unsat: bool = False) -> None:
        """One blasted cone rewritten by the AIG strash/sweep passes
        (preanalysis/aig_opt.py) before CNF emission, fingerprinting and
        dispatch. A statically-proven-UNSAT root set is counted but the
        verdict still settles through the CDCL (crosscheck policy)."""
        if self.enabled:
            self.aig_nodes_before += nodes_before
            self.aig_nodes_after += nodes_after
            self.aig_strash_merges += strash_merges
            self.aig_const_folds += const_folds
            if trivial_unsat:
                self.aig_trivial_unsat += 1

    def add_aig_components(self, components: int) -> None:
        """One optimized cone partitioned into `components` variable-
        disjoint sub-cones at the AIG level (counted per prepared
        instance, whether or not the router later dispatches them)."""
        if self.enabled:
            self.aig_components += components

    def add_ragged_window(self, cones: int, stream_bytes: int) -> None:
        """One ragged flat stream dispatched (a single kernel launch
        covering `cones` variable-shape cones), with the assembled
        paged-stream bytes it shipped. A coalescing window that chunks
        under the byte/round budgets counts once per stream — the unit
        is the launch, which is what the evidence cap bounds."""
        if self.enabled:
            self.ragged_windows += 1
            self.ragged_cones_packed += cones
            self.paged_stream_bytes += stream_bytes

    def add_xcontract_window(self, cones: int) -> None:
        """One ragged stream launch whose cones came from >= 2 distinct
        origins (contracts) — the cross-contract packing seam actually
        firing. `cones` is the stream's whole cone count: every cone on
        a mixed stream shares the one launch the mixing amortizes."""
        if self.enabled:
            self.xcontract_windows += 1
            self.xcontract_cones_packed += cones

    def add_xcontract_dedup_hit(self, count: int = 1) -> None:
        """A persistent-tier entry (whole-instance or component
        sub-model) recorded by one contract's analysis and served to a
        DIFFERENT contract's query this process — the disk tier's
        content-addressed fingerprints deduping identical sub-cones
        across contracts."""
        if self.enabled:
            self.xcontract_dedup_hits += count

    def add_cube_dispatch(self, cubes: int, refuted: int = 0) -> None:
        """One cube-and-conquer pass: `cubes` assumption-pinned replicas
        of a hard cone rode a ragged stream; `refuted` of them came back
        modelless (candidate refutations — the host CDCL remains the
        sole UNSAT oracle)."""
        if self.enabled:
            self.cubes_dispatched += cubes
            self.cube_device_refutes += refuted

    def add_pallas_launch(self, cells: int) -> None:
        """One shape-polymorphic Pallas round launch (interpret mode or
        pl.pallas_call), stepping `cells` block-aligned real-gate cells
        (steps x 2 x the stream's padded gate count — the
        pallas_cells_s calibration unit)."""
        if self.enabled:
            self.pallas_launches += 1
            self.pallas_cells_stepped += cells

    def add_kernel_recompile(self, count: int = 1) -> None:
        """A device round compiled a DISTINCT kernel signature after the
        process's first — the per-window-shape compile cost the
        shape-polymorphic Pallas kernel exists to retire (its signature
        is the fixed capacity tuple, so it never lands here)."""
        if self.enabled:
            self.kernel_recompiles += count

    def add_aig_device_components(self, components: int) -> None:
        """Partitioned sub-cones that rode a device dispatch individually
        (the per-component root projection the router performs for
        multi-component instances)."""
        if self.enabled:
            self.aig_device_components += components

    def add_router_clauses(self, clauses: int) -> None:
        """CNF clause volume of queries reaching the device router —
        preprocessed shrinkage shows up here as smaller dispatched cones."""
        if self.enabled:
            self.router_dispatched_clauses += clauses

    def add_prepare_seconds(self, seconds: float) -> None:
        """Wall spent inside Solver._prepare (simplify + substitution +
        lowering + blasting + AIG rewrite + CNF preprocessing) — the
        prepare component of the solver-wall split."""
        if self.enabled:
            self.prepare_wall += seconds

    def add_prepare_simplify_hits(self, count: int = 1) -> None:
        """Constraint terms whose simplification was served from the
        cross-query simplify memo (smt/solver/incremental.py) instead of
        a full DAG walk."""
        if self.enabled:
            self.prepare_incremental_hits += count

    @staticmethod
    def _suffix_bucket(suffix_terms: int) -> str:
        if suffix_terms == 0:
            return "0"
        if suffix_terms == 1:
            return "1"
        if suffix_terms <= 4:
            return "2-4"
        if suffix_terms <= 16:
            return "5-16"
        return "17+"

    def add_prefix_resume(self, suffix_terms: int) -> None:
        """One prepare resumed from a sibling query's prefix snapshot:
        only `suffix_terms` new constraints went through substitution /
        lowering (0 = exact prefix match, the whole word-level phase was
        skipped). The histogram shows the suffix-size distribution the
        monotone path-constraint growth actually produces."""
        if self.enabled:
            self.prepare_prefix_resumes += 1
            self.prepare_suffix_terms += suffix_terms
            bucket = self._suffix_bucket(suffix_terms)
            self.prepare_suffix_hist[bucket] = (
                self.prepare_suffix_hist.get(bucket, 0) + 1)

    def add_prefix_fallback(self) -> None:
        """A prepare that found a prefix snapshot but had to re-run the
        full pipeline: a suffix term introduced a new `sym == rhs`
        definition or a narrowing bound that would substitute back
        through the already-lowered prefix."""
        if self.enabled:
            self.prepare_prefix_fallbacks += 1

    def add_strash_xquery(self, count: int) -> None:
        """Gates a cone rewrite reused from SIBLING queries via the
        session strash/rewrite table (preanalysis/aig_opt.py) — cross-
        query structural sharing the per-query fresh-table rewrite of
        PR 4 could not see."""
        if self.enabled:
            self.strash_xquery_merges += count

    def add_frontier_step(self, states: int, slots: int,
                          fallback_exits: int = 0,
                          cut_exits: int = 0,
                          hook_exits: int = 0,
                          symbolic_exits: int = 0,
                          symbolic_cuts: int = 0,
                          sym_rows: int = 0) -> None:
        """One batched frontier step: `states` sibling machine states
        executed a straight-line opcode run as one device step, padded to
        `slots` batch slots (the jit shape bucket). Mid-run bails back to
        the per-state interpreter are split by reason: `fallback_exits`
        dynamic bails (memory-window overflow, gas exhaustion, a
        dynamically-symbolic operand where the kernel needs a concrete
        value), `hook_exits` rows bailed so a conditionally-transparent
        hook fires per-state (tripped value guard), `symbolic_exits`
        symbolic-operand bails. `cut_exits` / `symbolic_cuts` are
        completed rows whose run cut at an unforked JUMPI /
        unpromoted RETURN/STOP (dialect) or at a CALLDATALOAD the
        symbolic lane was off for (symbolic-operand) — they leave the
        batch dialect but, unlike bails, also count as stepped rows.
        `sym_rows` completed rows decoded via the symbolic lane's
        structural replay (counted inside `states` too)."""
        if self.enabled:
            self.frontier_vmap_steps += 1
            self.frontier_states_stepped += states
            self.frontier_batch_slots += slots
            bails = fallback_exits + hook_exits + symbolic_exits
            self.frontier_batch_bails += bails
            self.frontier_fallback_exits += bails + cut_exits \
                + symbolic_cuts
            self.frontier_fallback_dynamic += fallback_exits
            self.frontier_fallback_hook += hook_exits
            self.frontier_fallback_symbolic += symbolic_exits \
                + symbolic_cuts
            self.frontier_fallback_dialect += cut_exits
            self.frontier_symlane_rows += sym_rows

    def add_fork_site_exit(self, count: int = 1,
                           reason: str = "dialect") -> None:
        """A state handed to the per-state interpreter at a
        lane-capable site the configuration left unbatched (fork or
        symbolic-lane feature off, hook-gated, depth-capped, or
        unencodable at the minimal run) — the off-leg side of the
        branch_fusion / symlane fallback-exit comparison. `reason`
        names the breakdown bucket (dialect / dynamic / symbolic)."""
        if self.enabled:
            self.frontier_fallback_exits += count
            counter = f"frontier_fallback_{reason}"
            setattr(self, counter, getattr(self, counter) + count)

    def add_frontier_fork(self, rows: int, seconds: float = 0.0,
                          cohort_rows: int = 0) -> None:
        """One batched fork event: `rows` live sibling rows reached a
        symbolic JUMPI and split batch-wise into taken/fall-through
        cohorts inside the dense representation; `seconds` is the fork
        epilogue wall (pending-condition rebuild + coalesced feasibility
        + cohort materialization); `cohort_rows` materialized successors
        BEYOND one per forked row (the fall-through clones) — credited
        to the batch-occupancy numerator, since each forked slot left
        the step as that many extra live dense rows."""
        if self.enabled:
            self.frontier_forks += 1
            self.frontier_fork_rows += rows
            self.frontier_fork_wall += seconds
            self.frontier_fork_cohort_rows += cohort_rows

    def add_fork_pair_pack(self, hit: bool) -> None:
        """One fork pair the ragged lane tried to pack as a shared cone
        (both sides blasted in one AIG, root sets differing by exactly
        the fork literal and its negation). `hit` = it packed shared and
        both sides rode one stream page set; a miss packs the sides
        individually — still fork traffic, just no page sharing."""
        if self.enabled:
            self.fork_pair_pack_attempts += 1
            if hit:
                self.fork_pair_pack_hits += 1

    def add_fork_pruned(self, count: int = 1) -> None:
        """Fork sides masked dead after a solver-confirmed (host-CDCL
        UNSAT oracle) infeasibility verdict — never device-candidate
        evidence — before the side materialized as a GlobalState."""
        if self.enabled:
            self.frontier_fork_infeasible_pruned += count

    def add_fork_stream_dispatch(self, count: int = 1) -> None:
        """One ragged stream launch that carried fork-side feasibility
        cones (shared-cone extra-root pairs or per-side cones alike)."""
        if self.enabled:
            self.fork_stream_dispatches += count

    def add_resilience_event(self, site: str, event: str,
                             count: int = 1) -> None:
        """One fault-containment event at a registered fault site
        (mythril_tpu/resilience/): bumps the matching resilience_*
        scalar and the per-site breakdown behind the stats JSON
        "resilience" section."""
        if self.enabled:
            counter = self._RESILIENCE_EVENT_COUNTERS.get(event)
            if counter is not None:
                setattr(self, counter, getattr(self, counter) + count)
            per_site = self.resilience_events.setdefault(site, {})
            per_site[event] = per_site.get(event, 0) + count

    def add_interp_seconds(self, seconds: float) -> None:
        """Wall spent stepping states in LaserEVM.exec (per-state +
        batched) — the interpreter component of the wall split."""
        if self.enabled:
            self.interp_wall += seconds

    def add_interp_opcode_wall(self, opcode: str, seconds: float) -> None:
        """One per-state (fallback-path) instruction execution: feeds the
        per-opcode cumulative-wall histogram."""
        if self.enabled:
            record = self.interp_opcode_wall.get(opcode)
            if record is None:
                self.interp_opcode_wall[opcode] = [1, seconds]
            else:
                record[0] += 1
                record[1] += seconds

    def add_serve_admission(self, admitted: bool) -> None:
        """One serve-daemon admission decision: admitted into the
        bounded queue, or rejected (`overloaded`/`draining` — the
        explicit backpressure answer instead of unbounded latency)."""
        if self.enabled:
            if admitted:
                self.serve_requests_admitted += 1
            else:
                self.serve_requests_rejected += 1

    def add_serve_wait_seconds(self, seconds: float) -> None:
        """Queue latency of one admitted request (submit -> its batch
        popped): the admission-latency roll-up behind the soak
        harness's per-request p99 samples."""
        if self.enabled:
            self.serve_admission_wall += seconds

    def add_serve_batch(self, requests: int, tenants: int) -> None:
        """One cross-request serve batch handed to the interleave
        coordinator: `requests` admitted requests from `tenants`
        distinct tenants share its coalescing windows."""
        if self.enabled:
            self.serve_batches += 1
            self.serve_batch_requests += requests
            self.serve_batch_tenants += tenants

    def add_serve_outcome(self, outcome: str) -> None:
        """Terminal disposition of one serve request: completed (a real
        report, ok or error), requeued (deadline/worker fault — goes
        around once more), or incomplete (second failure; answered,
        never hung)."""
        if self.enabled:
            if outcome == "completed":
                self.serve_requests_completed += 1
            elif outcome == "requeued":
                self.serve_requests_requeued += 1
            elif outcome == "incomplete":
                self.serve_requests_incomplete += 1

    def add_serve_drain_seconds(self, seconds: float) -> None:
        if self.enabled:
            self.serve_drain_wall += seconds

    def add_fleet_route(self, count: int = 1) -> None:
        """One digest-keyed shard-routing decision (fleet/router.py):
        the request's code digest picked its shard by rendezvous hash
        (or round-robin under the fleet.route degradation fuse)."""
        if self.enabled:
            self.fleet_shard_routes += count

    def add_fleet_requeue(self, count: int = 1) -> None:
        """A fleet request re-routed to a surviving shard after its
        first shard died or faulted mid-proxy — goes around exactly
        once, then answers `incomplete` (never lost, never hung)."""
        if self.enabled:
            self.fleet_requeues += count

    def add_fleet_shard_restart(self, count: int = 1) -> None:
        """One crash-only shard restart by the fleet supervisor (dead
        process or repeated health-probe failure); the replacement
        re-warms from the shared network tier."""
        if self.enabled:
            self.fleet_shard_restarts += count

    def add_net_tier_hit(self, count: int = 1) -> None:
        """A shared network-tier entry served to this process — stored
        by ANY shard, replay-verified through Solver._reconstruct (SAT)
        or the UNSAT provenance gate before being trusted. A strict
        subset of persistent_hits when the network tier is mounted."""
        if self.enabled:
            self.net_tier_hits += count

    def add_net_tier_store(self, count: int = 1) -> None:
        """An entry this process published into the shared network
        tier, where every shard in the fleet can serve it."""
        if self.enabled:
            self.net_tier_stores += count

    def add_net_tier_verify_reject(self, count: int = 1) -> None:
        """A shared network-tier entry that failed replay/provenance
        verification on the reading shard — quarantined there as a safe
        miss; the writing shard keeps running untouched."""
        if self.enabled:
            self.net_tier_verify_rejects += count

    def add_autotune_candidate(self) -> None:
        """One candidate configuration measured by the autotune search."""
        if self.enabled:
            self.autotune_candidates_tried += 1

    def add_autotune_rejected(self, parity: bool) -> None:
        """A tried candidate rejected: `parity` = its probe findings
        were not byte-identical to the default config's (the hard guard
        — its wall never ranked); otherwise it was not persisted — no
        better than the default config within the margin, eliminated by
        a successive-halving round, or failed/timed out under the
        candidate budget. candidates_tried always reconciles as
        parity + regression + (1 if a winner persisted)."""
        if self.enabled:
            if parity:
                self.autotune_rejected_parity += 1
            else:
                self.autotune_rejected_regression += 1

    def add_tuned_knobs_applied(self, count: int) -> None:
        """Tuned-profile knobs live this process (installed at startup
        and not shadowed by an explicit env var)."""
        if self.enabled:
            self.tuned_knobs_applied += count

    def add_tuned_profile_reject(self) -> None:
        """A persisted tuned profile ignored at apply time (corrupt
        file, stale schema, unregistered/malformed knobs) — counted so a
        silently-defaulting run says why."""
        if self.enabled:
            self.tuned_profile_rejects += 1

    @property
    def serve_tenant_window_share(self) -> float:
        """Mean requests each tenant contributed per serve batch — the
        per-tenant share of a cross-request window (1.0 = every batch
        held one request per tenant; higher = some tenant occupied more
        of the shared window than its siblings)."""
        if not self.serve_batch_tenants:
            return 0.0
        return self.serve_batch_requests / self.serve_batch_tenants

    @property
    def frontier_batch_occupancy(self) -> float:
        """Mean live dense rows per padded frontier batch slot
        (states_stepped + mid-run bails are all live on entry; padding
        to the jit shape bucket is the waste). Fork-cohort rows — the
        extra fall-through clones a forked slot materializes — count in
        the numerator too: a fork-heavy batch's slots each produce up
        to two live rows, and excluding them under-reported occupancy
        on exactly the batches device-side branching exists for (may
        exceed 1.0 on fork-dense batches by construction). Dialect
        exits that never occupied a slot (fork-site handoffs) are
        deliberately excluded."""
        if not self.frontier_batch_slots:
            return 0.0
        return (self.frontier_states_stepped + self.frontier_batch_bails
                + self.frontier_fork_cohort_rows) \
            / self.frontier_batch_slots

    @property
    def coalesce_occupancy(self) -> float:
        """Mean queries per coalescing-window flush (>1 means single-query
        traffic actually merged into multi-query dispatches)."""
        if not self.window_flushes:
            return 0.0
        return self.coalesced_queries / self.window_flushes

    @property
    def device_occupancy(self) -> float:
        """Mean fraction of padded device batch slots holding live queries."""
        if not self.device_slots:
            return 0.0
        return self.device_dispatched_queries / self.device_slots

    def reset(self) -> None:
        for name in self._COUNTERS:
            setattr(self, name, 0)
        for name in self._TIMERS:
            setattr(self, name, 0.0)
        self.prepare_suffix_hist = {}
        self.interp_opcode_wall = {}
        self.resilience_events = {}

    def interp_opcode_wall_top(self, n: int = 10) -> dict:
        """Top-`n` fallback-path opcodes by cumulative wall:
        {opcode: [count, seconds]} — which opcodes the per-state
        interpreter still pays for (the frontier promotion shortlist)."""
        ranked = sorted(self.interp_opcode_wall.items(),
                        key=lambda item: item[1][1], reverse=True)
        return {op: [count, round(seconds, 4)]
                for op, (count, seconds) in ranked[:n]}

    def as_dict(self) -> dict:
        """Plain-data snapshot (pickles across the --jobs worker boundary;
        serializes to the MYTHRIL_TPU_STATS_JSON bench artifact)."""
        out = {name: getattr(self, name) for name in self._COUNTERS}
        out.update(
            {name: round(getattr(self, name), 4) for name in self._TIMERS})
        out["device_occupancy"] = round(self.device_occupancy, 4)
        out["coalesce_occupancy"] = round(self.coalesce_occupancy, 4)
        out["frontier_batch_occupancy"] = round(
            self.frontier_batch_occupancy, 4)
        out["serve_tenant_window_share"] = round(
            self.serve_tenant_window_share, 4)
        out["prepare_suffix_hist"] = dict(self.prepare_suffix_hist)
        # the FULL per-opcode histogram is what absorb() merges across
        # --jobs workers (a top-10 slice silently dropped tail opcodes at
        # every merge and skewed the parent's ranking); the _top view
        # stays alongside as the human-facing shortlist
        out["interp_opcode_wall"] = {
            op: [count, round(seconds, 4)]
            for op, (count, seconds) in self.interp_opcode_wall.items()}
        out["interp_opcode_wall_top"] = self.interp_opcode_wall_top()
        out["device"] = self.device_stats()
        # speed-of-light accounting: per-stage attained vs attainable and
        # the reconciled solver-wall decomposition (observe/roofline.py)
        from mythril_tpu.observe import roofline

        out["roofline"] = roofline.build(self)
        # fault containment: per-site degradation events (every
        # registered site appears, zero-filled, so the section's shape is
        # stable for the check_fault_sites lint and post-hoc diffing) and
        # the armed fault-injection spec, if any (chaos provenance)
        from mythril_tpu.resilience import faults, registry

        sites = {name: dict(self.resilience_events.get(name, {}))
                 for name in registry.FAULT_SITES}
        for site, events in self.resilience_events.items():
            sites.setdefault(site, dict(events))
        out["resilience"] = {
            "sites": sites,
            "faults_active": faults.active_spec(),
        }
        # the fully-resolved knob configuration (value + source tier:
        # env/cli/tuned/default per knob) — every stats artifact says
        # exactly which schedule produced it (mythril_tpu/tune/space.py)
        from mythril_tpu.tune import space as tune_space

        out["knobs"] = tune_space.resolved_config()
        # the resolved device-kernel backend (MYTHRIL_TPU_KERNEL): a
        # string stamp, not a counter — every stats artifact names which
        # kernel produced its device figures (tpu/pallas_kernel.py)
        from mythril_tpu.tpu import pallas_kernel

        out["kernel_backend"] = pallas_kernel.kernel_mode()
        # span-summary of the run's trace ({stage: [count, seconds]};
        # empty unless MYTHRIL_TPU_TRACE / --trace enabled the tracer)
        from mythril_tpu.observe.tracer import Tracer

        tracer = Tracer._instance
        out["trace_spans"] = (
            tracer.summary() if tracer is not None and tracer.enabled
            else {})
        return out

    def absorb(self, snapshot: dict) -> None:
        """Fold a worker process's as_dict() into this (parent) singleton.
        Device-backend stats stay per-process (the backend object never
        crosses the spawn boundary) — only the routing counters aggregate."""
        if not self.enabled or not snapshot:
            return
        for name in self._COUNTERS:
            setattr(self, name, getattr(self, name)
                    + int(snapshot.get(name, 0)))
        for name in self._TIMERS:
            setattr(self, name, getattr(self, name)
                    + float(snapshot.get(name, 0.0)))
        for bucket, count in (snapshot.get("prepare_suffix_hist")
                              or {}).items():
            self.prepare_suffix_hist[bucket] = (
                self.prepare_suffix_hist.get(bucket, 0) + int(count))
        # merge the FULL per-opcode histogram; top-N slicing happens only
        # at emission (interp_opcode_wall_top). Pre-fix snapshots carried
        # only the top slice — accept it as a degraded fallback so mixed
        # worker versions still merge what they reported.
        histogram = (snapshot.get("interp_opcode_wall")
                     or snapshot.get("interp_opcode_wall_top") or {})
        for op, (count, seconds) in histogram.items():
            record = self.interp_opcode_wall.setdefault(op, [0, 0.0])
            record[0] += int(count)
            record[1] += float(seconds)
        # per-site resilience events: a worker's breaker trips /
        # quarantines / requeues must survive the --jobs merge like the
        # scalar counters do (the scalars merged above via _COUNTERS)
        worker_sites = (snapshot.get("resilience") or {}).get("sites") or {}
        for site, events in worker_sites.items():
            per_site = self.resilience_events.setdefault(site, {})
            for event, count in events.items():
                per_site[event] = per_site.get(event, 0) + int(count)

    def __repr__(self):
        out = (f"Solver statistics: query count: {self.query_count}, "
               f"solver time: {self.solver_time:.3f}")
        if self.batch_query_count:
            out += (f", batched queries: {self.batch_query_count}"
                    f", device-eligible: {self.device_batch_queries}"
                    f" (hits: {self.device_batch_hits})"
                    f", device-ineligible: {self.device_ineligible}")
        if self.device_dispatches:
            out += (f", device dispatches: {self.device_dispatches}"
                    f" (occupancy {self.device_occupancy:.2f},"
                    f" {self.route_device_seconds:.2f}s device"
                    f"/{self.route_host_seconds:.2f}s host settle)")
        if self.router_host_direct or self.cap_rejects:
            out += (f", routed host-direct: {self.router_host_direct}"
                    f", cap-rejects: {self.cap_rejects}")
        if self.memory_hits or self.quick_sat_hits or self.persistent_hits \
                or self.persistent_misses:
            out += (f", cache tiers: memory {self.memory_hits}"
                    f"/quick-sat {self.quick_sat_hits}"
                    f"/persistent {self.persistent_hits}"
                    f" (misses {self.persistent_misses},"
                    f" verify-rejects {self.persistent_verify_rejects},"
                    f" stores {self.persistent_stores})")
        if self.window_flushes:
            out += (f", coalesce windows: {self.window_flushes}"
                    f" flushes ({self.coalesced_queries} queries,"
                    f" occupancy {self.coalesce_occupancy:.2f})")
        if self.cdcl_settles:
            out += (f", cdcl settles: {self.cdcl_settles}"
                    f" ({self.cdcl_clauses} clauses,"
                    f" {self.settle_wall:.2f}s wall)")
        if self.modules_gated or self.queries_avoided \
                or self.cnf_units_propagated or self.cnf_pure_literals \
                or self.cnf_components_split:
            out += (f", preanalysis: {self.modules_gated} modules gated"
                    f"/{self.queries_avoided} queries avoided"
                    f"/{self.cnf_units_propagated} units"
                    f"+{self.cnf_pure_literals} pures propagated"
                    f" ({self.cnf_clauses_removed} clauses removed,"
                    f" {self.cnf_components_split} components split)")
        if self.prepare_wall or self.prepare_prefix_resumes \
                or self.prepare_incremental_hits:
            out += (f", prepare: {self.prepare_wall:.2f}s wall"
                    f" ({self.prepare_incremental_hits} simplify hits,"
                    f" {self.prepare_prefix_resumes} prefix resumes"
                    f"/{self.prepare_prefix_fallbacks} fallbacks,"
                    f" {self.prepare_suffix_terms} suffix terms,"
                    f" {self.strash_xquery_merges} cross-query strash)")
        if self.frontier_vmap_steps or self.interp_wall:
            out += (f", frontier: {self.frontier_vmap_steps} vmap steps"
                    f" ({self.frontier_states_stepped} states,"
                    f" {self.frontier_symlane_rows} symlane rows,"
                    f" {self.frontier_fallback_exits} fallback exits,"
                    f" occupancy {self.frontier_batch_occupancy:.2f}),"
                    f" interp {self.interp_wall:.2f}s wall")
        if self.aig_nodes_before:
            out += (f", aig opt: {self.aig_nodes_before}"
                    f"->{self.aig_nodes_after} nodes"
                    f" ({self.aig_strash_merges} strash merges,"
                    f" {self.aig_const_folds} const folds,"
                    f" {self.aig_trivial_unsat} trivially unsat,"
                    f" {self.aig_components} components"
                    f"/{self.aig_device_components} on device)")
        if self.ragged_windows:
            out += (f", ragged: {self.ragged_windows} windows"
                    f" ({self.ragged_cones_packed} cones,"
                    f" {self.paged_stream_bytes} stream bytes,"
                    f" {self.cubes_dispatched} cubes"
                    f"/{self.cube_device_refutes} device refutes)")
        if self.xcontract_windows or self.xcontract_dedup_hits:
            out += (f", cross-contract: {self.xcontract_windows} mixed"
                    f" windows ({self.xcontract_cones_packed} cones,"
                    f" {self.xcontract_dedup_hits} dedup hits)")
        if self.resilience_events:
            out += (f", resilience: {self.resilience_retries} retries"
                    f"/{self.resilience_breaker_trips} breaker trips"
                    f"/{self.resilience_quarantines} quarantines"
                    f"/{self.resilience_degraded} degraded"
                    f"/{self.resilience_deadline_trips} deadline trips"
                    f" ({self.resilience_faults_injected} injected)")
        if self.crosscheck_runs or self.crosscheck_cap_skips:
            out += (f", unsat crosschecks: {self.crosscheck_runs}"
                    f" (+{self.crosscheck_cap_skips} cap-skipped)")
        device = self.device_stats()
        if device:
            out += (f", device pack/ship/solve: {device['pack_seconds']}"
                    f"/{device['ship_seconds']}/{device['solve_seconds']} s"
                    f" (pack cache {device['pack_hits']} hits"
                    f"/{device['pack_misses']} misses,"
                    f" {device['cap_rejects']} cap-rejects)")
        return out

    @staticmethod
    def device_stats() -> dict:
        """Per-stage timing of the device solver (pack/ship/solve), if the
        backend was ever instantiated. Feeds the per-contract stats line and
        bench.py's extra diagnostics."""
        from mythril_tpu.tpu import backend as device_backend

        if device_backend._backend is None:
            return {}
        return device_backend._backend.stats()


# the per-reason breakdown of frontier_fallback_exits and the fork
# pair-packing hit-rate counters, named so tools/check_stats_keys.py can
# pin them end to end (counter -> stats JSON -> bench ROUTING_KEYS)
# independently of the aggregate they roll up into
FALLBACK_REASON_COUNTERS = (
    "frontier_fallback_dialect",
    "frontier_fallback_dynamic",
    "frontier_fallback_hook",
    "frontier_fallback_symbolic",
)
FORK_PAIR_PACK_COUNTERS = (
    "fork_pair_pack_attempts",
    "fork_pair_pack_hits",
)
# the Pallas device-kernel counters, pinned BY NAME the same way (the
# kernel_backend STAMP rides as_dict() as a string key, checked by the
# same lint): renaming one must fail tools/check_stats_keys.py, not
# silently drop the kernel_backend bench leg's evidence
PALLAS_KERNEL_COUNTERS = (
    "pallas_launches",
    "pallas_cells_stepped",
    "kernel_recompiles",
)
# the sharded-fleet counters (fleet/ router + supervisor + the shared
# network result tier), pinned BY NAME like the tuples above: renaming
# or dropping one must fail tools/check_stats_keys.py, not silently
# blind the fleet bench leg and the per-shard /metrics rollup
FLEET_COUNTERS = (
    "fleet_shard_routes",
    "fleet_requeues",
    "fleet_shard_restarts",
    "net_tier_hits",
    "net_tier_stores",
    "net_tier_verify_rejects",
)


def stat_smt_query(func):
    @wraps(func)
    def wrapped(*args, **kwargs):
        stats = SolverStatistics()
        start = time.monotonic()
        try:
            return func(*args, **kwargs)
        finally:
            stats.add_query(time.monotonic() - start)

    return wrapped

"""Query-count/time singleton (reference laser/smt/solver/solver_statistics.py)."""

import time
from functools import wraps


class SolverStatistics:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enabled = False
            cls._instance.query_count = 0
            cls._instance.solver_time = 0.0
            cls._instance.batch_query_count = 0
            cls._instance.device_batch_queries = 0
            cls._instance.device_batch_hits = 0
            cls._instance.device_ineligible = 0
        return cls._instance

    def add_query(self, seconds: float) -> None:
        if self.enabled:
            self.query_count += 1
            self.solver_time += seconds

    def add_batch(self, num_queries: int, seconds: float) -> None:
        """One get_models_batch call covering num_queries sibling queries."""
        if self.enabled:
            self.batch_query_count += num_queries
            self.solver_time += seconds

    def add_device_batch_query(self, hit: bool) -> None:
        """A query that reached the batched device solver (hit = model
        found on device; miss = CDCL settled it)."""
        if self.enabled:
            self.device_batch_queries += 1
            if hit:
                self.device_batch_hits += 1

    def add_device_ineligible(self) -> None:
        """A query that could not take the device path (dense-cap/empty)."""
        if self.enabled:
            self.device_ineligible += 1

    def reset(self) -> None:
        self.query_count = 0
        self.solver_time = 0.0
        self.batch_query_count = 0
        self.device_batch_queries = 0
        self.device_batch_hits = 0
        self.device_ineligible = 0

    def __repr__(self):
        out = (f"Solver statistics: query count: {self.query_count}, "
               f"solver time: {self.solver_time:.3f}")
        if self.batch_query_count:
            out += (f", batched queries: {self.batch_query_count}"
                    f", device-eligible: {self.device_batch_queries}"
                    f" (hits: {self.device_batch_hits})"
                    f", device-ineligible: {self.device_ineligible}")
        device = self.device_stats()
        if device:
            out += (f", device pack/ship/solve: {device['pack_seconds']}"
                    f"/{device['ship_seconds']}/{device['solve_seconds']} s"
                    f" (pack cache {device['pack_hits']} hits"
                    f"/{device['pack_misses']} misses,"
                    f" {device['cap_rejects']} cap-rejects)")
        return out

    @staticmethod
    def device_stats() -> dict:
        """Per-stage timing of the device solver (pack/ship/solve), if the
        backend was ever instantiated. Feeds the per-contract stats line and
        bench.py's extra diagnostics."""
        from mythril_tpu.tpu import backend as device_backend

        if device_backend._backend is None:
            return {}
        return device_backend._backend.stats()


def stat_smt_query(func):
    @wraps(func)
    def wrapped(*args, **kwargs):
        stats = SolverStatistics()
        start = time.monotonic()
        try:
            return func(*args, **kwargs)
        finally:
            stats.add_query(time.monotonic() - start)

    return wrapped

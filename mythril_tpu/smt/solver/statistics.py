"""Query-count/time singleton (reference laser/smt/solver/solver_statistics.py)."""

import time
from functools import wraps


class SolverStatistics:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enabled = False
            cls._instance.query_count = 0
            cls._instance.solver_time = 0.0
        return cls._instance

    def add_query(self, seconds: float) -> None:
        if self.enabled:
            self.query_count += 1
            self.solver_time += seconds

    def reset(self) -> None:
        self.query_count = 0
        self.solver_time = 0.0

    def __repr__(self):
        return (f"Solver statistics: query count: {self.query_count}, "
                f"solver time: {self.solver_time:.3f}")


def stat_smt_query(func):
    @wraps(func)
    def wrapped(*args, **kwargs):
        stats = SolverStatistics()
        start = time.monotonic()
        try:
            return func(*args, **kwargs)
        finally:
            stats.add_query(time.monotonic() - start)

    return wrapped

"""Word-level solver frontend.

check() pipeline:
  1. simplify + trivial verdicts
  2. lower to pure QF_BV:
     - unwind select-over-store chains into ite ladders (read-over-write)
     - ackermannize remaining selects on base arrays (fresh symbol per
       distinct index term + pairwise congruence axioms)
     - ackermannize uninterpreted-function applications the same way
  3. bit-blast to CNF (smt/bitblast.py)
  4. CDCL SAT (solver/sat_backend.py — C++ with Python fallback)
  5. reconstruct a word-level model (incl. array/UF tables) and VALIDATE it
     against the original constraints with the independent evaluator —
     the soundness net replacing the absent z3 oracle.

Optimize implements minimize/maximize by MSB-first bit fixing under
assumptions over the objective's CNF bits (role of z3.Optimize in
reference analysis/solver.py:217-257 exploit minimization).
"""

import logging
import time
from typing import Dict, List, Optional, Tuple

from mythril_tpu import resilience
from mythril_tpu.observe.tracer import NULL_SPAN, span as trace_span
from mythril_tpu.smt import terms
from mythril_tpu.smt.bitblast import Blaster
from mythril_tpu.smt.bitvec import Expression
from mythril_tpu.smt.eval import evaluate
from mythril_tpu.smt.model import Model
from mythril_tpu.smt.solver import sat_backend
from mythril_tpu.smt.solver.statistics import SolverStatistics
from mythril_tpu.smt.terms import BOOL, Term

log = logging.getLogger(__name__)


class UnsatError(Exception):
    pass


class SolverTimeOutException(Exception):
    pass


class SolverInternalError(Exception):
    """A produced model failed validation — a bug in the solver stack."""


SAT, UNSAT, UNKNOWN = sat_backend.SAT, sat_backend.UNSAT, sat_backend.UNKNOWN


def _raw(constraint) -> Term:
    return constraint.raw if isinstance(constraint, Expression) else constraint


def _substitute(roots: List[Term], mapping: Dict[str, Term]) -> List[Term]:
    """Replace sym leaves per `mapping` (name -> term), rebuilding bottom-up
    through the shared smart constructors so folding re-fires."""
    cache: Dict[int, Term] = {}
    for node in terms.walk_terms(roots):
        if node.op == "sym":
            replacement = mapping.get(node.params[0])
            cache[id(node)] = (
                replacement
                if replacement is not None and replacement.sort == node.sort
                else node
            )
            continue
        if not node.children:
            cache[id(node)] = node
            continue
        new_children = [cache[id(c)] for c in node.children]
        if all(a is b for a, b in zip(new_children, node.children)):
            cache[id(node)] = node
        else:
            cache[id(node)] = terms.rebuild(node, new_children)
    return [cache[id(r)] for r in roots]


def _substitute_simplify_fixpoint(term: Term, mapping) -> Term:
    """Substitute `mapping` through `term` and re-simplify until stable.

    A single _substitute pass inserts replacement subtrees VERBATIM, so a
    definition chain (x := y+1 with y := z+1, z := 3 in the same map)
    leaves bound symbols inside the inserted rhs — the residual would
    keep a symbol whose definition was already dropped, and model
    reconstruction would pin it to a value the solver never saw
    (observed: a 3-deep chain left `z` free, the solver chose z freely,
    and validation against the original constraints raised
    SolverInternalError). Bounded by the map size: each pass eliminates
    at least one bound symbol or reaches the fixpoint."""
    for _ in range(len(mapping) + 1):
        new = terms.simplify_expr(_substitute([term], mapping)[0])
        if new is term:
            break
        term = new
    return term


def _extract_binding(term: Term, taken) -> Optional[Tuple[str, Term]]:
    """If `term` asserts sym == rhs (or a bool unit), return the binding."""
    if term.op == "sym" and term.sort == BOOL:
        return term.params[0], terms.TRUE
    if term.op == "not" and term.children[0].op == "sym" \
            and term.children[0].sort == BOOL:
        return term.children[0].params[0], terms.FALSE
    if term.op != "eq":
        return None
    lhs, rhs = term.children
    if not (isinstance(lhs.sort, int) or lhs.sort == BOOL):
        return None  # array equality: not handled here
    # prefer binding to a constant; otherwise either side's symbol
    for sym_side, value_side in ((lhs, rhs), (rhs, lhs)):
        if sym_side.op != "sym" or sym_side.params[0] in taken:
            continue
        name = sym_side.params[0]
        if (name, sym_side.sort) in terms.free_symbols([value_side]):
            continue  # occurs check: x == f(x) is not a definition
        return name, value_side
    return None


def propagate_equalities(
    asserted: List[Term], max_rounds: int = 8
) -> Tuple[List[Term], List[Tuple[str, Term]], bool]:
    """Equality/constant propagation over the assertion set (pre-blast).

    Asserted `sym == rhs` definitions are substituted through every other
    constraint and dropped; repeated to fixpoint. EVM path constraints pin
    many symbols (selector bytes, caller, callvalue), and substituting them
    collapses ite ladders and whole arithmetic cones before the expensive
    bit-blast — the word-level preprocessing role z3 plays for the
    reference. Returns (residual constraints, substitutions in insertion
    order, trivially_unsat). Model reconstruction re-derives substituted
    symbols by evaluating their definitions in reverse insertion order."""
    substitutions: List[Tuple[str, Term]] = []
    taken = set()
    work = list(asserted)
    for _ in range(max_rounds):
        found: Dict[str, Term] = {}
        remaining: List[Term] = []
        for term in work:
            if found:
                # apply this round's earlier bindings (to fixpoint — the
                # map's values may chain) before inspecting, so
                # `x == 5; y == x + 1` resolves in one round and a
                # recorded rhs never references a same-round EARLIER
                # binding (the reverse-resolution order depends on it)
                term = _substitute_simplify_fixpoint(term, found)
            if term.is_const:
                if term.value is False:
                    return [], substitutions, True
                continue
            binding = _extract_binding(term, taken)
            if binding is not None:
                name, rhs = binding
                taken.add(name)
                found[name] = rhs
                substitutions.append((name, rhs))
                continue
            remaining.append(term)
        if not found:
            return remaining, substitutions, False
        work = []
        for term in remaining:
            term = _substitute_simplify_fixpoint(term, found)
            if term.is_const:
                if term.value is False:
                    return [], substitutions, True
                continue
            work.append(term)
    return work, substitutions, False


def narrow_bounded_symbols(
    asserted: List[Term], taken: set
) -> Tuple[List[Term], List[Tuple[str, Term]]]:
    """Bounds-driven symbol narrowing (pre-blast word-level rewrite).

    An asserted constant upper bound `x < c` / `x <= c` proves x's high
    bits are zero; substituting `x := zext(fresh_k)` (k = the bound's bit
    width) makes those zeros STRUCTURAL, so downstream multiplier partial
    products, comparison borrow chains, and adder carries over x collapse
    in the AIG instead of burdening the CDCL. Always sound: the bound
    constraint itself is kept (now a cheap comparison over mostly-constant
    bits), so no models are lost and none are added — any model must
    satisfy the bound anyway. The substitutions flow through
    the standard reconstruction machinery (the fresh symbol's "!" prefix
    keeps it out of visible models). Returns (residual terms, new
    substitutions); residual None means a constraint folded to false under
    the restriction — since the restriction loses no models, that proves
    the original set unsat."""
    bounds: Dict[str, int] = {}  # name -> tightest narrowed width
    widths: Dict[str, int] = {}
    for term in asserted:
        if term.op not in ("bvult", "bvule"):
            continue
        lhs, rhs = term.children
        if lhs.op != "sym" or not isinstance(lhs.sort, int):
            continue
        if not (rhs.is_const and isinstance(rhs.value, int)):
            continue
        bound = rhs.value - 1 if term.op == "bvult" else rhs.value
        if bound < 0:
            continue  # x < 0: unsat; leave it to the solver
        narrow = max(1, bound.bit_length())
        name = lhs.params[0]
        if name in taken or narrow >= lhs.sort:
            continue
        widths[name] = lhs.sort
        bounds[name] = min(bounds.get(name, narrow), narrow)
    if not bounds:
        return asserted, []
    substitutions: List[Tuple[str, Term]] = []
    mapping: Dict[str, Term] = {}
    for name, narrow in bounds.items():
        width = widths[name]
        fresh = terms.bv_sym(f"!narrow!{name}", narrow)
        definition = terms.zext(width - narrow, fresh)
        mapping[name] = definition
        substitutions.append((name, definition))
        taken.add(name)
    narrowed: List[Term] = []
    for term in _substitute(asserted, mapping):
        term = terms.simplify_expr(term)
        if term.is_const:
            if term.value is False:
                # false under the (sound) restriction => unsat overall
                return None, substitutions
            continue
        narrowed.append(term)
    return narrowed, substitutions


class _Lowering:
    """Rewrites a set of bool terms into pure QF_BV + side constraints."""

    def __init__(self):
        self.cache: Dict[int, Term] = {}
        self.side_constraints: List[Term] = []
        # (array_name) -> list of (index_term, fresh_sym_term)
        self.array_reads: Dict[str, List[Tuple[Term, Term]]] = {}
        # func name -> list of (args_tuple, fresh_sym_term)
        self.func_apps: Dict[str, List[Tuple[Tuple[Term, ...], Term]]] = {}
        self._fresh = 0

    def fresh(self, size: int, tag: str) -> Term:
        self._fresh += 1
        return terms.bv_sym(f"!{tag}!{self._fresh}", size)

    def lower(self, term: Term) -> Term:
        hit = self.cache.get(id(term))
        if hit is not None:
            return hit
        result = self._lower_node(term)
        self.cache[id(term)] = result
        return result

    def drain_side_constraints(self) -> List[Term]:
        out = self.side_constraints
        self.side_constraints = []
        return out

    def clone(self) -> "_Lowering":
        """Independent copy for the incremental prefix memo: a snapshot
        must survive this query's drain/extend, and a resumed child must
        not mutate the shared snapshot. Side constraints are copied
        UNDRAINED so a resume appends the suffix's constraints to the
        prefix's and the final drain reproduces the full pipeline's root
        order exactly."""
        twin = _Lowering.__new__(_Lowering)
        twin.cache = dict(self.cache)
        twin.side_constraints = list(self.side_constraints)
        twin.array_reads = {k: list(v) for k, v in self.array_reads.items()}
        twin.func_apps = {k: list(v) for k, v in self.func_apps.items()}
        twin._fresh = self._fresh
        return twin

    def _lower_node(self, term: Term) -> Term:
        op = term.op
        if op == "select":
            return self._lower_select(term.children[0], self.lower_index(term.children[1]))
        if op == "apply":
            decl = term.params[0]
            args = tuple(self.lower(a) for a in term.children)
            return self._ackermann_apply(decl, args)
        if op == "eq" and not isinstance(term.children[0].sort, int) \
                and term.children[0].sort != BOOL:
            raise NotImplementedError("array extensionality is not supported")
        if not term.children:
            return term
        new_children = [self.lower(c) for c in term.children]
        if all(a is b for a, b in zip(new_children, term.children)):
            return term
        return terms.rebuild(term, new_children)

    def lower_index(self, index: Term) -> Term:
        return self.lower(index)

    def _lower_select(self, arr: Term, index: Term) -> Term:
        """Unwind store/ite chains; terminate at base array / karray."""
        if arr.op == "store":
            base, widx, wval = arr.children
            widx_l = self.lower(widx)
            wval_l = self.lower(wval)
            hit = terms.eq(index, widx_l)
            if hit.is_const:
                if hit.value:
                    return wval_l
                return self._lower_select(base, index)
            return terms.ite(hit, wval_l, self._lower_select(base, index))
        if arr.op == "karray":
            return self.lower(arr.children[0])
        if arr.op == "ite":
            cond = self.lower(arr.children[0])
            then = self._lower_select(arr.children[1], index)
            otherwise = self._lower_select(arr.children[2], index)
            return terms.ite(cond, then, otherwise)
        if arr.op == "array":
            return self._ackermann_select(arr, index)
        raise NotImplementedError(f"select over {arr.op}")

    def _ackermann_select(self, arr: Term, index: Term) -> Term:
        name = arr.params[0]
        rng = arr.sort[2]
        reads = self.array_reads.setdefault(name, [])
        for prev_index, prev_sym in reads:
            if prev_index == index:
                return prev_sym
        sym = self.fresh(rng, f"sel!{name}")
        # congruence with all previous reads of the same array
        for prev_index, prev_sym in reads:
            self.side_constraints.append(
                terms.bool_or([
                    terms.bool_not(terms.eq(index, prev_index)),
                    terms.eq(sym, prev_sym),
                ])
            )
        reads.append((index, sym))
        return sym

    def _ackermann_apply(self, decl: terms.FuncDecl, args: Tuple[Term, ...]) -> Term:
        apps = self.func_apps.setdefault(decl.name, [])
        for prev_args, prev_sym in apps:
            if prev_args == args:
                return prev_sym
        sym = self.fresh(decl.range, f"app!{decl.name}")
        for prev_args, prev_sym in apps:
            same_args = terms.bool_and(
                [terms.eq(a, b) for a, b in zip(args, prev_args)]
            )
            self.side_constraints.append(
                terms.bool_or([terms.bool_not(same_args), terms.eq(sym, prev_sym)])
            )
        apps.append((args, sym))
        return sym


class _Prepared:
    """Lowered + blasted problem state shared across assumption probes."""

    __slots__ = ("trivial", "original", "lowering", "blaster",
                 "num_vars", "clauses", "objective_bits", "last_bits",
                 "substitutions", "aig_roots", "symbols", "var_dense",
                 "session")

    def __init__(self):
        self.trivial: Optional[str] = None
        self.original: List[Term] = []
        self.lowering: Optional[_Lowering] = None
        self.blaster: Optional[Blaster] = None
        self.num_vars = 0
        self.clauses: List = []
        self.objective_bits: List[List[int]] = []
        self.last_bits: Optional[List[bool]] = None
        # (name, definition) pairs eliminated by propagate_equalities
        self.substitutions: List[Tuple[str, Term]] = []
        # (aig, root literals) snapshot for THIS problem — with the shared
        # global blaster, blaster.last_roots belongs to whoever blasted last
        self.aig_roots: Optional[Tuple] = None
        # free symbols of THIS problem's lowered terms: the shared blaster's
        # symbol tables span every problem ever blasted, so reconstruction
        # must filter to these (same-named symbols from other problems would
        # otherwise leak into — and corrupt — the model)
        self.symbols: Optional[set] = None
        # global AIG var -> dense CNF var (the cone's compact numbering)
        self.var_dense: dict = {}
        # lazily-created per-query native solver session (sat_backend);
        # holds the loaded instance across assumption probes
        self.session = None


_global_blaster: Optional[Blaster] = None
_global_blaster_generation = -1
BLASTER_VAR_CAP = 20_000_000  # reset past this to bound memory


def get_global_blaster() -> Blaster:
    """Process-wide blaster: terms are hash-consed (smt/terms.py), so its
    id-keyed memo + structurally-hashed AIG persist across solver calls —
    repeated confirmation queries share their blasted cones instead of
    rebuilding them. Resets when the term intern table is cleared (the memo
    keys would dangle) or when the AIG outgrows the var cap."""
    global _global_blaster, _global_blaster_generation
    if (
        _global_blaster is None
        or _global_blaster_generation != terms.Term.generation
        or _global_blaster.aig.num_vars > BLASTER_VAR_CAP
    ):
        _global_blaster = Blaster()
        _global_blaster_generation = terms.Term.generation
    return _global_blaster


class Solver:
    """Check a conjunction of Bool constraints; extract word-level models."""

    def __init__(self, timeout: Optional[float] = None):
        self.timeout = timeout  # seconds
        self.constraints: List[Term] = []
        self._model: Optional[Model] = None
        self.conflict_budget = 0
        # False = plain CDCL only (the batched device path sets this for
        # leftover settling so solve_cnf doesn't re-enter the device)
        self.allow_device = True
        # True = UNSAT verdicts are re-solved on a permuted instance
        # (support/model.py sets this inside detection contexts)
        self.unsat_crosscheck = False

    def set_timeout(self, timeout_ms: int) -> None:
        self.timeout = timeout_ms / 1000.0

    def add(self, *constraints) -> None:
        for c in constraints:
            if isinstance(c, (list, tuple)):
                self.add(*c)
            else:
                self.constraints.append(_raw(c))

    append = add

    def check(self, *extra) -> str:
        stats = SolverStatistics()
        start = time.monotonic()
        try:
            return self._check([_raw(e) for e in extra])
        finally:
            stats.add_query(time.monotonic() - start)

    def _prepare(self, extra: List[Term],
                 objectives: List[Term] = ()) -> "_Prepared":
        """Simplify, lower, and blast the assertion set (+ objective bits).
        Timed into prepare_wall — the prepare component of the solver-wall
        split (host settle and device dispatch are timed at their seams) —
        and traced as the solver.prepare stage (the span's `mode` attr
        distinguishes prefix resume from full pipeline from trivial)."""
        start = time.monotonic()
        with trace_span("solver.prepare", cat="solver",
                        constraints=len(self.constraints) + len(extra)) as sp:
            try:
                return self._prepare_impl(extra, objectives, sp)
            finally:
                SolverStatistics().add_prepare_seconds(
                    time.monotonic() - start)

    def _prepare_impl(self, extra: List[Term],
                      objectives: List[Term] = (),
                      sp=NULL_SPAN) -> "_Prepared":
        from mythril_tpu.smt.solver import incremental

        prep = _Prepared()
        # incremental cross-query preparation (smt/solver/incremental.py):
        # memoized simplify + prefix-snapshot resume. Withheld under
        # Optimize objectives — objectives interleave with the lowering
        # state and the memo would have to snapshot them too for no
        # production traffic (the engine's sibling fan-out never minimizes).
        # the incremental layer is a registered disable-action fault site
        # (resilience/registry.py prepare.incremental): a fault inside it
        # degrades THIS query to the full pipeline, and repeated faults
        # blow the session fuse so the layer stays off
        use_incr = (not objectives and incremental.enabled()
                    and not resilience.fuse_blown("prepare.incremental"))
        simplify = (incremental.simplify_cached if use_incr
                    else terms.simplify_expr)
        asserted: List[Term] = []
        for term in self.constraints + extra:
            term = simplify(term)
            if term.is_const:
                if term.value is False:
                    prep.trivial = UNSAT
                    return prep
                continue
            asserted.append(term)
        prep.original = asserted

        resume = None
        if use_incr:
            try:
                resilience.maybe_inject("prepare.incremental")
                resume = incremental.try_resume(asserted)
            except Exception:
                log.warning("incremental prefix resume failed; full "
                            "prepare pipeline for this query",
                            exc_info=True)
                resilience.note_stage_failure("prepare.incremental")
                use_incr = False
                resume = None
        if resume is not None and resume.unsat:
            prep.trivial = UNSAT
            return prep
        sp.set(mode="prefix_resume" if resume is not None else "full")
        if resume is not None:
            # path constraints grow monotonically: this query's list is a
            # memoized sibling's plus a suffix — the prefix's substitution
            # map, lowering state and lowered terms are resumed and only
            # the suffix runs the word-level pipeline below
            asserted_residual = resume.suffix_residual
            residual_full = resume.residual
            prep.substitutions = resume.substitutions
            taken_equal = resume.taken_equal
            taken_narrow = resume.taken_narrow
            lowering = resume.lowering
            lowered_prefix = resume.lowered_prefix
        else:
            # pre-blast word-level preprocessing: substitute asserted
            # definitions (sym == rhs) through the set before any lowering
            asserted_residual, prep.substitutions, unsat = \
                propagate_equalities(asserted)
            if unsat:
                prep.trivial = UNSAT
                return prep
            taken_equal = {name for name, _ in prep.substitutions}
            # then narrow constant-bounded symbols so their high bits become
            # structural zeros (collapses multiplier/comparison cones)
            taken = set(taken_equal)
            asserted_residual, narrow_subs = narrow_bounded_symbols(
                asserted_residual, taken
            )
            prep.substitutions = prep.substitutions + narrow_subs
            taken_narrow = {name for name, _ in narrow_subs}
            if asserted_residual is None:
                prep.trivial = UNSAT
                return prep
            residual_full = asserted_residual
            # objectives must see the same substitution; iterate because
            # later bindings may appear inside earlier definitions
            if objectives and prep.substitutions:
                mapping = dict(prep.substitutions)
                objectives = list(objectives)
                for _ in range(len(prep.substitutions)):
                    new_objectives = [
                        terms.simplify_expr(t)
                        for t in _substitute(objectives, mapping)
                    ]
                    if all(a is b
                           for a, b in zip(new_objectives, objectives)):
                        break
                    objectives = new_objectives
            lowering = _Lowering()
            lowered_prefix = []

        try:
            lowered = lowered_prefix + [
                lowering.lower(t) for t in asserted_residual]
            lowered_objectives = [lowering.lower(o) for o in objectives]
        except NotImplementedError:
            prep.trivial = UNKNOWN
            return prep
        if use_incr:
            # snapshot BEFORE draining side constraints so a resumed child
            # reproduces the full pipeline's root ordering
            incremental.record(asserted, residual_full, prep.substitutions,
                               taken_equal, taken_narrow, lowering, lowered)
        lowered = lowered + lowering.drain_side_constraints()
        lowered = [simplify(t) for t in lowered]
        if any(t.is_const and t.value is False for t in lowered):
            prep.trivial = UNSAT
            return prep
        lowered = [t for t in lowered if not t.is_const]
        if not lowered and not objectives:
            prep.trivial = SAT
            return prep

        prep.lowering = lowering
        prep.blaster = get_global_blaster()
        objective_lits: List[int] = []
        prep.objective_bits = []
        for lowered_obj in lowered_objectives:
            bits = prep.blaster.bv_bits(lowered_obj)
            prep.objective_bits.append(bits)
            objective_lits.extend(bits)
        # AIG structural analysis & rewriting (preanalysis/aig_opt.py):
        # the blasted cone is swept (root-forced constants propagated,
        # dead fanout pruned, trivially-UNSAT roots detected — the
        # verdict still settles through the CDCL so the detection-path
        # crosscheck policy survives) and re-strashed BEFORE the CNF is
        # emitted, so the fingerprint, the router's PackedCircuit, and
        # the host CDCL all consume the smaller rewritten instance.
        # Withheld under Optimize objectives: bit probes assume over
        # objective-bit literals of the ORIGINAL shared AIG, and the
        # rewrite could fold those gates away. prep.var_dense stays in
        # ORIGINAL global numbering (composed through the rewrite's
        # input map) so _reconstruct — which validates every model
        # against the original constraints — works unchanged, while
        # prep.aig_roots carries the rewritten (aig, roots, dense) the
        # device path and fingerprint consume.
        aig_opted = False
        if not objectives and not resilience.fuse_blown("aig.session"):
            from mythril_tpu.preanalysis import aig_opt

            if aig_opt.enabled():
                # registered disable-action fault site (aig.session): a
                # fault anywhere in the rewrite degrades THIS query to the
                # un-rewritten blaster CNF below — assert_bool/cnf are
                # memoized, so the fallback re-lowering is free and lands
                # on identical roots — and repeated faults blow the
                # session fuse
                try:
                    resilience.maybe_inject("aig.session")
                    roots = [prep.blaster.assert_bool(t) for t in lowered]
                    prep.blaster.last_roots = roots
                    with trace_span("solver.aig_opt", cat="solver",
                                    roots=len(roots)):
                        opt = aig_opt.optimize_roots_cached(
                            prep.blaster.aig, roots)
                    if opt is not None:
                        prep.num_vars, prep.clauses, opt_dense = \
                            opt.aig.to_cnf(list(opt.roots))
                        prep.aig_roots = (opt.aig, list(opt.roots),
                                          opt_dense)
                        prep.var_dense = aig_opt.ComposedDense(
                            opt.input_map, opt_dense)
                        stats = SolverStatistics()
                        stats.add_aig_opt(
                            opt.nodes_before, opt.nodes_after,
                            opt.strash_merges, opt.const_folds,
                            trivial_unsat=opt.trivially_unsat)
                        # gates reused from SIBLING queries via the
                        # session strash table (cross-query sharing)
                        stats.add_strash_xquery(opt.xquery_merges)
                        from mythril_tpu.preanalysis import aig_partition

                        partition = aig_partition.partition_cached(
                            opt.aig, opt.roots)
                        if partition is not None:
                            stats.add_aig_components(
                                len(partition.components))
                        aig_opted = True
                except Exception:
                    log.warning("AIG session optimization failed; "
                                "un-rewritten CNF for this query",
                                exc_info=True)
                    resilience.note_stage_failure("aig.session")
                    aig_opted = False
        if not aig_opted:
            prep.num_vars, prep.clauses, prep.var_dense = prep.blaster.cnf(
                lowered, objective_lits)
            prep.aig_roots = (prep.blaster.aig,
                              list(prep.blaster.last_roots),
                              prep.var_dense)
        if use_incr:
            # per-root memoized scan: sibling queries share most of their
            # constraint roots, and the full free_symbols walk re-visits
            # the whole DAG per query otherwise
            prep.symbols = set(incremental.free_symbols_cached(
                list(lowered) + list(lowered_objectives)))
        else:
            prep.symbols = {
                (name, sort)
                for (name, sort) in terms.free_symbols(
                    list(lowered) + list(lowered_objectives))
            }
        # static CNF preprocessing (preanalysis/cnf_prep.py): unit
        # propagation + pure literals over the blasted instance BEFORE the
        # disk-tier fingerprint and router dispatch see it — variable
        # numbering is preserved, so dense maps, sessions, stored-bit
        # replay, and reconstruction are untouched. The pure-literal rule
        # is withheld when objectives exist: Optimize probes the instance
        # under assumptions later, and pinning a bit the original CNF
        # leaves free would flip those probes' verdicts (mis-minimizing
        # exploits). A propagation-derived CONFLICT deliberately does NOT
        # short-circuit: the detection path's UNSAT verdicts carry a
        # permuted-instance second opinion (sat_backend._crosscheck_unsat),
        # and a preprocessor-trusted UNSAT would silently bypass that
        # soundness net — the original clauses go to the CDCL, which
        # re-derives the conflict by native propagation in microseconds
        # and applies the standard crosscheck policy.
        from mythril_tpu import preanalysis

        if preanalysis.enabled():
            from mythril_tpu.preanalysis.cnf_prep import preprocess_cnf
            from mythril_tpu.support.args import args as _args

            # the pure rule is also withheld when this instance may ride
            # the device path: the circuit kernel searches the ORIGINAL
            # AIG's model space, and a model putting a pure-pinned
            # variable at the opposite polarity would fail the clause
            # check against the pinned CNF — a wasted device hit. An
            # AIG-rewritten instance is ALWAYS treated as device-possible
            # here: its (aig, roots, dense) triple is a self-contained
            # dispatchable artifact (harvest/dryrun paths re-solve it on
            # device regardless of the configured backend), and the sweep
            # routinely leaves single-polarity literals the pure rule
            # would otherwise pin against the kernel's model space.
            device_possible = (
                (_args.solver_backend == "tpu" and self.allow_device)
                or aig_opted)
            with trace_span("solver.cnf_prep", cat="solver",
                            clauses=len(prep.clauses)):
                simplified = preprocess_cnf(
                    prep.num_vars, prep.clauses,
                    allow_pure=not objectives and not device_possible)
            if simplified is not None and simplified.changed \
                    and not simplified.conflict:
                SolverStatistics().add_cnf_preprocess(
                    simplified.units, simplified.pures,
                    simplified.removed_clauses)
                prep.clauses = simplified.cnf
        return prep

    def _solve_prepared(self, prep: "_Prepared",
                        assumptions: List[int] = ()) -> str:
        aig_roots = prep.aig_roots if not assumptions else None
        # connected-component splitting (preanalysis/cnf_prep.py): when
        # this solve is host-CDCL-bound anyway, variable-disjoint
        # sub-instances settle independently (first UNSAT component ends
        # it; SAT components' models recompose through _reconstruct).
        # Assumption probes reuse the monolithic session instead — their
        # literals may bridge components across probes.
        if not assumptions and prep.session is None:
            split_status = self._try_solve_split(prep)
            if split_status is not None:
                return split_status
        # per-query session: the instance loads into a persistent native
        # solver on first use; every later probe (Optimize bit fixing,
        # re-solves) reuses it under assumptions with learnt clauses intact
        if prep.session is None and prep.blaster is not None:
            prep.session = sat_backend.create_prep_session(
                prep.num_vars, prep.clauses)
        status, bits = sat_backend.solve_cnf(
            prep.num_vars,
            prep.clauses,
            assumptions=assumptions,
            timeout_seconds=self.timeout or 0.0,
            conflict_budget=self.conflict_budget,
            allow_device=self.allow_device,
            aig_roots=aig_roots,
            # assumption probes (Optimize bit fixing) are exempt: their
            # UNSATs only shape exploit cosmetics, not issue presence, and
            # most probes ARE unsat — crosschecking them would multiply
            # minimization cost for no soundness gain
            crosscheck=self.unsat_crosscheck and not assumptions,
            session_ctx=prep.session,
        )
        if status == SAT:
            prep.last_bits = bits
            self._model = self._reconstruct(prep, bits)
        return status

    def _try_solve_split(self, prep: "_Prepared") -> Optional[str]:
        """Settle a multi-component instance component-by-component on the
        host CDCL; None when splitting does not apply (single component,
        oversize, preanalysis off, or a device dispatch is still possible
        for the whole cone — the circuit kernel needs the full AIG)."""
        from mythril_tpu import preanalysis
        from mythril_tpu.support.args import args as _args

        if not preanalysis.enabled():
            return None
        if (_args.solver_backend == "tpu" and self.allow_device
                and prep.aig_roots is not None):
            return None
        from mythril_tpu.preanalysis.cnf_prep import (
            merge_component_bits,
            split_components,
        )

        components = split_components(prep.num_vars, prep.clauses)
        if components is None:
            return None
        SolverStatistics().add_cnf_split(len(components))
        deadline = (time.monotonic() + self.timeout) if self.timeout else None
        bits_list = []
        for component in components:
            if component.trivial_bits is not None:
                # all-unit consistent component: its model is its literals
                # (no solver round-trip, no cdcl_settle counted)
                bits_list.append(component.trivial_bits)
                continue
            remaining = 0.0
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return UNKNOWN
            status, bits = sat_backend.solve_cnf(
                component.num_vars,
                component.cnf,
                timeout_seconds=max(remaining, 0.0),
                conflict_budget=self.conflict_budget,
                allow_device=False,
                # an UNSAT component proves the whole instance UNSAT: it
                # carries the detection-path second opinion (and its
                # crosscheck-confirmed flag feeds persistence provenance)
                crosscheck=self.unsat_crosscheck,
            )
            if status == UNSAT:
                return UNSAT
            if status != SAT:
                return UNKNOWN
            bits_list.append(bits)
        merged = merge_component_bits(prep.num_vars, components, bits_list)
        prep.last_bits = merged
        self._model = self._reconstruct(prep, merged)
        return SAT

    def _check(self, extra: List[Term]) -> str:
        self._model = None
        prep = self._prepare(extra)
        if prep.trivial is not None:
            if prep.trivial == SAT:
                self._model = self._trivial_model(prep)
            return prep.trivial
        self.last_prep = prep  # query-capture hook (support/model.py)
        return self._solve_prepared(prep)

    @staticmethod
    def _resolve_substitutions(assignment: Dict, prep: "_Prepared") -> None:
        """Re-derive symbols eliminated by propagate_equalities.

        Reverse insertion order works because each definition was fully
        substituted w.r.t. earlier bindings when recorded — it can only
        reference later-bound or never-bound symbols."""
        for name, definition in reversed(prep.substitutions):
            assignment[name] = evaluate(definition, assignment)

    def _trivial_model(self, prep: "_Prepared") -> Model:
        """All constraints eliminated by preprocessing: the model is just
        the substituted definitions (empty only when none were made)."""
        assignment: Dict = {}
        self._resolve_substitutions(assignment, prep)
        model = Model(assignment)
        for term in prep.original:
            if evaluate(term, model.assignment) is not True:
                raise SolverInternalError(
                    f"model validation failed on {terms.term_to_str(term)}"
                )
        return model

    def _reconstruct(self, prep: "_Prepared", bits: List[bool]) -> Model:
        blaster, lowering = prep.blaster, prep.lowering
        assignment: Dict = {}
        # the blaster is shared across problems: symbols allocated AFTER
        # this prep's CNF snapshot have vars past len(bits) — they are not
        # part of this problem and default to 0 via model completion
        dense = prep.var_dense
        # iterate THIS problem's symbols (prep.symbols), not the shared
        # blaster's tables, which accumulate every symbol ever blasted
        for name, sort in prep.symbols or ():
            if sort == terms.BOOL:
                var = blaster.bool_symbol_vars.get(name)
                if var is None:
                    continue
                dvar = dense.get(var)
                assignment[name] = bits[dvar] if dvar is not None else False
            elif isinstance(sort, int):
                var_list = blaster.bv_symbol_vars.get((name, sort))
                if var_list is None:
                    continue
                value = 0
                for i, var in enumerate(var_list):
                    dvar = dense.get(var)
                    # bits outside the cone are unconstrained -> 0
                    if dvar is not None and bits[dvar]:
                        value |= 1 << i
                assignment[name] = value
        # rebuild array tables from the ackermannized reads
        for arr_name, reads in lowering.array_reads.items():
            entries = {}
            for index_term, sym_term in reads:
                index_value = evaluate(index_term, assignment)
                entries[index_value] = assignment.get(sym_term.params[0], 0)
            assignment[arr_name] = (0, entries)
        # rebuild UF tables
        for func_name, apps in lowering.func_apps.items():
            table = {}
            for args_terms, sym_term in apps:
                key = tuple(evaluate(a, assignment) for a in args_terms)
                table[key] = assignment.get(sym_term.params[0], 0)
            assignment[func_name] = (0, table)
        # symbols eliminated pre-blast come back via their definitions.
        # AFTER the array/UF tables: a definition like x == storage[0]
        # needs the rebuilt table, while the recorded array-read index
        # terms were lowered post-substitution and so never reference an
        # eliminated symbol — this order has no cycle.
        self._resolve_substitutions(assignment, prep)
        # drop internal fresh symbols from the visible model
        visible = {k: v for k, v in assignment.items()
                   if not (isinstance(k, str) and k.startswith("!"))}
        model = Model(visible)
        # soundness net: the model must satisfy the ORIGINAL constraints
        # (one shared node cache — sibling constraints share their cone)
        from mythril_tpu.smt.eval import evaluate_shared

        values: Dict = {}
        for term in prep.original:
            if evaluate_shared(term, model.assignment, values) is not True:
                raise SolverInternalError(
                    f"model validation failed on {terms.term_to_str(term)}"
                )
        return model

    def model(self) -> Model:
        if self._model is None:
            raise ValueError("no model available (last check not sat)")
        return self._model


class Optimize(Solver):
    """Lexicographic minimize/maximize via MSB-first bit fixing.

    The problem is lowered and blasted ONCE; each bit probe is a SAT call
    under assumptions on the shared CNF (no re-lowering/re-blasting).

    Past OPTIMIZE_CLAUSE_CAP clauses, per-bit probing switches to GROUPED
    prefix fixing (round-4 verdict item 8 — the old behavior skipped
    minimization entirely there, leaving unminimized exploit blobs on
    exactly the heaviest contracts): the longest MSB prefix of the
    objective is pinned to the preferred value in ONE conflict-budgeted
    solve, halving the span on failure — ~log(bits) probes instead of one
    per bit, each time-boxed, so calldatasize/callvalue still collapse to
    small values on ~1M-clause confirmation queries. The reference always
    minimizes (analysis/solver.py:217-257)."""

    OPTIMIZE_CLAUSE_CAP = 200_000
    BIG_PROBE_CONFLICTS = 50_000   # per grouped probe on heavy instances
    BIG_PROBE_DEADLINE_S = 10.0    # total minimization box past the cap

    def __init__(self, timeout: Optional[float] = None):
        super().__init__(timeout)
        self._objectives: List[Tuple[str, Term]] = []

    def minimize(self, expression) -> None:
        self._objectives.append(("min", _raw(expression)))

    def maximize(self, expression) -> None:
        self._objectives.append(("max", _raw(expression)))

    def _check(self, extra: List[Term]) -> str:
        if not self._objectives:
            return super()._check(extra)
        self._model = None
        prep = self._prepare(extra, [obj for _, obj in self._objectives])
        if prep.trivial is not None:
            if prep.trivial == SAT:
                self._model = self._trivial_model(prep)
            return prep.trivial
        self.last_prep = prep  # query-capture hook (support/model.py)
        status = self._solve_prepared(prep)
        if status != SAT:
            return status
        big = len(prep.clauses) > self.OPTIMIZE_CLAUSE_CAP
        box = (
            min(self.timeout or self.BIG_PROBE_DEADLINE_S,
                self.BIG_PROBE_DEADLINE_S)
            if big else (self.timeout or 10.0)
        )
        deadline = time.monotonic() + box
        probe = self._optimize_one_grouped if big else self._optimize_one
        assumptions: List[int] = []  # DIMACS lits, grown lexicographically
        for (direction, _), bit_lits in zip(self._objectives, prep.objective_bits):
            if time.monotonic() > deadline:
                break
            probe(direction, bit_lits, prep, assumptions, deadline)
        return SAT

    def _optimize_one(self, direction: str, bit_lits: List[int],
                      prep: "_Prepared", assumptions: List[int],
                      deadline: float) -> None:
        """Fix objective bits MSB-first, appending to `assumptions` in place.

        `bit_lits` are AIG literals (LSB-first); constant bits are skipped,
        the rest are probed as SAT assumptions over the shared CNF. The best
        model found is kept in self._model."""
        prefer_negative = direction == "min"
        dense = prep.var_dense
        for aig_lit in reversed(bit_lits):  # MSB first
            if time.monotonic() > deadline:
                return
            var = dense.get(aig_lit >> 1)
            if not var:
                continue  # constant bit (or outside the cone): undecidable
            dimacs = -var if aig_lit & 1 else var
            trial = -dimacs if prefer_negative else dimacs
            # witnessed-bit skip: if the current model already has this bit at
            # the preferred value, it witnesses SAT of (assumptions + trial) —
            # adopt the assumption without a solver call
            if prep.last_bits is not None:
                bit_value = prep.last_bits[var] ^ bool(aig_lit & 1)
                if bit_value == (not prefer_negative):
                    assumptions.append(trial)
                    continue
            saved = self.timeout
            self.timeout = max(0.25, deadline - time.monotonic())
            try:
                status = self._solve_prepared_keep_model(
                    prep, assumptions + [trial])
            finally:
                self.timeout = saved
            if status == SAT:
                assumptions.append(trial)
            elif status == UNSAT:
                assumptions.append(-trial)
            else:
                return

    def _optimize_one_grouped(self, direction: str, bit_lits: List[int],
                              prep: "_Prepared", assumptions: List[int],
                              deadline: float) -> None:
        """Heavy-instance variant: pin the longest MSB prefix per solve.

        Bits the current model already has at the preferred value are
        adopted free; past the first wrong bit, a whole remaining-suffix
        group is tried as one conflict-budgeted assumption solve, halving
        the span on UNSAT/UNKNOWN. A span-1 UNSAT fixes the bit at its
        non-preferred value (sound: budget overruns report UNKNOWN, never
        UNSAT) and the walk continues."""
        prefer_negative = direction == "min"
        dense = prep.var_dense
        trials: List[Tuple[int, int, int]] = []  # (trial lit, var, aig lit)
        for aig_lit in reversed(bit_lits):  # MSB first
            var = dense.get(aig_lit >> 1)
            if not var:
                continue  # constant bit (or outside the cone): undecidable
            dimacs = -var if aig_lit & 1 else var
            trials.append((-dimacs if prefer_negative else dimacs, var, aig_lit))
        total = len(trials)
        index = 0
        saved_timeout, saved_budget = self.timeout, self.conflict_budget
        self.conflict_budget = self.BIG_PROBE_CONFLICTS
        try:
            while index < total and time.monotonic() < deadline:
                trial, var, aig_lit = trials[index]
                if prep.last_bits is not None:
                    bit_value = prep.last_bits[var] ^ bool(aig_lit & 1)
                    if bit_value == (not prefer_negative):
                        assumptions.append(trial)
                        index += 1
                        continue
                span = total - index
                advanced = False
                while span >= 1 and time.monotonic() < deadline:
                    group = [t for t, _, _ in trials[index:index + span]]
                    self.timeout = max(
                        0.25, min(5.0, deadline - time.monotonic()))
                    status = self._solve_prepared_keep_model(
                        prep, assumptions + group)
                    if status == SAT:
                        assumptions.extend(group)
                        index += span
                        advanced = True
                        break
                    if span == 1:
                        if status == UNSAT:
                            assumptions.append(-group[0])
                            index += 1
                            advanced = True
                        break  # UNKNOWN at span 1: no progress possible
                    span //= 2
                if not advanced:
                    return
        finally:
            self.timeout = saved_timeout
            self.conflict_budget = saved_budget

    def _solve_prepared_keep_model(self, prep, assumptions) -> str:
        """Like _solve_prepared but keeps the previous model on non-SAT."""
        saved = self._model
        status = self._solve_prepared(prep, assumptions)
        if status != SAT:
            self._model = saved
        return status

"""Incremental cross-query preparation: prefix-memoized word-level
pipeline (Solver._prepare).

The engine issues thousands of sibling solver queries per analyze run,
and path constraints grow monotonically: query N+1's constraint list is
query N's plus a handful of new terms. The full prepare pipeline
(simplify -> substitution fixpoint -> lowering -> blast) nevertheless
re-walks the ENTIRE list every time. This module memoizes the word-level
phase across queries, exploiting that terms are hash-consed
(smt/terms.py) so id-keyed memo tables are sound until the intern table
generation bumps:

  simplify memo   `simplify_expr` per interned term id, with the walk
                  stopping at already-simplified subterms — a suffix
                  term costs O(new nodes), a repeated term costs O(1)
                  (counted `prepare_incremental_hits`).
  prefix memo     each prepared query snapshots its word-level state —
                  residual constraints, substitution list, the live
                  `_Lowering` (side constraints undrained) and the
                  lowered prefix — keyed on the tuple of asserted term
                  ids. A child query whose assertion list extends a
                  snapshot resumes from it and only substitutes/lowers
                  its suffix (counted `prepare_prefix_resumes` + a
                  suffix-length histogram).
  free-symbols    `terms.free_symbols` per root term id (the per-query
                  prep.symbols scan re-walks the whole constraint DAG
                  otherwise).

Correctness guard: a suffix term that introduces a new `sym == rhs`
definition over a symbol the prefix residual still references — or a
narrowing bound (`x < c`) on such a symbol — would substitute back
through the already-lowered prefix. Those queries fall back to the full
pipeline (counted `prepare_prefix_fallbacks`). Suffix-only definitions
and bounds (symbols the prefix never saw) are handled incrementally,
mirroring `propagate_equalities` / `narrow_bounded_symbols` term-for-term
so the resumed pipeline emits the IDENTICAL lowered list, side-constraint
order and fresh-symbol numbering the full pipeline would — the CNF, the
model bits and the reconstructed model are byte-identical on vs off.

Invalidation: every memo keys on `terms.Term.generation` and clears when
the intern table is rebuilt (ids would dangle), exactly like the global
blaster; `support/model.clear_caches` resets it explicitly. Gated by
`--no-incremental-prep` / MYTHRIL_TPU_INCR_PREP on top of the
preanalysis master switch.
"""

import os
from collections import OrderedDict
from typing import Dict, List, Optional

from mythril_tpu.smt import terms
from mythril_tpu.smt.solver.statistics import SolverStatistics

# memo caps: cleared wholesale on overflow (per-entry eviction would
# break the pinning argument — see _State docstring)
SIMPLIFY_MEMO_MAX = 1_000_000
FREE_SYMBOLS_MEMO_MAX = 200_000
PREFIX_MEMO_MAX = 32
# snapshots past this many lowering-cache entries are not recorded: the
# clone cost and retained memory would outweigh the resume win
SNAPSHOT_NODE_CAP = 200_000


def _prefix_memo_max() -> int:
    """Live prefix-memo cap: MYTHRIL_TPU_PREFIX_MEMO_MAX (env or tuned
    profile — support/env resolution) over the module default. Read at
    use, not import, so a tuned profile applied at startup reaches it."""
    from mythril_tpu.support.env import env_int

    return env_int("MYTHRIL_TPU_PREFIX_MEMO_MAX", PREFIX_MEMO_MAX)


def _snapshot_node_cap() -> int:
    """Live snapshot-size cap: MYTHRIL_TPU_SNAPSHOT_NODE_CAP (env or
    tuned profile) over the module default."""
    from mythril_tpu.support.env import env_int

    return env_int("MYTHRIL_TPU_SNAPSHOT_NODE_CAP", SNAPSHOT_NODE_CAP)
# mirrors propagate_equalities' max_rounds for the suffix fixpoint
SUFFIX_ROUNDS = 8


def enabled() -> bool:
    """The incremental layer rides the preanalysis subsystem: on by
    default whenever preanalysis is, `--no-incremental-prep` turns just
    this layer off, and MYTHRIL_TPU_INCR_PREP=0/1 overrides the flag
    either way (the preanalysis master switch still gates everything)."""
    from mythril_tpu import preanalysis

    if not preanalysis.enabled():
        return False
    env = os.environ.get("MYTHRIL_TPU_INCR_PREP", "")
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    from mythril_tpu.support.args import args

    return not getattr(args, "no_incremental_prep", False)


class PrefixSnapshot:
    """Word-level prepare state at one assertion-list prefix.

    Self-contained for pinning: `key_terms` pins the key ids, `residual`
    pins every term the lowering cache keys can name (a resumed child's
    residual extends its parent's, so the containment is inductive), and
    the lowering is stored with its side constraints UNDRAINED so a
    resume reproduces the full pipeline's root ordering exactly."""

    __slots__ = ("key_terms", "residual", "substitutions", "taken_equal",
                 "taken_narrow", "free_names", "lowering", "lowered")

    def __init__(self, key_terms, residual, substitutions, taken_equal,
                 taken_narrow, free_names, lowering, lowered):
        self.key_terms = key_terms
        self.residual = residual
        self.substitutions = substitutions
        self.taken_equal = taken_equal
        self.taken_narrow = taken_narrow
        self.free_names = free_names
        self.lowering = lowering
        self.lowered = lowered


class Resume:
    """A prepare resumed (or statically settled) from a prefix snapshot."""

    __slots__ = ("unsat", "residual", "suffix_residual", "substitutions",
                 "taken_equal", "taken_narrow", "lowering",
                 "lowered_prefix")

    def __init__(self, unsat=False, residual=None, suffix_residual=None,
                 substitutions=None, taken_equal=None, taken_narrow=None,
                 lowering=None, lowered_prefix=None):
        self.unsat = unsat
        self.residual = residual
        self.suffix_residual = suffix_residual
        self.substitutions = substitutions
        self.taken_equal = taken_equal
        self.taken_narrow = taken_narrow
        self.lowering = lowering
        self.lowered_prefix = lowered_prefix


class _State:
    """All cross-query memo state for one term-table generation.

    Memo keys are `id(term)`; every key's term is pinned (a reused id
    after garbage collection would alias another term's entry, the same
    hazard the Blaster pins against). Simplify/free-symbol memos pin
    their walk roots — interior keys stay alive through the roots'
    children tuples. Prefix snapshots pin themselves (see
    PrefixSnapshot)."""

    __slots__ = ("generation", "simp_memo", "simp_pinned", "free_memo",
                 "free_pinned", "prefix_memo", "lengths", "origins")

    def __init__(self, generation: int):
        self.generation = generation
        self.simp_memo: Dict[int, terms.Term] = {}
        self.simp_pinned: List[terms.Term] = []
        self.free_memo: Dict[int, frozenset] = {}
        self.free_pinned: List[terms.Term] = []
        self.prefix_memo: "OrderedDict" = OrderedDict()
        self.lengths: Dict[int, int] = {}  # key length -> live snapshots
        # snapshot key -> origin tag of the analysis that RECORDED it
        # (None outside a tenancy context). Drives session-scoped
        # eviction (evict_session): one tenant's invalidation drops its
        # snapshots without cold-starting every other tenant's.
        self.origins: Dict[tuple, Optional[str]] = {}

    def drop_snapshot(self, key: tuple) -> None:
        self.prefix_memo.pop(key, None)
        self.origins.pop(key, None)
        live = self.lengths.get(len(key), 0) - 1
        if live <= 0:
            self.lengths.pop(len(key), None)
        else:
            self.lengths[len(key)] = live

    def clear_simplify(self) -> None:
        self.simp_memo = {}
        self.simp_pinned = []

    def clear_free(self) -> None:
        self.free_memo = {}
        self.free_pinned = []


_state_obj: Optional[_State] = None


def _state() -> _State:
    global _state_obj
    generation = terms.Term.generation
    if _state_obj is None or _state_obj.generation != generation:
        _state_obj = _State(generation)
    return _state_obj


def reset() -> None:
    """Drop every memo (clear_caches / testing hook)."""
    global _state_obj
    _state_obj = None


def evict_session(session: str) -> int:
    """Drop ONE session's prefix snapshots (those recorded while one of
    its origins held the baton), leaving every other tenant's snapshots
    — and the content-addressed simplify/free-symbol memos — intact.
    Returns the number of evicted snapshots."""
    state = _state_obj
    if state is None:
        return 0
    from mythril_tpu.service.tenancy import origin_in_session

    doomed = [key for key, origin in list(state.origins.items())
              if origin is not None and origin_in_session(origin, session)]
    for key in doomed:
        state.drop_snapshot(key)
    return len(doomed)


def snapshot_count(session: Optional[str] = None) -> int:
    """Live prefix snapshots, optionally only those a session recorded
    (isolation-audit/test observability)."""
    state = _state_obj
    if state is None:
        return 0
    if session is None:
        return len(state.prefix_memo)
    from mythril_tpu.service.tenancy import origin_in_session

    return sum(1 for origin in list(state.origins.values())
               if origin is not None and origin_in_session(origin, session))


# -- memoized simplify --------------------------------------------------------


def simplify_cached(term: terms.Term) -> terms.Term:
    """terms.simplify_expr with a cross-query per-node memo: the walk
    stops at any subterm simplified by an earlier query, so sibling
    queries pay only for their genuinely new nodes."""
    state = _state()
    memo = state.simp_memo
    hit = memo.get(id(term))
    if hit is not None:
        SolverStatistics().add_prepare_simplify_hits()
        return hit
    if len(memo) > SIMPLIFY_MEMO_MAX:
        state.clear_simplify()
        memo = state.simp_memo
    stack = [(term, False)]
    while stack:
        node, expanded = stack.pop()
        if id(node) in memo:
            continue
        if not expanded:
            stack.append((node, True))
            for child in node.children:
                if id(child) not in memo:
                    stack.append((child, False))
            continue
        if not node.children:
            result = node
        else:
            new_children = [memo[id(c)] for c in node.children]
            if all(a is b for a, b in zip(new_children, node.children)):
                result = node
            else:
                result = terms.rebuild(node, new_children)
        memo[id(node)] = result
        state.simp_pinned.append(node)
    return memo[id(term)]


def free_symbols_cached(roots) -> set:
    """Union of terms.free_symbols keys over `roots`, memoized per root
    term id (repeated constraint roots dominate sibling queries)."""
    state = _state()
    memo = state.free_memo
    out = set()
    for root in roots:
        hit = memo.get(id(root))
        if hit is None:
            if len(memo) > FREE_SYMBOLS_MEMO_MAX:
                state.clear_free()
                memo = state.free_memo
            hit = frozenset(terms.free_symbols([root]))
            memo[id(root)] = hit
            state.free_pinned.append(root)
        out |= hit
    return out


# -- prefix memo --------------------------------------------------------------


def record(asserted, residual, substitutions, taken_equal, taken_narrow,
           lowering, lowered) -> None:
    """Snapshot a prepared query's word-level state under its assertion
    ids so a child query can resume from it. Must be called BEFORE the
    lowering's side constraints are drained (the snapshot clones the
    live object)."""
    if not asserted:
        return
    if len(lowering.cache) > _snapshot_node_cap():
        return
    state = _state()
    key = tuple(id(t) for t in asserted)
    if key in state.prefix_memo:
        state.prefix_memo.move_to_end(key)
        return
    free_names = frozenset(
        name for name, _sort in free_symbols_cached(residual))
    from mythril_tpu.service.interleave import current_origin

    state.origins[key] = current_origin()
    state.prefix_memo[key] = PrefixSnapshot(
        key_terms=tuple(asserted),
        residual=tuple(residual),
        substitutions=tuple(substitutions),
        taken_equal=frozenset(taken_equal),
        taken_narrow=frozenset(taken_narrow),
        free_names=free_names,
        lowering=lowering.clone(),
        lowered=tuple(lowered),
    )
    state.lengths[len(key)] = state.lengths.get(len(key), 0) + 1
    while len(state.prefix_memo) > _prefix_memo_max():
        old_key, _old = state.prefix_memo.popitem(last=False)
        state.origins.pop(old_key, None)
        live = state.lengths.get(len(old_key), 0) - 1
        if live <= 0:
            state.lengths.pop(len(old_key), None)
        else:
            state.lengths[len(old_key)] = live


def try_resume(asserted) -> Optional[Resume]:
    """Resume `asserted`'s prepare from the longest memoized prefix, or
    None (no snapshot, or the guard forced a full-pipeline fallback —
    counted). The returned lowering is a private clone the caller may
    mutate."""
    state = _state()
    if not state.prefix_memo or not asserted:
        return None
    ids = tuple(id(t) for t in asserted)
    snap = None
    prefix_len = 0
    for length in sorted(state.lengths, reverse=True):
        if length > len(ids):
            continue
        candidate = state.prefix_memo.get(ids[:length])
        if candidate is not None:
            state.prefix_memo.move_to_end(ids[:length])
            snap, prefix_len = candidate, length
            break
    if snap is None:
        return None
    stats = SolverStatistics()
    suffix = asserted[prefix_len:]
    from mythril_tpu.observe.tracer import span as trace_span

    with trace_span("solver.prefix_resume", cat="solver",
                    prefix=prefix_len, suffix=len(suffix)) as sp:
        resume = _resume_from(snap, suffix)
        if resume is None:
            sp.set(fallback=True)
            stats.add_prefix_fallback()
            return None
        stats.add_prefix_resume(len(suffix))
    return resume


def _narrow_candidate(term) -> Optional[str]:
    """Name of the symbol `term` would narrow (mirrors the eligibility
    filter of frontend.narrow_bounded_symbols), or None."""
    if term.op not in ("bvult", "bvule"):
        return None
    lhs, rhs = term.children
    if lhs.op != "sym" or not isinstance(lhs.sort, int):
        return None
    if not (rhs.is_const and isinstance(rhs.value, int)):
        return None
    bound = rhs.value - 1 if term.op == "bvult" else rhs.value
    if bound < 0:
        return None
    if max(1, bound.bit_length()) >= lhs.sort:
        return None
    return lhs.params[0]


def _substitute_fixpoint(term, mapping, frontend):
    """Apply a substitution map to fixpoint — the memoized-simplify twin
    of frontend._substitute_simplify_fixpoint (definition chains leave
    bound symbols inside verbatim-inserted rhs subtrees; both pipelines
    must resolve them identically)."""
    if not mapping:
        return term
    for _ in range(len(mapping) + 1):
        new = simplify_cached(frontend._substitute([term], mapping)[0])
        if new is term:
            break
        term = new
    return term


def _resume_from(snap: PrefixSnapshot, suffix) -> Optional[Resume]:
    """Run the word-level pipeline over `suffix` only, on top of `snap`.

    Returns None to force the full-pipeline fallback whenever the suffix
    would have changed how the prefix itself was processed:

      - a new `sym == rhs` definition over a symbol the prefix residual
        still references (it would substitute back through already-
        lowered terms), or over a symbol the prefix NARROWED (the full
        pipeline would have bound it before narrowing ever ran);
      - a narrowing bound on a symbol the prefix residual references or
        already narrowed (the full pipeline computes the min width over
        ALL bounds and rewrites every use site).

    The raw-term guard matters: the prefix's substitutions rewrite
    `x` into `zext(!narrow!x)`, which MASKS the binding/bound shape the
    full pipeline would have seen — so narrowed names are checked on the
    raw suffix terms before any substitution."""
    from mythril_tpu.smt.solver import frontend

    taken_equal = set(snap.taken_equal)
    taken_narrow = snap.taken_narrow
    blocked = snap.free_names

    for term in suffix:
        binding = frontend._extract_binding(term, taken_equal)
        if binding is not None and binding[0] in taken_narrow:
            return None
        name = _narrow_candidate(term)
        if name is not None and name in taken_narrow:
            return None

    mapping = dict(snap.substitutions)
    local_subs = []
    work = []
    for term in suffix:
        term = _substitute_fixpoint(term, mapping, frontend)
        if term.is_const:
            if term.value is False:
                return Resume(unsat=True)
            continue
        work.append(term)

    # suffix-local equality propagation, mirroring propagate_equalities:
    # bindings over symbols the prefix never saw are safe (nothing to
    # substitute back through), everything else falls back
    residual_suffix = work
    for _ in range(SUFFIX_ROUNDS):
        found: Dict[str, terms.Term] = {}
        remaining = []
        for term in work:
            if found:
                term = _substitute_fixpoint(term, found, frontend)
                if term.is_const:
                    if term.value is False:
                        return Resume(unsat=True)
                    continue
            binding = frontend._extract_binding(term, taken_equal)
            if binding is not None:
                name, rhs = binding
                if name in blocked or name in taken_narrow:
                    return None  # substitutes back through the prefix
                taken_equal.add(name)
                found[name] = rhs
                local_subs.append((name, rhs))
                continue
            remaining.append(term)
        if not found:
            residual_suffix = remaining
            break
        work = []
        for term in remaining:
            term = _substitute_fixpoint(term, found, frontend)
            if term.is_const:
                if term.value is False:
                    return Resume(unsat=True)
                continue
            work.append(term)
        residual_suffix = work

    # suffix-local narrowing: only for symbols the prefix never saw
    taken_all = taken_equal | set(taken_narrow)
    candidates = {_narrow_candidate(t) for t in residual_suffix}
    candidates.discard(None)
    if (candidates - taken_all) & blocked:
        return None  # the bound would narrow prefix use sites
    residual_suffix, narrow_subs = frontend.narrow_bounded_symbols(
        residual_suffix, taken_all)
    if residual_suffix is None:
        return Resume(unsat=True)

    return Resume(
        unsat=False,
        residual=list(snap.residual) + residual_suffix,
        suffix_residual=residual_suffix,
        substitutions=(list(snap.substitutions) + local_subs
                       + list(narrow_subs)),
        taken_equal=taken_equal,
        taken_narrow=set(taken_narrow) | {n for n, _ in narrow_subs},
        lowering=snap.lowering.clone(),
        lowered_prefix=list(snap.lowered),
    )

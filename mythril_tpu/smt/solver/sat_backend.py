"""CNF SAT backends.

Primary: the C++ CDCL solver in native/sat.cpp, compiled on first use with
g++ (no pybind11 in this environment — plain C ABI via ctypes). Fallback:
a compact pure-Python CDCL, used when no compiler is available and by the
test suite for differential checks.
"""

import ctypes
import os
import subprocess
import tempfile
import threading
import time
from typing import Iterable, List, Optional, Sequence, Tuple

from mythril_tpu.observe.tracer import span as trace_span

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))
_SOURCE = os.path.join(_REPO_ROOT, "native", "sat.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "_build")

SAT, UNSAT, UNKNOWN = "sat", "unsat", "unknown"

_lib = None
_lib_lock = threading.Lock()
_native_failed = False
_device_warned = False


def _compile_native() -> Optional[ctypes.CDLL]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so_path = os.path.join(_BUILD_DIR, "libsat.so")
    if (not os.path.exists(so_path)
            or os.path.getmtime(so_path) < os.path.getmtime(_SOURCE)):
        with tempfile.NamedTemporaryFile(
            suffix=".so", dir=_BUILD_DIR, delete=False
        ) as tmp:
            tmp_path = tmp.name
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
               "-o", tmp_path, _SOURCE]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp_path, so_path)
        except (subprocess.SubprocessError, OSError):
            if os.path.exists(tmp_path):
                os.unlink(tmp_path)
            return None
    lib = ctypes.CDLL(so_path)
    lib.sat_solve.restype = ctypes.c_int
    lib.sat_solve.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
        ctypes.c_double,
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_byte),
    ]
    lib.aig_cone.restype = None
    lib.aig_cone.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_ubyte),
        ctypes.POINTER(ctypes.c_longlong),
    ]
    lib.aig_emit_cnf.restype = ctypes.c_longlong
    lib.aig_emit_cnf.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_ubyte),
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong),
    ]
    lib.sat_session_new.restype = ctypes.c_void_p
    lib.sat_session_new.argtypes = []
    lib.sat_session_free.restype = None
    lib.sat_session_free.argtypes = [ctypes.c_void_p]
    lib.sat_session_add_cnf.restype = None
    lib.sat_session_add_cnf.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_longlong),
        ctypes.c_int,
    ]
    lib.sat_session_solve.restype = ctypes.c_int
    lib.sat_session_solve.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
        ctypes.c_double,
        ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_byte),
    ]
    return lib


def _get_native():
    global _lib, _native_failed
    if _lib is not None or _native_failed:
        return _lib
    with _lib_lock:
        if _lib is None and not _native_failed:
            _lib = _compile_native()
            if _lib is None:
                _native_failed = True
    return _lib


def get_native_lib():
    """The compiled native library (or None) — also hosts the AIG cone/
    Tseitin exporters used by smt/bitblast.py."""
    return _get_native()


# ---------------------------------------------------------------------------
# Per-query incremental CDCL sessions. A prepared problem's cone instance
# (up to ~1M clauses on heavy contracts) used to be re-marshalled and
# re-loaded into a fresh solver for EVERY assumption probe — Optimize's
# minimization alone fires a dozen probes per exploit. A session loads the
# instance once; probes solve under assumptions on the persistent solver,
# reusing its learnt clauses, saved phases, and VSIDS state. (A cross-query
# global-AIG session was tried first and was 2x SLOWER: every solve must
# assign and propagate the union of all queries' cones.)


class PrepSession:
    """Owns one native solver pre-loaded with a query's CNF.

    A session is single-instance by contract: reloading a live session
    that already holds learnt clauses from a previous CNF would be unsound
    (the learnt clauses were derived from the OLD instance). load_cnf
    enforces that — it refuses a second load instead of trusting every
    caller to know the rule (round-5 advisor #3)."""

    __slots__ = ("_ptr", "num_vars", "_loaded")

    def __init__(self, ptr, num_vars: int):
        self._ptr = ptr
        self.num_vars = num_vars
        self._loaded = False

    def load_cnf(self, num_vars: int, clauses) -> None:
        """Load the instance into the native solver — exactly once."""
        if self._loaded:
            raise RuntimeError(
                "PrepSession already holds a CNF instance; a second load "
                "would solve under learnt clauses from the previous "
                "instance (unsound). Create a fresh session instead.")
        import numpy as np

        lib = _get_native()
        if not hasattr(clauses, "lits"):
            from mythril_tpu.smt.bitblast import CNF

            clauses = CNF.from_clauses(clauses)
        lits_np = np.ascontiguousarray(clauses.lits, dtype=np.int32)
        offs_np = np.ascontiguousarray(clauses.offsets, dtype=np.int64)
        lib.sat_session_add_cnf(
            self._ptr, num_vars,
            lits_np.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            offs_np.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            len(clauses))
        self.num_vars = num_vars
        self._loaded = True

    def solve(self, assumptions, timeout_seconds: float = 0.0,
              conflict_budget: int = 0):
        import numpy as np

        lib = _get_native()
        assume = np.ascontiguousarray(
            np.asarray(list(assumptions), dtype=np.int32))
        model = np.zeros(self.num_vars + 1, dtype=np.int8)
        i32p = ctypes.POINTER(ctypes.c_int)
        status = lib.sat_session_solve(
            self._ptr, assume.ctypes.data_as(i32p), len(assume),
            float(timeout_seconds), int(conflict_budget),
            model.ctypes.data_as(ctypes.POINTER(ctypes.c_byte)))
        if status == 10:
            # List[bool], matching _solve_native's contract: np.bool_ would
            # leak into models and fail the frontend's `is not True`
            # identity validation on genuinely valid assignments
            return SAT, model.astype(bool).tolist()
        if status == 20:
            return UNSAT, None
        return UNKNOWN, None

    def __del__(self):
        try:
            lib = _lib  # avoid re-compiling during interpreter shutdown
            if lib is not None and self._ptr:
                lib.sat_session_free(self._ptr)
                self._ptr = None
        except Exception:
            pass


def create_prep_session(num_vars: int, clauses) -> Optional[PrepSession]:
    """Load a query's CNF into a fresh persistent solver (None without the
    native lib). `clauses` may be CNF buffers or a clause list (the latter
    is normalized through CNF.from_clauses rather than re-flattened here)."""
    lib = _get_native()
    if lib is None:
        return None
    ptr = lib.sat_session_new()
    if not ptr:
        return None
    session = PrepSession(ptr, num_vars)
    session.load_cnf(num_vars, clauses)
    return session


def solve_cnf(
    num_vars: int,
    clauses: Sequence[Tuple[int, ...]],
    assumptions: Iterable[int] = (),
    timeout_seconds: float = 0.0,
    conflict_budget: int = 0,
    allow_device: bool = True,
    aig_roots=None,
    crosscheck: bool = False,
    session_ctx: Optional[PrepSession] = None,
) -> Tuple[str, Optional[List[bool]]]:
    """Solve CNF with DIMACS-signed literals.

    Returns (status, model) where model[v] is the boolean of var v (1-based),
    or None unless SAT.

    With `--solver-backend=tpu` the batched device local-search solver gets
    the first slice of the budget (it can only return SAT-with-model; every
    model is re-checked on host). The CDCL remains the UNSAT prover and
    ground-truth oracle.
    """
    assumptions = list(assumptions)
    from mythril_tpu.support.args import args as _args

    if _args.solver_backend == "tpu" and not conflict_budget and allow_device:
        import time as _time

        start = _time.monotonic()
        # Local search cannot prove UNSAT, and feasibility queries are
        # mostly UNSAT: let a conflict-budgeted CDCL probe settle the easy
        # ones first; only queries it can't crack go to the device. Skip
        # the probe on mega-instances (multiplier confirms, ~10 s solves):
        # 20k conflicts never settles those, and the wasted half-second
        # pushed near-deadline SAT verdicts into timeout on the tpu path
        # while the cpu path found them.
        if len(clauses) <= 200_000:
            # forward `crosscheck`: a probe-settled UNSAT is still a
            # detection verdict and must get its second opinion — without
            # this the tpu path silently bypassed the crosscheck for
            # exactly the small UNSAT queries the probe settles
            probe_status, probe_model = solve_cnf(
                num_vars, clauses, assumptions,
                timeout_seconds=min(0.5, timeout_seconds or 0.5),
                conflict_budget=20000,
                crosscheck=crosscheck,
                session_ctx=session_ctx,
            )
            if probe_status != UNKNOWN:
                return probe_status, probe_model
        if aig_roots is not None and not assumptions:
            try:
                from mythril_tpu.smt.solver.statistics import (
                    SolverStatistics,
                )
                from mythril_tpu.tpu.router import get_router

                # the adaptive router owns the device decision (calibrated
                # caps, cost model, host-fallback deadline, health
                # breaker); a lone query is just a batch of one
                stats = SolverStatistics()
                bits = get_router().dispatch(
                    [(num_vars, clauses, aig_roots)],
                    timeout_seconds, stats)[0]
                stats.add_device_batch_query(hit=bits is not None)
                if bits is not None:
                    return SAT, bits
            except Exception as error:
                # jax absent OR broken at runtime (device OOM, compile
                # error, wedged transport): degrade to CDCL-only, never
                # crash the run
                global _device_warned
                if not _device_warned:
                    _device_warned = True
                    import logging

                    logging.getLogger(__name__).warning(
                        "device solver unavailable, falling back to CDCL "
                        "for the rest of the run: %s", error)
        if timeout_seconds:
            timeout_seconds = max(
                0.05, timeout_seconds - (_time.monotonic() - start))
    lib = _get_native()
    # one terminal host-CDCL solve (session/native/python alike): the
    # number the solve-service cache tiers exist to shrink — crosscheck
    # re-solves are deliberately excluded (they call _solve_* directly).
    # Timed into settle_wall (the settle leg of the roofline wall split)
    # and traced as the solver.settle stage.
    from mythril_tpu.smt.solver.statistics import SolverStatistics

    settle_start = time.monotonic()
    with trace_span("solver.settle", cat="solver",
                    clauses=len(clauses), vars=num_vars,
                    assumptions=len(assumptions)):
        if lib is not None and session_ctx is not None:
            # per-query session: the instance is already loaded; only the
            # assumptions vary per probe. Models are dense-numbered as
            # usual — the frontend's independent validation re-checks them
            # against the ORIGINAL constraints regardless of which path
            # produced them. Cheap invariant: a session solves whatever
            # instance it was loaded with, so a caller handing it a
            # DIFFERENT problem's (num_vars, clauses) would silently get
            # the wrong verdict (round-5 advisor #3). A real raise, not
            # assert: python -O must not compile away a soundness guard
            if session_ctx.num_vars != num_vars:
                raise ValueError(
                    f"session holds a {session_ctx.num_vars}-var instance, "
                    f"caller passed {num_vars} vars — wrong session for "
                    f"this problem")
            status, model = session_ctx.solve(
                assumptions, timeout_seconds, conflict_budget)
        elif lib is not None:
            status, model = _solve_native(lib, num_vars, clauses,
                                          assumptions, timeout_seconds,
                                          conflict_budget)
        else:
            status, model = _solve_python(num_vars, clauses, assumptions,
                                          timeout_seconds, conflict_budget)
    SolverStatistics().add_cdcl_settle(
        clauses=len(clauses), seconds=time.monotonic() - settle_start)
    if status == UNSAT and (crosscheck or _crosscheck_enabled()):
        status = _crosscheck_unsat(num_vars, clauses, assumptions,
                                   timeout_seconds, conflict_budget)
    return status, model


def _crosscheck_enabled() -> bool:
    """Global force-enable (the CI sweep runs the whole suite with it on).
    Detection-path crosschecking is on by DEFAULT via the `crosscheck`
    parameter (support/model.py detection_context); this env var extends it
    to every solve (=1) or force-disables nothing here (=0 is handled by
    the caller's _crosscheck_wanted)."""
    return os.environ.get("MYTHRIL_TPU_UNSAT_CROSSCHECK", "") not in ("", "0")


CROSSCHECK_CLAUSE_CAP = 150_000
_crosscheck_cap_warned = False

# outcome of the most recent _crosscheck_unsat in this thread: True only
# when the permuted re-solve POSITIVELY re-proved UNSAT (cap-skips and
# inconclusive timeouts are False). The persistent result store reads this
# right after an UNSAT settle to record provenance-as-confirmed, never
# provenance-as-requested (support/model._crosscheck_confirmed).
_last_crosscheck_confirmed = False


def last_crosscheck_confirmed() -> bool:
    return _last_crosscheck_confirmed


def _crosscheck_unsat(num_vars, clauses, assumptions, timeout_seconds,
                      conflict_budget=0) -> str:
    """Soundness net for UNSAT verdicts (SAT models are independently
    validated at the frontend; UNSAT had no second opinion). Re-solve under
    a random variable relabeling + clause shuffle — a search-order-dependent
    CDCL bug that wrongly reports UNSAT is overwhelmingly unlikely to do so
    again on the permuted instance. Disagreement degrades the verdict to
    UNKNOWN (callers treat that as possibly-feasible) and logs loudly.
    On by default for detection-path verdicts (support/model.py);
    MYTHRIL_TPU_UNSAT_CROSSCHECK=1 extends it to every solve. Bounded two
    ways: instances past CROSSCHECK_CLAUSE_CAP are skipped (a permuted
    multiplier cone inside the cap budget is almost always UNKNOWN — pure
    cost, no information) and the re-solve itself is capped at 3 s."""
    from mythril_tpu.smt.solver.statistics import SolverStatistics

    global _last_crosscheck_confirmed
    _last_crosscheck_confirmed = False
    if len(clauses) > CROSSCHECK_CLAUSE_CAP:
        # the skip is counted (and announced once per process): callers —
        # and CI — must be able to tell a netted UNSAT verdict from one
        # that never got its second opinion (round-5 advisor #1: the net
        # is absent on exactly the heaviest confirmation cones, where a
        # CDCL bug is most likely to hide)
        SolverStatistics().add_crosscheck(skipped=True)
        global _crosscheck_cap_warned
        if not _crosscheck_cap_warned:
            _crosscheck_cap_warned = True
            import logging

            logging.getLogger(__name__).warning(
                "UNSAT crosscheck skipped: instance has %d clauses "
                "(cap %d). Detection UNSATs this size keep their verdict "
                "WITHOUT a permuted-instance second opinion; the "
                "crosscheck_cap_skips statistic counts every such skip "
                "this run.", len(clauses), CROSSCHECK_CLAUSE_CAP)
        return UNSAT
    SolverStatistics().add_crosscheck(skipped=False)
    crosscheck_start = time.monotonic()
    try:
        with trace_span("solver.crosscheck", cat="solver",
                        clauses=len(clauses), vars=num_vars):
            return _crosscheck_resolve(num_vars, clauses, assumptions,
                                       timeout_seconds, conflict_budget)
    finally:
        SolverStatistics().add_crosscheck_seconds(
            time.monotonic() - crosscheck_start)


def _crosscheck_resolve(num_vars, clauses, assumptions, timeout_seconds,
                        conflict_budget) -> str:
    """The permuted re-solve itself (split out so the caller can time it
    into crosscheck_wall around every return path)."""
    global _last_crosscheck_confirmed
    import random as _random

    rng = _random.Random(num_vars * 1_000_003 + len(clauses))
    perm = list(range(1, num_vars + 1))
    rng.shuffle(perm)
    relabel = {v: perm[v - 1] for v in range(1, num_vars + 1)}

    def map_lit(lit: int) -> int:
        return relabel[lit] if lit > 0 else -relabel[-lit]

    if hasattr(clauses, "lits"):
        # CNF buffers: vectorized relabel + clause-order shuffle (the
        # tuple-by-tuple path burned seconds per crosscheck on 100k-clause
        # instances)
        import numpy as np

        from mythril_tpu.smt.bitblast import CNF

        perm_arr = np.empty(num_vars + 1, dtype=np.int64)
        perm_arr[0] = 0
        perm_arr[1:] = perm
        lits = clauses.lits
        relabeled = np.where(
            lits > 0, perm_arr[np.abs(lits)], -perm_arr[np.abs(lits)]
        ).astype(np.int32)
        offsets = clauses.offsets
        order = np.arange(len(clauses))
        rng.shuffle(order)
        lengths = (offsets[1:] - offsets[:-1])[order]
        new_offsets = np.zeros(len(clauses) + 1, dtype=np.int64)
        np.cumsum(lengths, out=new_offsets[1:])
        # ragged gather of source literal indices in shuffled clause order:
        # position i maps to src_start[clause(i)] + (i - dst_start[clause(i)])
        total = int(new_offsets[-1])
        src_starts = offsets[:-1][order]
        gather = (
            np.arange(total, dtype=np.int64)
            + np.repeat(src_starts - new_offsets[:-1], lengths)
        )
        shuffled = CNF(relabeled[gather], new_offsets, len(clauses),
                       clauses.has_empty)
    else:
        shuffled = [tuple(map_lit(l) for l in clause) for clause in clauses]
        rng.shuffle(shuffled)
    mapped_assumptions = [map_lit(a) for a in assumptions]
    # crosscheck runs CDCL-only (allow_device False by construction: this
    # path is below the device dispatch) and never re-crosschecks. Always
    # bounded: the caller's timeout carries over but is capped at 3 s —
    # the second opinion must not double detection-path wall on heavy
    # cones (an inconclusive timeout keeps the original UNSAT verdict:
    # crosscheck can only DEGRADE a verdict on positive disagreement)
    timeout_seconds = min(timeout_seconds or 3.0, 3.0)
    lib = _get_native()
    if lib is not None:
        second, _ = _solve_native(lib, num_vars, shuffled,
                                  mapped_assumptions, timeout_seconds,
                                  conflict_budget)
    else:
        second, _ = _solve_python(num_vars, shuffled, mapped_assumptions,
                                  timeout_seconds, conflict_budget)
    if second == SAT:
        import logging

        logging.getLogger(__name__).critical(
            "UNSAT crosscheck DISAGREED: permuted instance is SAT "
            "(%d vars, %d clauses) — degrading verdict to UNKNOWN",
            num_vars, len(clauses))
        return UNKNOWN
    # UNSAT = positively re-proved; UNKNOWN (timeout) keeps the verdict
    # but is NOT a confirmation — persistence must not record it as one
    _last_crosscheck_confirmed = second == UNSAT
    return UNSAT


def _solve_native(lib, num_vars, clauses, assumptions, timeout_seconds,
                  conflict_budget):
    num_clauses = len(clauses)
    if hasattr(clauses, "lits"):
        # CNF buffers (smt/bitblast.py): hand the numpy storage straight to
        # the C ABI — per-literal Python marshalling was a top-2 hotspot on
        # heavy contracts (round-4 profile: ~37 s of ether_send's wall)
        import numpy as np

        lits_np = np.ascontiguousarray(clauses.lits, dtype=np.int32)
        offs_np = np.ascontiguousarray(clauses.offsets, dtype=np.int64)
        lits_arr = lits_np.ctypes.data_as(ctypes.POINTER(ctypes.c_int))
        offs_arr = offs_np.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))
    else:
        flat: List[int] = []
        offsets: List[int] = [0]
        for clause in clauses:
            flat.extend(clause)
            offsets.append(len(flat))
        lits_arr = (ctypes.c_int * max(len(flat), 1))(*flat)
        offs_arr = (ctypes.c_longlong * len(offsets))(*offsets)
    assume_arr = (ctypes.c_int * max(len(assumptions), 1))(*assumptions)
    model_arr = (ctypes.c_byte * (num_vars + 1))()
    status = lib.sat_solve(
        num_vars, lits_arr, offs_arr, num_clauses, assume_arr,
        len(assumptions), float(timeout_seconds), int(conflict_budget),
        model_arr,
    )
    if status == 10:
        return SAT, [bool(model_arr[v]) for v in range(num_vars + 1)]
    if status == 20:
        return UNSAT, None
    return UNKNOWN, None


# ---------------------------------------------------------------------------
# pure-Python fallback CDCL (watched literals, VSIDS-lite; assumptions are
# applied as unit clauses — sound for one-shot solving)


def _solve_python(num_vars, clauses, assumptions, timeout_seconds,
                  conflict_budget=0):
    import time as _time

    deadline = _time.monotonic() + timeout_seconds if timeout_seconds else None

    # preprocess: dedupe lits, drop tautologies
    db: List[List[int]] = []
    units: List[int] = list(assumptions)
    for clause in clauses:
        lits = sorted(set(clause))
        if not lits:
            return UNSAT, None
        if any(-l in lits for l in lits):
            continue
        if len(lits) == 1:
            units.append(lits[0])
        else:
            db.append(lits)

    assign = {}          # var -> bool
    level = {}
    reason = {}
    trail: List[int] = []
    trail_lim: List[int] = []
    watches = {}         # lit -> list of clause indices watching -lit ... use neg map
    activity = [0.0] * (num_vars + 1)
    var_inc = 1.0

    for ci, lits in enumerate(db):
        for lit in lits[:2]:
            watches.setdefault(-lit, []).append(ci)

    def lit_value(lit):
        v = assign.get(abs(lit))
        if v is None:
            return None
        return v if lit > 0 else not v

    def enqueue(lit, r):
        var = abs(lit)
        if var in assign:
            return lit_value(lit)
        assign[var] = lit > 0
        level[var] = len(trail_lim)
        reason[var] = r
        trail.append(lit)
        return True

    def propagate():
        while propagate.qhead < len(trail):
            p = trail[propagate.qhead]
            propagate.qhead += 1
            watching = watches.get(p, [])
            i = 0
            while i < len(watching):
                ci = watching[i]
                lits = db[ci]
                if lits[0] == -p:
                    lits[0], lits[1] = lits[1], lits[0]
                if lit_value(lits[0]) is True:
                    i += 1
                    continue
                moved = False
                for k in range(2, len(lits)):
                    if lit_value(lits[k]) is not False:
                        lits[1], lits[k] = lits[k], lits[1]
                        watches.setdefault(-lits[1], []).append(ci)
                        watching[i] = watching[-1]
                        watching.pop()
                        moved = True
                        break
                if moved:
                    continue
                if lit_value(lits[0]) is False:
                    propagate.qhead = len(trail)
                    return ci
                enqueue(lits[0], ci)
                i += 1
        return None
    propagate.qhead = 0

    def rescale_activity():
        nonlocal var_inc
        if var_inc > 1e100:
            for v in range(len(activity)):
                activity[v] *= 1e-100
            var_inc *= 1e-100

    def analyze(ci):
        nonlocal var_inc
        learnt = [None]
        counter = 0
        seen = set()
        p = None
        index = len(trail)
        while True:
            lits = db[ci] if ci is not None else []
            start = 0 if p is None else 1
            for lit in lits[start:]:
                var = abs(lit)
                if var not in seen and level.get(var, 0) > 0:
                    seen.add(var)
                    activity[var] += var_inc
                    if level[var] >= len(trail_lim):
                        counter += 1
                    else:
                        learnt.append(lit)
            while True:
                index -= 1
                if abs(trail[index]) in seen:
                    break
            p = trail[index]
            ci = reason.get(abs(p))
            seen.discard(abs(p))
            counter -= 1
            if counter == 0:
                break
        learnt[0] = -p
        var_inc /= 0.95
        rescale_activity()
        if len(learnt) == 1:
            return learnt, 0
        bt = max(level[abs(l)] for l in learnt[1:])
        max_i = max(range(1, len(learnt)), key=lambda i: level[abs(learnt[i])])
        learnt[1], learnt[max_i] = learnt[max_i], learnt[1]
        return learnt, bt

    def cancel_until(lvl):
        while len(trail_lim) > lvl:
            mark = trail_lim.pop()
            while len(trail) > mark:
                lit = trail.pop()
                var = abs(lit)
                del assign[var]
                level.pop(var, None)
                reason.pop(var, None)
        propagate.qhead = len(trail)

    for unit in units:
        if enqueue(unit, None) is False:
            return UNSAT, None
    if propagate() is not None:
        return UNSAT, None

    conflicts = 0
    while True:
        confl = propagate()
        if confl is not None:
            conflicts += 1
            if deadline and conflicts % 256 == 0 and _time.monotonic() > deadline:
                return UNKNOWN, None
            if conflict_budget and conflicts > conflict_budget:
                return UNKNOWN, None
            if not trail_lim:
                return UNSAT, None
            learnt, bt = analyze(confl)
            cancel_until(bt)
            if len(learnt) == 1:
                if enqueue(learnt[0], None) is False:
                    return UNSAT, None
            else:
                db.append(learnt)
                ci = len(db) - 1
                for lit in learnt[:2]:
                    watches.setdefault(-lit, []).append(ci)
                enqueue(learnt[0], ci)
        else:
            free = None
            best = -1.0
            for var in range(1, num_vars + 1):
                if var not in assign and activity[var] > best:
                    best = activity[var]
                    free = var
            if free is None:
                model = [False] * (num_vars + 1)
                for var, val in assign.items():
                    model[var] = val
                return SAT, model
            trail_lim.append(len(trail))
            enqueue(-free, None)

"""Solver backends: word-level frontend, CPU CDCL (C++), TPU batched solver."""

from mythril_tpu.smt.solver.frontend import (  # noqa: F401
    Optimize,
    Solver,
    UnsatError,
    SolverTimeOutException,
)
from mythril_tpu.smt.solver.statistics import SolverStatistics  # noqa: F401

"""Model objects returned by the solvers (reference laser/smt/model.py).

A model is an assignment (see eval.py) plus `eval(expr, model_completion)`.
Supports merging several sub-models (the independence solver concatenates
per-bucket models, reference solver/independence_solver.py:123-144)."""

from typing import Dict, List, Optional

from mythril_tpu.smt import terms
from mythril_tpu.smt.bitvec import BitVec, Expression
from mythril_tpu.smt.eval import evaluate


class Model:
    def __init__(self, assignment: Optional[Dict] = None, sub_models: Optional[List["Model"]] = None):
        self.assignment: Dict = dict(assignment or {})
        for sub in sub_models or []:
            self.assignment.update(sub.assignment)

    def decls(self):
        return list(self.assignment)

    def __bool__(self):
        return True

    def eval(self, expression, model_completion: bool = True):
        """Evaluate a wrapper or raw term to a concrete BitVec/bool."""
        raw = expression.raw if isinstance(expression, Expression) else expression
        result = evaluate(raw, self.assignment)
        if isinstance(raw.sort, int):
            return BitVec.value(result, raw.sort)
        return result

    def eval_int(self, expression, default: int = 0) -> int:
        raw = expression.raw if isinstance(expression, Expression) else expression
        result = evaluate(raw, self.assignment)
        if isinstance(result, bool):
            return int(result)
        return result

    def satisfies(self, constraints) -> bool:
        """Check this model against a constraint list (quick-sat probe).
        One shared node cache across the list: sibling constraints share
        their path-prefix cone, which the per-constraint evaluate() used to
        re-walk (a top hotspot on heavy contracts)."""
        from mythril_tpu.smt.eval import evaluate_shared

        values: Dict = {}
        try:
            for constraint in constraints:
                raw = constraint.raw if isinstance(constraint, Expression) else constraint
                if evaluate_shared(raw, self.assignment, values) is not True:
                    return False
            return True
        except NotImplementedError:
            return False

    def __repr__(self):
        items = ", ".join(f"{k}={v}" for k, v in list(self.assignment.items())[:8])
        return f"Model({items}{'…' if len(self.assignment) > 8 else ''})"

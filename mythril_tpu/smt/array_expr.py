"""Functional arrays (reference mythril/laser/smt/array.py surface).

`Array("Storage_...", 256, 256)` — free symbolic array;
`K(256, 256, 0)` — constant array. Index read returns a BitVec; item
assignment rebinds the wrapper to a Store chain (matching the reference's
mutate-in-place usage for storage/balances)."""

from mythril_tpu.smt import terms
from mythril_tpu.smt.bitvec import BitVec, Expression, _union, coerce


class BaseArray(Expression):
    __slots__ = ()

    @property
    def domain(self) -> int:
        return self.raw.sort[1]

    @property
    def range(self) -> int:
        return self.raw.sort[2]

    def __getitem__(self, index) -> BitVec:
        index = coerce(index, self.domain)
        return BitVec(
            terms.select(self.raw, index.raw),
            _union(self.annotations, index.annotations),
        )

    def __setitem__(self, index, value) -> None:
        index = coerce(index, self.domain)
        value = coerce(value, self.range)
        self.raw = terms.store(self.raw, index.raw, value.raw)
        self.annotations = _union(
            self.annotations, index.annotations, value.annotations
        )

    def clone(self) -> "BaseArray":
        dup = type(self).__new__(type(self))
        dup.raw = self.raw
        dup.annotations = set(self.annotations)
        return dup


class Array(BaseArray):
    __slots__ = ()

    def __init__(self, name: str, domain: int = 256, range_: int = 256):
        super().__init__(terms.array_sym(name, domain, range_))


class K(BaseArray):
    __slots__ = ()

    def __init__(self, domain: int = 256, range_: int = 256, value: int = 0):
        value_term = terms.bv_val(value, range_)
        super().__init__(terms.const_array(domain, value_term))

"""QF_BV -> AIG -> CNF lowering.

The seam between the word-level term DAG and both SAT backends (C++ CDCL on
host, batched clause tensors on TPU). Terms reaching this layer must be pure
QF_BV — arrays and UFs are eliminated by the solver frontend first
(ackermannization + read-over-write unwinding, see solver/frontend.py).

Literal encoding (standard AIG): variable v -> literals 2v (pos) / 2v+1
(neg); constants FALSE=0, TRUE=1. AND gates are structurally hashed.
Bit vectors are LSB-first literal lists. CNF via Tseitin (3 clauses/gate).
"""

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from mythril_tpu.smt.terms import BOOL, Term

FALSE_LIT = 0
TRUE_LIT = 1


_AIG_UID = 0


class CNF:
    """Flat CNF: DIMACS literals in one int32 array + int64 clause offsets.

    The numpy buffers go straight to the C++ CDCL via pointer (no per-lit
    marshalling) and to the vectorized clause checker; iteration yields the
    legacy tuple-of-ints view for the pure-Python fallback paths."""

    __slots__ = ("lits", "offsets", "num_clauses", "has_empty")

    def __init__(self, lits, offsets, num_clauses: int, has_empty: bool):
        self.lits = lits
        self.offsets = offsets
        self.num_clauses = num_clauses
        self.has_empty = has_empty

    def __len__(self) -> int:
        return self.num_clauses

    def __iter__(self) -> Iterator[Tuple[int, ...]]:
        lits, offsets = self.lits, self.offsets
        for c in range(self.num_clauses):
            yield tuple(int(l) for l in lits[offsets[c]:offsets[c + 1]])

    @classmethod
    def from_clauses(cls, clauses) -> "CNF":
        offsets = np.zeros(len(clauses) + 1, dtype=np.int64)
        flat: List[int] = []
        has_empty = False
        for i, clause in enumerate(clauses):
            if not clause:
                has_empty = True
            flat.extend(clause)
            offsets[i + 1] = len(flat)
        return cls(np.array(flat, dtype=np.int32), offsets, len(clauses),
                   has_empty)


class DenseMap:
    """global AIG var -> dense CNF var, over a numpy column (0 = absent).
    Drop-in for the dict the Python exporter used (.get protocol)."""

    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = arr

    def get(self, var: int, default=None):
        if 0 <= var < len(self.arr):
            dense = int(self.arr[var])
            if dense:
                return dense
        return default

    def __getitem__(self, var: int) -> int:
        dense = self.get(var)
        if dense is None:
            raise KeyError(var)
        return dense

    def __len__(self) -> int:
        return int(np.count_nonzero(self.arr))


class _GateView:
    """Dict-like read view of the AIG's flat gate arrays (compat shim for
    the levelizer and tests; no per-gate dict is materialized)."""

    __slots__ = ("_aig",)

    def __init__(self, aig: "AIG"):
        self._aig = aig

    def get(self, var: int, default=None):
        lhs = self._aig.gate_lhs
        if 0 <= var < len(lhs) and lhs[var] >= 0:
            return (lhs[var], self._aig.gate_rhs[var])
        return default

    def __getitem__(self, var: int) -> Tuple[int, int]:
        gate = self.get(var)
        if gate is None:
            raise KeyError(var)
        return gate

    def items(self):
        lhs, rhs = self._aig.gate_lhs, self._aig.gate_rhs
        for var in range(1, len(lhs)):
            if lhs[var] >= 0:
                yield var, (lhs[var], rhs[var])


class AIG:
    """And-Inverter Graph with structural hashing. Append-only: a root
    literal's cone never changes once created, so (aig.uid, roots) is a
    sound cache key for packed/blasted artifacts.

    Gates live in flat per-var lists (gate_lhs/gate_rhs, -1 = circuit
    input), mirrored incrementally into numpy arrays so cone extraction and
    Tseitin export run in native/sat.cpp instead of per-node Python."""

    def __init__(self):
        global _AIG_UID
        _AIG_UID += 1
        self.uid = _AIG_UID
        self.num_vars = 0          # var 0 reserved for constant TRUE/FALSE
        self.gate_lhs: List[int] = [-1]   # per var: defining gate's inputs
        self.gate_rhs: List[int] = [-1]   # (-1 for circuit inputs / const)
        self._strash: Dict[Tuple[int, int], int] = {}
        self._np_lhs: Optional[np.ndarray] = None
        self._np_rhs: Optional[np.ndarray] = None
        self._np_count = 0  # entries already mirrored into the numpy arrays

    @property
    def gate_of_var(self) -> _GateView:
        return _GateView(self)

    def new_var(self) -> int:
        self.num_vars += 1
        self.gate_lhs.append(-1)
        self.gate_rhs.append(-1)
        return self.num_vars

    def lit_of_var(self, var: int, negated: bool = False) -> int:
        return 2 * var + (1 if negated else 0)

    def gate_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """int32 views of the gate table, synced to the current watermark
        (only the tail appended since the last call is converted)."""
        n = self.num_vars + 1
        if self._np_lhs is None or len(self._np_lhs) < n:
            # capacity-doubling growth: a concatenate-per-sync would
            # re-copy the whole mirrored prefix on every blast call
            # (quadratic over an analyze run on the shared global AIG)
            capacity = 1024
            while capacity < n:
                capacity *= 2
            new_lhs = np.empty(capacity, dtype=np.int32)
            new_rhs = np.empty(capacity, dtype=np.int32)
            if self._np_lhs is not None and self._np_count:
                new_lhs[:self._np_count] = self._np_lhs[:self._np_count]
                new_rhs[:self._np_count] = self._np_rhs[:self._np_count]
            else:
                self._np_count = 0
            self._np_lhs, self._np_rhs = new_lhs, new_rhs
        if self._np_count < n:
            self._np_lhs[self._np_count:n] = self.gate_lhs[self._np_count:n]
            self._np_rhs[self._np_count:n] = self.gate_rhs[self._np_count:n]
            self._np_count = n
        return self._np_lhs[:n], self._np_rhs[:n]

    def and_gate(self, a: int, b: int) -> int:
        if a > b:
            a, b = b, a
        if a == FALSE_LIT:
            return FALSE_LIT
        if a == TRUE_LIT:
            return b
        if a == b:
            return a
        if a ^ 1 == b:
            return FALSE_LIT
        key = (a, b)
        hit = self._strash.get(key)
        if hit is not None:
            return hit
        var = self.new_var()
        self.gate_lhs[var] = a
        self.gate_rhs[var] = b
        lit = 2 * var
        self._strash[key] = lit
        return lit

    def or_gate(self, a: int, b: int) -> int:
        return self.and_gate(a ^ 1, b ^ 1) ^ 1

    def xor_gate(self, a: int, b: int) -> int:
        return self.or_gate(self.and_gate(a, b ^ 1), self.and_gate(a ^ 1, b))

    def xnor_gate(self, a: int, b: int) -> int:
        return self.xor_gate(a, b) ^ 1

    def mux(self, sel: int, then: int, otherwise: int) -> int:
        return self.or_gate(self.and_gate(sel, then), self.and_gate(sel ^ 1, otherwise))

    def to_cnf(self, roots: List[int], defined: List[int] = ()):
        """Tseitin-encode gates reachable from `roots` + `defined`.

        `roots` are asserted true; `defined` literals only get their defining
        gate clauses emitted (used by Optimize to constrain objective bits
        via SAT assumptions without asserting them).

        The cone's variables are renumbered into a DENSE 1..N space — the
        AIG is shared across problems (frontend get_global_blaster), and a
        CNF in global numbering would make every solve pay O(all vars ever
        blasted). Returns (num_dense_vars, cnf, dense_of_global) where `cnf`
        is a CNF of DIMACS-signed DENSE literals and dense_of_global a
        DenseMap. Cone extraction + emission run in native/sat.cpp when the
        library is available (the pure-Python exporter dominated
        heavy-contract wall time); the Python path below is the fallback
        and the differential reference for it (tests/test_bitblast.py)."""
        native = self._to_cnf_native(roots, defined)
        if native is not None:
            return native
        return self._to_cnf_python(roots, defined)

    def _to_cnf_native(self, roots, defined):
        import ctypes

        from mythril_tpu.smt.solver import sat_backend

        lib = sat_backend.get_native_lib()
        if lib is None:
            return None
        i32p = ctypes.POINTER(ctypes.c_int)
        i64p = ctypes.POINTER(ctypes.c_longlong)
        u8p = ctypes.POINTER(ctypes.c_ubyte)

        def p32(arr):
            return arr.ctypes.data_as(i32p)

        lhs, rhs = self.gate_arrays()
        seeds = np.array(
            [r for r in list(roots) + list(defined) if (r >> 1) != 0],
            dtype=np.int32,
        )
        needed = np.empty(self.num_vars + 1, dtype=np.uint8)
        counts = np.zeros(2, dtype=np.int64)
        lib.aig_cone(self.num_vars, p32(lhs), p32(rhs), p32(seeds),
                     len(seeds), needed.ctypes.data_as(u8p),
                     counts.ctypes.data_as(i64p))
        gates = int(counts[0])
        roots_arr = np.asarray(list(roots), dtype=np.int32)
        lits = np.empty(7 * gates + len(roots_arr), dtype=np.int32)
        offsets = np.empty(3 * gates + len(roots_arr) + 1, dtype=np.int64)
        dense_arr = np.empty(self.num_vars + 1, dtype=np.int32)
        meta = np.zeros(3, dtype=np.int64)
        n_lits = lib.aig_emit_cnf(
            self.num_vars, p32(lhs), p32(rhs), needed.ctypes.data_as(u8p),
            p32(roots_arr), len(roots_arr), p32(dense_arr), p32(lits),
            offsets.ctypes.data_as(i64p), meta.ctypes.data_as(i64p))
        num_clauses = int(meta[1])
        cnf = CNF(lits[:n_lits], offsets[:num_clauses + 1], num_clauses,
                  bool(meta[2]))
        return int(meta[0]), cnf, DenseMap(dense_arr)

    def _to_cnf_python(self, roots, defined):
        clauses: List[Tuple[int, ...]] = []

        # find reachable gates (the gate table is maintained incrementally
        # so a small cone never pays for the whole shared AIG)
        needed = set()
        gate_of_var = self.gate_of_var
        stack = [r >> 1 for r in list(roots) + list(defined) if r >> 1 != 0]
        while stack:
            var = stack.pop()
            if var in needed:
                continue
            needed.add(var)
            gate = gate_of_var.get(var)
            if gate is not None:
                for lit in gate:
                    if lit >> 1 != 0:
                        stack.append(lit >> 1)

        dense = {var: i for i, var in enumerate(sorted(needed), start=1)}

        def dimacs(lit: int) -> int:
            var = dense[lit >> 1]
            return -var if lit & 1 else var

        for var in sorted(needed):
            gate = gate_of_var.get(var)
            if gate is None:
                continue  # circuit input
            lhs, rhs = gate
            g, a, b = dense[var], dimacs(lhs), dimacs(rhs)
            clauses.append((-g, a))
            clauses.append((-g, b))
            clauses.append((g, -a, -b))

        for root in roots:
            if root == FALSE_LIT:
                clauses.append(())  # empty clause: trivially unsat
            elif root == TRUE_LIT:
                continue
            else:
                clauses.append((dimacs(root),))
        dense_arr = np.zeros(self.num_vars + 1, dtype=np.int32)
        for var, dvar in dense.items():
            dense_arr[var] = dvar
        return len(dense), CNF.from_clauses(clauses), DenseMap(dense_arr)


class Blaster:
    """Memoized lowering of a term DAG into one shared AIG."""

    def __init__(self):
        self.aig = AIG()
        self._bv_cache: Dict[int, List[int]] = {}
        self._bool_cache: Dict[int, int] = {}
        # memo keys are id(term): pin every memoized term so it cannot be
        # garbage collected — a reused id would make the cache return
        # another term's literals (the blaster outlives single problems)
        self._pinned: List[Term] = []
        # (name, width) -> var ids (LSB first) for model extraction
        self.bv_symbol_vars: Dict[Tuple[str, int], List[int]] = {}
        self.bool_symbol_vars: Dict[str, int] = {}

    # -- public -------------------------------------------------------------

    def assert_bool(self, term: Term) -> int:
        return self._bool(term)

    def bv_bits(self, term: Term) -> List[int]:
        """AIG literals (LSB-first) of a bitvector term; grows the AIG."""
        return self._bv(term)

    def cnf(self, assertion_terms: List[Term], defined_lits: List[int] = ()):
        roots = [self._bool(t) for t in assertion_terms]
        # kept for the device circuit-SLS path (tpu/circuit.py), which
        # searches over AIG inputs instead of CNF variables
        self.last_roots = roots
        return self.aig.to_cnf(roots, defined_lits)

    # -- bool lowering ------------------------------------------------------

    def _bool(self, term: Term) -> int:
        assert term.sort == BOOL, f"not a bool: {term!r}"
        hit = self._bool_cache.get(id(term))
        if hit is not None:
            return hit
        lit = self._bool_compute(term)
        self._bool_cache[id(term)] = lit
        self._pinned.append(term)
        return lit

    def _bool_compute(self, term: Term) -> int:
        aig = self.aig
        op = term.op
        if op == "true":
            return TRUE_LIT
        if op == "false":
            return FALSE_LIT
        if op == "sym":
            name = term.params[0]
            var = self.bool_symbol_vars.get(name)
            if var is None:
                var = aig.new_var()
                self.bool_symbol_vars[name] = var
            return 2 * var
        if op == "not":
            return self._bool(term.children[0]) ^ 1
        if op == "and":
            acc = TRUE_LIT
            for child in term.children:
                acc = aig.and_gate(acc, self._bool(child))
            return acc
        if op == "or":
            acc = FALSE_LIT
            for child in term.children:
                acc = aig.or_gate(acc, self._bool(child))
            return acc
        if op == "xor":
            return aig.xor_gate(self._bool(term.children[0]), self._bool(term.children[1]))
        if op == "ite":
            return aig.mux(
                self._bool(term.children[0]),
                self._bool(term.children[1]),
                self._bool(term.children[2]),
            )
        if op == "eq":
            a, b = term.children
            if a.sort == BOOL:
                return aig.xnor_gate(self._bool(a), self._bool(b))
            return self._eq_bits(self._bv(a), self._bv(b))
        if op in ("bvult", "bvule", "bvslt", "bvsle"):
            return self._compare(op, term.children[0], term.children[1])
        if op == "umul_novfl":
            return self._umul_no_ovfl(
                self._bv(term.children[0]), self._bv(term.children[1])
            )
        raise NotImplementedError(f"bool lowering: {op}")

    def _umul_no_ovfl(self, xs: List[int], ys: List[int]) -> int:
        """No-unsigned-mul-overflow at ~half the gates of a double-width
        multiplier: the product's high half is zero iff no partial product
        sheds bits past the width (x's top i bits with y_i set) and no
        accumulation step carries out of the low half. Exact: terms are
        non-negative, so the running total once >= 2^n stays there."""
        aig = self.aig
        size = len(xs)
        # suffix[j] = OR of xs[j:] (shed-bits detector, shared across steps)
        suffix = [FALSE_LIT] * (size + 1)
        for j in range(size - 1, -1, -1):
            suffix[j] = aig.or_gate(xs[j], suffix[j + 1])
        acc = [FALSE_LIT] * size
        overflow = FALSE_LIT
        for i, y in enumerate(ys):
            if y == FALSE_LIT:
                continue
            if i > 0:
                overflow = aig.or_gate(overflow, aig.and_gate(y, suffix[size - i]))
            partial = [FALSE_LIT] * i + [aig.and_gate(x, y) for x in xs[: size - i]]
            acc, carry = self._add_carry(acc, partial)
            overflow = aig.or_gate(overflow, carry)
        return overflow ^ 1

    def _eq_bits(self, xs: List[int], ys: List[int]) -> int:
        acc = TRUE_LIT
        for x, y in zip(xs, ys):
            acc = self.aig.and_gate(acc, self.aig.xnor_gate(x, y))
        return acc

    def _compare(self, op: str, a: Term, b: Term) -> int:
        xs, ys = self._bv(a), self._bv(b)
        if op in ("bvult", "bvule"):
            lt = self._ult(xs, ys)
            if op == "bvult":
                return lt
            return self.aig.or_gate(lt, self._eq_bits(xs, ys))
        # signed: flip sign bits then unsigned compare
        xs2 = xs[:-1] + [xs[-1] ^ 1]
        ys2 = ys[:-1] + [ys[-1] ^ 1]
        lt = self._ult(xs2, ys2)
        if op == "bvslt":
            return lt
        return self.aig.or_gate(lt, self._eq_bits(xs, ys))

    def _ult(self, xs: List[int], ys: List[int]) -> int:
        """Unsigned less-than via borrow chain, LSB->MSB."""
        aig = self.aig
        lt = FALSE_LIT
        for x, y in zip(xs, ys):
            x_eq_y = aig.xnor_gate(x, y)
            x_lt_y = aig.and_gate(x ^ 1, y)
            lt = aig.or_gate(x_lt_y, aig.and_gate(x_eq_y, lt))
        return lt

    # -- bitvector lowering -------------------------------------------------

    def _bv(self, term: Term) -> List[int]:
        hit = self._bv_cache.get(id(term))
        if hit is not None:
            return hit
        bits = self._bv_compute(term)
        assert len(bits) == term.size, f"{term.op}: {len(bits)} != {term.size}"
        self._bv_cache[id(term)] = bits
        self._pinned.append(term)
        return bits

    def _bv_compute(self, term: Term) -> List[int]:
        aig = self.aig
        op = term.op
        size = term.size
        if op == "const":
            return [TRUE_LIT if (term.value >> i) & 1 else FALSE_LIT for i in range(size)]
        if op == "sym":
            # keyed by (name, size): the blaster outlives one problem, and
            # an unrelated same-named symbol of another width must not
            # alias (model reconstruction writes per-name, latest wins)
            key = (term.params[0], size)
            cached = self.bv_symbol_vars.get(key)
            if cached is None:
                cached = [aig.new_var() for _ in range(size)]
                self.bv_symbol_vars[key] = cached
            return [2 * v for v in cached]
        child_bits = [self._bv(c) for c in term.children if isinstance(c.sort, int)]
        if op == "bvand":
            return [aig.and_gate(x, y) for x, y in zip(*child_bits)]
        if op == "bvor":
            return [aig.or_gate(x, y) for x, y in zip(*child_bits)]
        if op == "bvxor":
            return [aig.xor_gate(x, y) for x, y in zip(*child_bits)]
        if op == "bvnot":
            return [x ^ 1 for x in child_bits[0]]
        if op == "bvneg":
            return self._add(
                [x ^ 1 for x in child_bits[0]],
                [TRUE_LIT] + [FALSE_LIT] * (size - 1),
            )
        if op == "bvadd":
            return self._add(child_bits[0], child_bits[1])
        if op == "bvsub":
            return self._add(child_bits[0], [y ^ 1 for y in child_bits[1]], carry_in=TRUE_LIT)
        if op == "bvmul":
            return self._mul(child_bits[0], child_bits[1])
        if op in ("bvudiv", "bvurem"):
            quotient, remainder = self._udivrem(child_bits[0], child_bits[1])
            return quotient if op == "bvudiv" else remainder
        if op in ("bvsdiv", "bvsrem"):
            return self._sdivrem(op, child_bits[0], child_bits[1])
        if op in ("bvshl", "bvlshr", "bvashr"):
            return self._shift(op, child_bits[0], child_bits[1])
        if op == "concat":
            out: List[int] = []
            for c, bits in zip(reversed(term.children), reversed(child_bits)):
                out.extend(bits)
            return out
        if op == "extract":
            hi, lo = term.params
            return child_bits[0][lo : hi + 1]
        if op == "zext":
            return child_bits[0] + [FALSE_LIT] * term.params[0]
        if op == "sext":
            return child_bits[0] + [child_bits[0][-1]] * term.params[0]
        if op == "ite":
            sel = self._bool(term.children[0])
            then_bits = self._bv(term.children[1])
            else_bits = self._bv(term.children[2])
            return [aig.mux(sel, t, e) for t, e in zip(then_bits, else_bits)]
        raise NotImplementedError(f"bv lowering: {op}")

    def _add(self, xs: List[int], ys: List[int], carry_in: int = FALSE_LIT) -> List[int]:
        return self._add_carry(xs, ys, carry_in)[0]

    def _add_carry(
        self, xs: List[int], ys: List[int], carry_in: int = FALSE_LIT
    ) -> Tuple[List[int], int]:
        """Ripple-carry adder returning (sum bits, carry out)."""
        aig = self.aig
        out = []
        carry = carry_in
        for x, y in zip(xs, ys):
            x_xor_y = aig.xor_gate(x, y)
            out.append(aig.xor_gate(x_xor_y, carry))
            carry = aig.or_gate(aig.and_gate(x, y), aig.and_gate(carry, x_xor_y))
        return out, carry

    def _mul(self, xs: List[int], ys: List[int]) -> List[int]:
        """Shift-and-add; constant zero partial products vanish via folding."""
        aig = self.aig
        size = len(xs)
        acc = [FALSE_LIT] * size
        for i, y in enumerate(ys):
            if y == FALSE_LIT:
                continue
            partial = [FALSE_LIT] * i + [aig.and_gate(x, y) for x in xs[: size - i]]
            acc = self._add(acc, partial)
        return acc

    def _udivrem(self, xs: List[int], ys: List[int]) -> Tuple[List[int], List[int]]:
        """Restoring division MSB-first; EVM convention: x/0 = 0, x%0 = 0."""
        aig = self.aig
        size = len(xs)
        remainder = [FALSE_LIT] * size
        quotient = [FALSE_LIT] * size
        for i in range(size - 1, -1, -1):
            remainder = [xs[i]] + remainder[:-1]  # shift left, bring down bit i
            geq = self._ult(remainder, ys) ^ 1   # remainder >= divisor
            diff = self._add(remainder, [y ^ 1 for y in ys], carry_in=TRUE_LIT)
            remainder = [aig.mux(geq, d, r) for d, r in zip(diff, remainder)]
            quotient[i] = geq
        # EVM convention: x/0 = 0 and x%0 = 0
        zero = self._eq_bits(ys, [FALSE_LIT] * size)
        quotient = [aig.and_gate(q, zero ^ 1) for q in quotient]
        remainder = [aig.and_gate(r, zero ^ 1) for r in remainder]
        return quotient, remainder

    def _sdivrem(self, op: str, xs: List[int], ys: List[int]) -> List[int]:
        aig = self.aig
        size = len(xs)
        sign_x, sign_y = xs[-1], ys[-1]
        abs_x = self._abs(xs)
        abs_y = self._abs(ys)
        quotient, remainder = self._udivrem(abs_x, abs_y)
        if op == "bvsdiv":
            neg = aig.xor_gate(sign_x, sign_y)
            result = quotient
        else:  # bvsrem takes the sign of the dividend
            neg = sign_x
            result = remainder
        negated = self._add([r ^ 1 for r in result], [TRUE_LIT] + [FALSE_LIT] * (size - 1))
        return [aig.mux(neg, n, r) for n, r in zip(negated, result)]

    def _abs(self, xs: List[int]) -> List[int]:
        aig = self.aig
        size = len(xs)
        sign = xs[-1]
        negated = self._add([x ^ 1 for x in xs], [TRUE_LIT] + [FALSE_LIT] * (size - 1))
        return [aig.mux(sign, n, x) for n, x in zip(negated, xs)]

    def _shift(self, op: str, xs: List[int], ys: List[int]) -> List[int]:
        """Barrel shifter; shift amounts >= size give 0 (or sign for ashr)."""
        aig = self.aig
        size = len(xs)
        stages = max(1, (size - 1).bit_length())
        fill = xs[-1] if op == "bvashr" else FALSE_LIT
        bits = list(xs)
        for stage in range(stages):
            amount = 1 << stage
            sel = ys[stage] if stage < len(ys) else FALSE_LIT
            if op == "bvshl":
                shifted = [fill] * min(amount, size) + bits[: max(size - amount, 0)]
            else:
                shifted = bits[amount:] + [fill] * min(amount, size)
            bits = [aig.mux(sel, s, b) for s, b in zip(shifted, bits)]
        overshoot = FALSE_LIT
        for extra_bit in ys[stages:]:
            overshoot = aig.or_gate(overshoot, extra_bit)
        return [aig.mux(overshoot, fill, b) for b in bits]

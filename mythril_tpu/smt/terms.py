"""Core immutable term DAG for the self-contained SMT stack.

Every expression is a `Term`: (op, children, params, sort). Constant folding
happens eagerly at construction; `simplify_expr` applies a deeper local
rewrite pass. Sorts: positive int = bitvector width; BOOL; ("arr", dom, rng).

The user-facing wrappers (BitVec/Bool/Array in sibling modules) hold a Term
plus mythril-style annotations; this module knows nothing about annotations.
"""

from typing import Dict, Iterable, Optional, Tuple

BOOL = "bool"


def arr_sort(dom: int, rng: int) -> Tuple[str, int, int]:
    return ("arr", dom, rng)


def _mask(size: int) -> int:
    return (1 << size) - 1


def to_signed(value: int, size: int) -> int:
    return value - (1 << size) if value >> (size - 1) else value


def to_unsigned(value: int, size: int) -> int:
    return value & _mask(size)


class Term:
    """Hash-consed: every construction goes through an intern table keyed by
    (op, params, sort, value, child identities), so structurally equal terms
    ARE the same object. This makes equality checks O(1) in the common case
    and lets downstream id-keyed memo tables (the bit-blaster, the lowering
    pass) hit across solver calls — repeated confirmation queries share
    their multiplier/keccak cones instead of re-blasting them."""

    __slots__ = ("op", "children", "params", "sort", "_hash", "is_const", "value")

    _intern: Dict[tuple, "Term"] = {}
    _INTERN_CAP = 8_000_000
    generation = 0  # bumped on clear; consumers key their caches on it

    def __new__(cls, op, children, params, sort, value=None):
        key = (op, params, sort, value, tuple(map(id, children)))
        hit = cls._intern.get(key)
        if hit is not None:
            return hit
        if len(cls._intern) > cls._INTERN_CAP:
            clear_intern()
        self = super().__new__(cls)
        self.op = op
        self.children = children  # tuple of Term
        self.params = params      # tuple of static data (ints, names, FuncDecl)
        self.sort = sort
        self.value = value        # int/bool when is_const
        self.is_const = value is not None
        self._hash = hash(
            (op, params, sort, value, tuple(c._hash for c in children))
        )
        cls._intern[key] = self
        return self

    def __init__(self, op, children, params, sort, value=None):
        pass  # fully initialized (or reused) in __new__

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        if self is other:
            return True
        if not isinstance(other, Term):
            return NotImplemented
        if self._hash != other._hash:
            return False
        # iterative structural comparison (DAGs can be deep)
        stack = [(self, other)]
        seen = set()
        while stack:
            a, b = stack.pop()
            if a is b:
                continue
            key = (id(a), id(b))
            if key in seen:
                continue
            seen.add(key)
            if (
                a.op != b.op
                or a.params != b.params
                or a.sort != b.sort
                or a.value != b.value
                or len(a.children) != len(b.children)
            ):
                return False
            stack.extend(zip(a.children, b.children))
        return True

    def __repr__(self):
        return term_to_str(self, max_depth=4)

    @property
    def size(self) -> int:
        assert isinstance(self.sort, int), f"not a bitvector: {self.sort}"
        return self.sort


def clear_intern() -> None:
    """Drop the intern table (live terms stay valid; sharing restarts).
    Consumers holding id-keyed caches over terms must key on `generation`."""
    Term._intern.clear()
    Term.generation += 1
    # the singletons must stay interned: EVERY bool constant site uses them
    Term._intern[("true", (), BOOL, True, ())] = TRUE
    Term._intern[("false", (), BOOL, False, ())] = FALSE


# ---------------------------------------------------------------------------
# constructors with eager folding


TRUE = Term("true", (), (), BOOL, True)
FALSE = Term("false", (), (), BOOL, False)


def bool_val(value: bool) -> Term:
    return TRUE if value else FALSE


def bv_val(value: int, size: int) -> Term:
    return Term("const", (), (), size, value & _mask(size))


def bv_sym(name: str, size: int) -> Term:
    return Term("sym", (), (name,), size)


def bool_sym(name: str) -> Term:
    return Term("sym", (), (name,), BOOL)


_COMMUTATIVE = {"bvadd", "bvmul", "bvand", "bvor", "bvxor", "eq", "and", "or", "xor"}


def _fold2(op, a: int, b: int, size: int) -> int:
    if op == "bvadd":
        return (a + b) & _mask(size)
    if op == "bvsub":
        return (a - b) & _mask(size)
    if op == "bvmul":
        return (a * b) & _mask(size)
    if op == "bvudiv":
        return (a // b) & _mask(size) if b else 0  # EVM: div by zero -> 0
    if op == "bvurem":
        return (a % b) & _mask(size) if b else 0
    if op == "bvsdiv":
        if b == 0:
            return 0
        sa, sb = to_signed(a, size), to_signed(b, size)
        q = abs(sa) // abs(sb)
        if (sa < 0) != (sb < 0):
            q = -q
        return to_unsigned(q, size)
    if op == "bvsrem":
        if b == 0:
            return 0
        sa, sb = to_signed(a, size), to_signed(b, size)
        r = abs(sa) % abs(sb)
        if sa < 0:
            r = -r
        return to_unsigned(r, size)
    if op == "bvand":
        return a & b
    if op == "bvor":
        return a | b
    if op == "bvxor":
        return a ^ b
    if op == "bvshl":
        return (a << b) & _mask(size) if b < size else 0
    if op == "bvlshr":
        return a >> b if b < size else 0
    if op == "bvashr":
        sa = to_signed(a, size)
        return to_unsigned(sa >> min(b, size - 1), size)
    raise NotImplementedError(op)


def bv_binop(op: str, a: Term, b: Term) -> Term:
    assert a.sort == b.sort, f"width mismatch {a.sort} vs {b.sort} in {op}"
    size = a.size
    if a.is_const and b.is_const:
        return bv_val(_fold2(op, a.value, b.value, size), size)
    # normalize constants left for commutative ops
    if op in _COMMUTATIVE and b.is_const and not a.is_const:
        a, b = b, a
    # identity / annihilator rewrites
    if a.is_const:
        v = a.value
        if op == "bvadd" and v == 0:
            return b
        if op == "bvmul":
            if v == 0:
                return a
            if v == 1:
                return b
            if (v & (v - 1)) == 0:  # 2^k: shift beats a shift-add multiplier
                return bv_binop("bvshl", b, bv_val(v.bit_length() - 1, size))
        if op == "bvand":
            if v == 0:
                return a
            if v == _mask(size):
                return b
        if op == "bvor":
            if v == 0:
                return b
            if v == _mask(size):
                return a
        if op == "bvxor" and v == 0:
            return b
    if b.is_const:
        v = b.value
        if op in ("bvsub", "bvshl", "bvlshr", "bvashr") and v == 0:
            return a
        if op in ("bvudiv", "bvsdiv") and v == 1:
            return a
        if op in ("bvshl", "bvlshr") and v >= size:
            return bv_val(0, size)
        # power-of-two strength reduction: a restoring-division circuit is
        # ~1500 gates/bit when blasted (solc emits div/mod-by-32 for packed
        # storage and div-by-2^224 for selector extraction all the time)
        if v > 1 and (v & (v - 1)) == 0:
            shift = v.bit_length() - 1
            if op == "bvudiv":
                return bv_binop("bvlshr", a, bv_val(shift, size))
            if op == "bvurem":
                return bv_binop("bvand", a, bv_val(v - 1, size))
    if op == "bvsub" and a == b:
        return bv_val(0, size)
    if op == "bvxor" and a == b:
        return bv_val(0, size)
    # symbolic power-of-two divisor/factor: `1 << s` is 2^s (or 0 once
    # s >= size, which matches EVM div-by-zero -> 0 and shl saturation),
    # so div/mul reduce to shifts and rem to a mask — the packed-storage
    # access pattern solc emits via EXP(0x100, ...)
    shift = _as_one_shl(b)
    if shift is not None:
        if op == "bvudiv":
            return bv_binop("bvlshr", a, shift)
        if op == "bvmul":
            return bv_binop("bvshl", a, shift)
        if op == "bvurem":
            # b == 0 (s >= size) must give a % 0 == 0, not the full mask
            return ite(
                eq(b, bv_val(0, size)),
                bv_val(0, size),
                bv_binop("bvand", a, bv_binop("bvsub", b, bv_val(1, size))),
            )
    if op == "bvmul":
        shift = _as_one_shl(a)
        if shift is not None:
            return bv_binop("bvshl", b, shift)
    return Term(op, (a, b), (), size)


def _as_one_shl(t: Term):
    """Return s when t is literally `1 << s`, else None."""
    if (
        t.op == "bvshl"
        and t.children[0].is_const
        and t.children[0].value == 1
    ):
        return t.children[1]
    return None


def bv_not(a: Term) -> Term:
    if a.is_const:
        return bv_val(~a.value, a.size)
    if a.op == "bvnot":
        return a.children[0]
    return Term("bvnot", (a,), (), a.size)


def bv_neg(a: Term) -> Term:
    if a.is_const:
        return bv_val(-a.value, a.size)
    return Term("bvneg", (a,), (), a.size)


def concat(parts: Iterable[Term]) -> Term:
    """MSB-first concatenation; merges adjacent constants."""
    flat = []
    for p in parts:
        if p.op == "concat":
            flat.extend(p.children)
        else:
            flat.append(p)
    assert flat, "empty concat"
    merged = [flat[0]]
    for p in flat[1:]:
        last = merged[-1]
        if p.is_const and last.is_const:
            merged[-1] = bv_val((last.value << p.size) | p.value, last.size + p.size)
        else:
            merged.append(p)
    if len(merged) == 1:
        return merged[0]
    total = sum(p.size for p in merged)
    return Term("concat", tuple(merged), (), total)


def extract(hi: int, lo: int, a: Term) -> Term:
    assert 0 <= lo <= hi < a.size, f"bad extract [{hi}:{lo}] of {a.size}"
    width = hi - lo + 1
    if width == a.size:
        return a
    if a.is_const:
        return bv_val(a.value >> lo, width)
    if a.op == "extract":
        inner_lo = a.params[1]
        return extract(hi + inner_lo, lo + inner_lo, a.children[0])
    if a.op == "concat":
        # narrow into the covered children
        offset = a.size
        pieces = []
        for child in a.children:
            offset -= child.size
            child_hi = offset + child.size - 1
            if child_hi < lo or offset > hi:
                continue
            take_hi = min(hi, child_hi) - offset
            take_lo = max(lo, offset) - offset
            pieces.append(extract(take_hi, take_lo, child))
        if pieces:
            return concat(pieces)
    if a.op in ("zext", "sext") and hi < a.children[0].size:
        return extract(hi, lo, a.children[0])
    if a.op == "zext" and lo >= a.children[0].size:
        return bv_val(0, width)
    return Term("extract", (a,), (hi, lo), width)


def zext(extra: int, a: Term) -> Term:
    if extra == 0:
        return a
    if a.is_const:
        return bv_val(a.value, a.size + extra)
    return Term("zext", (a,), (extra,), a.size + extra)


def sext(extra: int, a: Term) -> Term:
    if extra == 0:
        return a
    if a.is_const:
        return bv_val(to_signed(a.value, a.size), a.size + extra)
    return Term("sext", (a,), (extra,), a.size + extra)


def eq(a: Term, b: Term) -> Term:
    assert a.sort == b.sort, f"sort mismatch in eq: {a.sort} vs {b.sort}"
    if a.is_const and b.is_const:
        return bool_val(a.value == b.value)
    if a == b:
        return TRUE
    if b.is_const and not a.is_const:
        a, b = b, a
    return Term("eq", (a, b), (), BOOL)


def bv_cmp(op: str, a: Term, b: Term) -> Term:
    assert a.sort == b.sort and isinstance(a.sort, int)
    size = a.size
    if a.is_const and b.is_const:
        if op == "bvult":
            return bool_val(a.value < b.value)
        if op == "bvule":
            return bool_val(a.value <= b.value)
        if op == "bvslt":
            return bool_val(to_signed(a.value, size) < to_signed(b.value, size))
        if op == "bvsle":
            return bool_val(to_signed(a.value, size) <= to_signed(b.value, size))
    if a == b:
        return TRUE if op in ("bvule", "bvsle") else FALSE
    if op == "bvult" and b.is_const and b.value == 0:
        return FALSE
    if op == "bvule" and a.is_const and a.value == 0:
        return TRUE
    return Term(op, (a, b), (), BOOL)


def umul_no_ovfl(a: Term, b: Term) -> Term:
    """True iff the unsigned product a*b fits in a's width.

    Dedicated op instead of `Extract(2n-1, n, zext*zext) == 0`: the
    bit-blaster gives it a carry-out-OR network at roughly half the gates
    of a double-width multiplier (smt/bitblast.py _umul_no_ovfl) — the
    SWC-101 mul-overflow confirmations are the heaviest query class the
    engine produces. Constant-by-symbol folds to one comparison:
    c*b fits iff b <= (2^n - 1) // c."""
    assert a.sort == b.sort and isinstance(a.sort, int)
    size = a.size
    if a.is_const and b.is_const:
        return bool_val((a.value * b.value) >> size == 0)
    if a.is_const and not b.is_const:
        a, b = b, a
    if b.is_const:
        if b.value <= 1:
            return TRUE  # 0 or 1 times anything fits
        return bv_cmp("bvule", a, bv_val(((1 << size) - 1) // b.value, size))
    return Term("umul_novfl", (a, b), (), BOOL)


def bool_and(parts: Iterable[Term]) -> Term:
    flat = []
    for p in parts:
        assert p.sort == BOOL
        if p.is_const:
            if not p.value:
                return FALSE
            continue
        if p.op == "and":
            flat.extend(p.children)
        else:
            flat.append(p)
    # dedupe preserving order
    seen, uniq = set(), []
    for p in flat:
        if p._hash not in seen:
            seen.add(p._hash)
            uniq.append(p)
    if not uniq:
        return TRUE
    if len(uniq) == 1:
        return uniq[0]
    return Term("and", tuple(uniq), (), BOOL)


def bool_or(parts: Iterable[Term]) -> Term:
    flat = []
    for p in parts:
        assert p.sort == BOOL
        if p.is_const:
            if p.value:
                return TRUE
            continue
        if p.op == "or":
            flat.extend(p.children)
        else:
            flat.append(p)
    seen, uniq = set(), []
    for p in flat:
        if p._hash not in seen:
            seen.add(p._hash)
            uniq.append(p)
    if not uniq:
        return FALSE
    if len(uniq) == 1:
        return uniq[0]
    return Term("or", tuple(uniq), (), BOOL)


def bool_not(a: Term) -> Term:
    if a.is_const:
        return bool_val(not a.value)
    if a.op == "not":
        return a.children[0]
    return Term("not", (a,), (), BOOL)


def bool_xor(a: Term, b: Term) -> Term:
    if a.is_const and b.is_const:
        return bool_val(a.value != b.value)
    if a.is_const:
        return b if a.value is False else bool_not(b)
    if b.is_const:
        return a if b.value is False else bool_not(a)
    return Term("xor", (a, b), (), BOOL)


def ite(cond: Term, then: Term, otherwise: Term) -> Term:
    assert cond.sort == BOOL
    assert then.sort == otherwise.sort
    if cond.is_const:
        return then if cond.value else otherwise
    if then == otherwise:
        return then
    if then.sort == BOOL:
        if then is TRUE and otherwise is FALSE:
            return cond
        if then is FALSE and otherwise is TRUE:
            return bool_not(cond)
    return Term("ite", (cond, then, otherwise), (), then.sort)


# ---------------------------------------------------------------------------
# arrays (functional: base symbol / const K / store chains)


def array_sym(name: str, dom: int, rng: int) -> Term:
    return Term("array", (), (name,), arr_sort(dom, rng))


def const_array(dom: int, value: Term) -> Term:
    return Term("karray", (value,), (), arr_sort(dom, value.size))


def store(arr: Term, index: Term, value: Term) -> Term:
    _, dom, rng = arr.sort
    assert index.sort == dom and value.sort == rng
    return Term("store", (arr, index, value), (), arr.sort)


def select(arr: Term, index: Term) -> Term:
    _, dom, rng = arr.sort
    assert index.sort == dom, f"index width {index.sort} != {dom}"
    # read-over-write elimination when decidable syntactically
    probe = arr
    while True:
        if probe.op == "store":
            base, widx, wval = probe.children
            if index == widx:
                return wval
            if index.is_const and widx.is_const:
                probe = base  # definitely distinct, skip this write
                continue
            break  # may alias: keep the select on the original chain
        if probe.op == "karray":
            return probe.children[0]
        break
    return Term("select", (arr, index), (), rng)


# ---------------------------------------------------------------------------
# uninterpreted functions


class FuncDecl:
    __slots__ = ("name", "domain", "range")

    def __init__(self, name: str, domain: Tuple[int, ...], range_: int):
        self.name = name
        self.domain = domain
        self.range = range_

    def __repr__(self):
        return f"FuncDecl({self.name}: {self.domain} -> {self.range})"

    def __hash__(self):
        return hash((self.name, self.domain, self.range))

    def __eq__(self, other):
        return (
            isinstance(other, FuncDecl)
            and self.name == other.name
            and self.domain == other.domain
            and self.range == other.range
        )


def apply_func(func: FuncDecl, args: Tuple[Term, ...]) -> Term:
    assert tuple(a.sort for a in args) == func.domain, (
        f"{func}: bad arg sorts {[a.sort for a in args]}"
    )
    return Term("apply", tuple(args), (func,), func.range)


# ---------------------------------------------------------------------------
# traversal helpers


def walk_terms(roots):
    """Post-order unique traversal over a DAG (iterative)."""
    seen = set()
    order = []
    stack = [(r, False) for r in roots]
    while stack:
        node, expanded = stack.pop()
        if id(node) in seen:
            continue
        if expanded:
            seen.add(id(node))
            order.append(node)
        else:
            stack.append((node, True))
            for child in node.children:
                if id(child) not in seen:
                    stack.append((child, False))
    return order


def free_symbols(roots) -> Dict[Tuple[str, object], Term]:
    """All 'sym' and 'array' leaves, keyed by (name, sort)."""
    out = {}
    for node in walk_terms(roots):
        if node.op in ("sym", "array"):
            out[(node.params[0], node.sort)] = node
    return out


def term_to_str(term: Term, max_depth: int = 12) -> str:
    if max_depth < 0:
        return "…"
    if term.op == "const":
        return f"{term.value:#x}[{term.size}]" if term.size > 8 else f"{term.value}[{term.size}]"
    if term.op in ("true", "false"):
        return term.op
    if term.op in ("sym", "array"):
        return str(term.params[0])
    if term.op == "apply":
        inner = ", ".join(term_to_str(c, max_depth - 1) for c in term.children)
        return f"{term.params[0].name}({inner})"
    if term.op == "extract":
        hi, lo = term.params
        return f"extract[{hi}:{lo}]({term_to_str(term.children[0], max_depth - 1)})"
    inner = ", ".join(term_to_str(c, max_depth - 1) for c in term.children)
    return f"{term.op}({inner})"


# rebuild map used by substitution / simplification
_CONSTRUCTORS = {}


def rebuild(term: Term, new_children) -> Term:
    """Re-run the smart constructor for `term` over new children."""
    op = term.op
    if op in ("const", "sym", "array", "true", "false"):
        return term
    c = tuple(new_children)
    if op in ("bvadd", "bvsub", "bvmul", "bvudiv", "bvurem", "bvsdiv", "bvsrem",
              "bvand", "bvor", "bvxor", "bvshl", "bvlshr", "bvashr"):
        return bv_binop(op, c[0], c[1])
    if op == "bvnot":
        return bv_not(c[0])
    if op == "bvneg":
        return bv_neg(c[0])
    if op == "concat":
        return concat(c)
    if op == "extract":
        return extract(term.params[0], term.params[1], c[0])
    if op == "zext":
        return zext(term.params[0], c[0])
    if op == "sext":
        return sext(term.params[0], c[0])
    if op == "eq":
        return eq(c[0], c[1])
    if op in ("bvult", "bvule", "bvslt", "bvsle"):
        return bv_cmp(op, c[0], c[1])
    if op == "umul_novfl":
        return umul_no_ovfl(c[0], c[1])
    if op == "and":
        return bool_and(c)
    if op == "or":
        return bool_or(c)
    if op == "not":
        return bool_not(c[0])
    if op == "xor":
        return bool_xor(c[0], c[1])
    if op == "ite":
        return ite(c[0], c[1], c[2])
    if op == "store":
        return store(c[0], c[1], c[2])
    if op == "select":
        return select(c[0], c[1])
    if op == "karray":
        return const_array(term.sort[1], c[0])
    if op == "apply":
        return apply_func(term.params[0], c)
    raise NotImplementedError(op)


def substitute(roots, mapping: Dict[Term, Term]):
    """Replace occurrences (by structural equality) throughout a DAG."""
    cache: Dict[int, Term] = {}
    lookup = {t._hash: (t, r) for t, r in mapping.items()}

    def subst(node: Term) -> Term:
        hit = cache.get(id(node))
        if hit is not None:
            return hit
        pair = lookup.get(node._hash)
        if pair is not None and pair[0] == node:
            cache[id(node)] = pair[1]
            return pair[1]
        if not node.children:
            cache[id(node)] = node
            return node
        new_children = [subst(c) for c in node.children]
        if all(a is b for a, b in zip(new_children, node.children)):
            result = node
        else:
            result = rebuild(node, new_children)
        cache[id(node)] = result
        return result

    # iterative wrapper to avoid recursion limits on deep chains
    import sys
    old_limit = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old_limit, 100000))
    try:
        return [subst(r) for r in roots]
    finally:
        sys.setrecursionlimit(old_limit)


def simplify_expr(term: Term) -> Term:
    """Bottom-up re-application of all smart constructors."""
    cache: Dict[int, Term] = {}
    for node in walk_terms([term]):
        if not node.children:
            cache[id(node)] = node
            continue
        new_children = [cache[id(c)] for c in node.children]
        if all(a is b for a, b in zip(new_children, node.children)):
            cache[id(node)] = node
        else:
            cache[id(node)] = rebuild(node, new_children)
    return cache[id(term)]

"""Interleaved corpus analysis: N contracts' analyses coexist in one
process so their sibling solve queries can share ONE device stream.

Why this exists: every device launch used to pack cones from exactly one
contract's coalescing window, so corpus throughput was bounded by the
per-contract query arrival rate rather than device occupancy — while
nothing in the ragged paged layout (tpu/circuit.RaggedStream) requires
cones to share a parent query, let alone a parent contract. The missing
piece was a driver that makes queries from DIFFERENT contracts coexist
in time. This module is that driver's machinery:

  baton        N analyses run on N threads, but only ONE thread executes
               at any instant — a baton (condition variable + current
               slot id) is handed off cooperatively at explicit yield
               points. The engine's process-global state (term intern
               table, shared blaster AIG, module singletons, solver
               caches) is therefore never mutated concurrently: the
               scheduling is cooperative round-robin, not parallelism.
               The win is windows that MIX origins, not CPU overlap.
  yield points (a) every `quantum` exec-loop iterations (laser/svm.py
               calls tick() — fairness: a stress_dispatch-class contract
               cannot starve 2 s contracts of engine time), and (b) the
               coalescing scheduler's solve seam: an analysis whose
               sibling-query bundle was buffered PARKS instead of
               demanding a flush, the baton passes to another analysis,
               and only when every live analysis is parked (or none can
               make progress) does the window flush — carrying queries
               from every parked origin in ONE batched router dispatch.
  contexts     the per-analysis slices of process-global engine state
               are context-switched at every handoff: the wall-clock
               budget (paused while the origin is off-baton), the tx-id
               counter, the keccak/exponent function managers, every
               detection module's issue/cache state, the in-memory
               result tier + quick-sat model deque (per-origin — the
               cross-contract reuse boundary is the content-addressed
               persistent tier, whose replay-verified fingerprints are
               origin-blind by design), and the ambient
               detection-context flag. Isolation is what makes
               per-contract findings independent of the schedule: the
               interleaved run's findings are byte-identical to the
               sequential (interleave=1) run's.

The per-origin context-switch machinery itself (EngineContext, the
private blaster registry, session eviction) lives in
service/tenancy.py — one implementation shared with the serve daemon's
cross-request batcher, so the two drivers cannot drift.

Knobs: MYTHRIL_TPU_CORPUS_INTERLEAVE / --corpus-interleave selects the
driver (core.MythrilAnalyzer._fire_lasers_interleaved);
MYTHRIL_TPU_INTERLEAVE_QUANTUM sets the exec iterations per turn.
"""

import logging
import threading
from collections import deque
from contextlib import contextmanager
from typing import List, Optional

from mythril_tpu.service import tenancy
from mythril_tpu.service.tenancy import EngineContext as _EngineContext

log = logging.getLogger(__name__)

DEFAULT_QUANTUM = 16  # exec-loop iterations per baton turn

_active: Optional["Coordinator"] = None


class BatchCancelled(BaseException):
    """Raised inside an ABANDONED analysis thread at its next yield
    point after its coordinator was cancelled (the serve daemon's
    deadline kill). BaseException on purpose: it must cut straight
    through the engine's per-contract `except Exception` capture — an
    abandoned thread's analysis must die, not be recorded as a
    contract-level failure racing the requeued batch over the engine
    globals."""


def active() -> Optional["Coordinator"]:
    """The live coordinator, or None outside an interleaved corpus run."""
    return _active


def current_origin() -> Optional[str]:
    """Origin tag (contract identity) of the analysis holding the baton.
    None outside an interleaved run — single-contract invocations and
    the legacy sequential path are origin-less by construction."""
    coordinator = _active
    return coordinator._current_origin if coordinator is not None else None


# the slot thread's OWN coordinator (set at attach, cleared at detach):
# an abandoned thread must die at its next tick even when its cancelled
# coordinator is no longer installed — the global _active alone cannot
# tell an abandoned thread from the main thread
_thread_coordinator = threading.local()


def tick() -> None:
    """Exec-loop yield point (laser/svm.py): hand the baton to the next
    runnable analysis every `quantum` iterations. One thread-local +
    one global load and a None check when no coordinator is live — the
    cost discipline every always-on crossing in this codebase
    follows."""
    own = getattr(_thread_coordinator, "value", None)
    if own is not None:
        own.maybe_switch()
        return
    coordinator = _active
    if coordinator is not None:
        coordinator.maybe_switch()


@contextmanager
def blaster_scope(origin):
    """Temporarily install `origin`'s blaster over the ambient one — the
    per-QUERY seam get_models_batch uses during a mixed window flush,
    where one baton holder prepares several origins' queries: blasting a
    sibling contract's terms into the flusher's AIG would re-couple the
    id spaces the per-origin blasters exist to keep apart. No-op outside
    the coordinator, for untagged queries, and when `origin` already
    holds the baton."""
    if _active is None or origin is None or origin == current_origin():
        yield
        return
    from mythril_tpu.smt.solver import frontend

    saved = (frontend._global_blaster, frontend._global_blaster_generation)
    tenancy.install_blaster(origin)
    try:
        yield
    finally:
        tenancy.stash_blaster(origin)
        (frontend._global_blaster,
         frontend._global_blaster_generation) = saved


class Coordinator:
    """Cooperative round-robin scheduler over N analysis slots.

    Exactly one slot holds the baton (self._current); the rest wait on
    the shared condition. All queue/flag state is guarded by the
    condition; engine-context save/restore runs inside the handoff while
    the world is stopped (the old holder has not released the baton yet,
    the new holder has not started), so the swap itself needs no extra
    locking."""

    def __init__(self, tasks, quantum: Optional[int] = None,
                 origins: Optional[List[str]] = None, warm: bool = False,
                 module_templates=None):
        """`tasks`: list of (idx, contract) in corpus order. Origin tags
        are minted here (index-qualified — corpus contracts loaded from
        bytecode all share the name MAIN) unless the caller supplies its
        own `origins` (parallel to `tasks` — the serve daemon mints
        tenant-qualified tags). `warm=True` preserves each origin's
        solve memos across runs (EngineContext.install_fresh
        preserve_caches — the serve daemon's cross-request reuse);
        `module_templates` reuses a caller-captured pristine module
        snapshot instead of capturing at construction (the serve daemon
        captures ONCE at startup so batch N's templates cannot carry
        batch N-1's module state)."""
        from mythril_tpu.support.env import env_float as _env_float

        self._cond = threading.Condition()
        self._warm = warm
        if origins is not None:
            self._tasks = deque(
                (idx, contract, origin)
                for (idx, contract), origin in zip(tasks, origins))
        else:
            self._tasks = deque(
                (idx, contract, f"{idx}:{getattr(contract, 'name', '?')}")
                for idx, contract in tasks)
        self._waitq: deque = deque()
        self._live = set()
        self._current: Optional[int] = None
        self._contexts = {}          # slot id -> _EngineContext or None
        self._wants_flush = set()    # slots parked awaiting a window flush
        self._parked_handles = {}    # slot id -> handles it is parked on
        self._tls = threading.local()
        self._current_origin: Optional[str] = None
        self._ticks = 0
        self._cancelled = False
        self.quantum = max(1, int(quantum if quantum is not None
                                  else _env_float(
                                      "MYTHRIL_TPU_INTERLEAVE_QUANTUM",
                                      DEFAULT_QUANTUM)))
        self._module_templates = (module_templates if module_templates
                                  is not None
                                  else tenancy.capture_module_templates())
        # the pre-driver module globals, restored by uninstall() so the
        # process's later origin-less work sees its own caches again
        from mythril_tpu.support import model as model_mod

        self._base_model_state = (model_mod._result_cache,
                                  model_mod.model_cache,
                                  model_mod._in_detection_context)

    # -- slot lifecycle ------------------------------------------------------

    def run_slot(self, slot_id: int, analyze_one) -> None:
        """Slot thread main: claim the baton, then loop over corpus
        tasks — fresh engine context per contract, a fairness yield
        between contracts. `analyze_one(idx, contract)` is the driver's
        per-contract closure (it must not raise; core's
        _analyze_one_contract captures exceptions per contract)."""
        self._attach(slot_id)
        try:
            while True:
                if not self._tasks:
                    return
                idx, contract, origin = self._tasks.popleft()
                context = _EngineContext(origin, self._module_templates)
                with self._cond:
                    self._contexts[slot_id] = context
                context.install_fresh(preserve_caches=self._warm)
                self._current_origin = origin
                self._ticks = 0
                try:
                    analyze_one(idx, contract)
                finally:
                    if self._warm and not self._cancelled:
                        # warm drivers (serve): the origin's final
                        # blaster state must survive task completion —
                        # handoffs stash it, but the LAST holder exits
                        # here without one. NEVER on cancellation: a
                        # slot unwinding from an off-baton wait would
                        # stash whichever SIBLING origin's blaster is
                        # live in the globals under ITS origin —
                        # cross-tenant id-space poisoning
                        tenancy.stash_blaster(origin)
                    with self._cond:
                        self._contexts[slot_id] = None
                    self._current_origin = None
                # rotate between contracts so one slot cannot drain the
                # whole task queue while siblings wait
                self._handoff(ready_only=True)
        finally:
            self._detach(slot_id)

    def _attach(self, slot_id: int) -> None:
        self._tls.slot = slot_id
        _thread_coordinator.value = self
        with self._cond:
            self._live.add(slot_id)
            if self._current is None:
                self._current = slot_id
                return
            self._waitq.append(slot_id)
            while self._current != slot_id:
                self._check_cancelled()
                self._cond.wait()
            self._check_cancelled()
            self._restore(slot_id)

    def _detach(self, slot_id: int) -> None:
        _thread_coordinator.value = None
        with self._cond:
            self._live.discard(slot_id)
            self._wants_flush.discard(slot_id)
            self._parked_handles.pop(slot_id, None)
            if self._current == slot_id:
                self._current = None
                if self._waitq:
                    # any waiter may run next — a flush-parked slot that
                    # wakes with no ready siblings flushes for itself
                    self._current = self._waitq.popleft()
                    self._cond.notify_all()

    # -- baton handoff -------------------------------------------------------

    def _pick_next(self, ready_only: bool) -> Optional[int]:
        """Pop the next runnable slot off the wait queue (caller holds
        the condition). ready_only skips flush-parked slots — handing
        them the baton before their window flushed would just bounce it
        back — UNLESS their parked handles have since resolved (a
        sibling's flush, or a count/age-triggered one, already carried
        their queries): those slots can make progress again."""
        for _ in range(len(self._waitq)):
            candidate = self._waitq.popleft()
            if ready_only and candidate in self._wants_flush \
                    and not all(handle.done for handle in
                                self._parked_handles.get(candidate, ())):
                self._waitq.append(candidate)
                continue
            return candidate
        return None

    def _handoff(self, ready_only: bool) -> bool:
        """Give the baton to the next runnable slot and wait to be
        rescheduled. Returns False (without switching) when no eligible
        slot is waiting. Caller must hold the baton."""
        me = self._tls.slot
        with self._cond:
            next_id = self._pick_next(ready_only)
            if next_id is None:
                return False
            self._save(me)
            self._waitq.append(me)
            self._current = next_id
            self._cond.notify_all()
            while self._current != me:
                self._check_cancelled()
                self._cond.wait()
            self._check_cancelled()
            self._restore(me)
        return True

    def _save(self, slot_id: int) -> None:
        context = self._contexts.get(slot_id)
        if context is not None:
            context.save()
        self._current_origin = None

    def _restore(self, slot_id: int) -> None:
        context = self._contexts.get(slot_id)
        if context is not None:
            context.restore()
            self._current_origin = context.origin
        else:
            self._current_origin = None
        self._ticks = 0

    def cancel(self) -> None:
        """Abandon every slot thread: each raises BatchCancelled at its
        next yield point (quantum tick, handoff wait, or solve park).
        The serve daemon calls this when a batch blows its hard
        deadline, so the abandoned threads stop mutating the engine
        globals instead of racing the requeued batch over them."""
        with self._cond:
            self._cancelled = True
            self._cond.notify_all()

    def _check_cancelled(self) -> None:
        if self._cancelled:
            raise BatchCancelled(
                "this analysis batch was abandoned by its driver")

    def maybe_switch(self) -> None:
        """Quantum yield point (module-level tick()). Only the baton
        holder executes engine code, so no lock is needed for the tick
        counter itself. A thread with NO slot on this coordinator is an
        abandoned sibling from a cancelled predecessor still running
        engine code — it dies here, before it can touch the handoff
        machinery it never attached to."""
        self._check_cancelled()
        if getattr(self._tls, "slot", None) is None:
            raise BatchCancelled(
                "engine thread is not a slot of the live coordinator")
        self._ticks += 1
        if self._ticks < self.quantum:
            return
        self._ticks = 0
        self._handoff(ready_only=True)

    # -- solve-seam parking (service/scheduler.py) ---------------------------

    def park_for_results(self, scheduler, handles: List) -> None:
        """An analysis buffered a sibling-query bundle: instead of
        demanding an immediate flush (which would make every window
        single-origin), park and let other analyses run up to THEIR
        solve seams. When no sibling can make engine progress — all
        parked or none left — whoever holds the baton flushes the
        window, which now carries every parked origin's queries: the
        cross-contract mixed window the ragged stream packs as one
        launch."""
        me = self._tls.slot
        while True:
            self._check_cancelled()
            if all(handle.done for handle in handles):
                return
            with self._cond:
                self._wants_flush.add(me)
                self._parked_handles[me] = handles
            try:
                switched = self._handoff(ready_only=True)
            finally:
                with self._cond:
                    self._wants_flush.discard(me)
                    self._parked_handles.pop(me, None)
            if not switched:
                # nobody else can progress: this window is as mixed as
                # it is going to get — flush it ourselves
                self._flush_safely(scheduler, handles)

    @staticmethod
    def _flush_safely(scheduler, handles) -> None:
        """Flush the shared window; a flush that dies wholesale (beyond
        the per-query isolation scheduler._solve_group already provides)
        must still resolve every parked origin's handles — an unresolved
        handle would deadlock a SIBLING contract's analysis, which is
        exactly the cross-origin fault leak the interleaved driver must
        never allow. Leftovers degrade to unknown (possibly feasible):
        precision on this window, never a missed finding, never a stuck
        sibling."""
        try:
            scheduler.flush()
        except Exception:
            log.exception("interleaved window flush failed; degrading "
                          "unresolved handles to unknown")
            from mythril_tpu import resilience

            resilience.record_event("scheduler.flush", "degraded")
            scheduler.clear()


_install_lock = threading.Lock()


def install(coordinator: Coordinator) -> None:
    global _active
    with _install_lock:
        _active = coordinator


def uninstall(keep_tenancy: bool = False,
              expected: Optional[Coordinator] = None) -> None:
    """Tear the coordinator down. `keep_tenancy=True` (the serve daemon,
    between request batches) keeps the per-origin blaster registry and
    memory tiers alive so the next batch starts WARM; the corpus driver
    clears them — its origins never recur. `expected` makes the
    teardown a compare-and-swap: an ABANDONED batch body unwinding late
    must not pop a successor batch's freshly installed coordinator (the
    check and the swap are atomic under one lock — a bare is-active
    check before calling would race the successor's install)."""
    global _active
    with _install_lock:
        if expected is not None and _active is not expected:
            return
        coordinator, _active = _active, None
    if not keep_tenancy:
        tenancy.clear_blasters()
    if coordinator is None:
        return
    from mythril_tpu.smt.solver import frontend
    from mythril_tpu.support import model as model_mod

    (model_mod._result_cache, model_mod.model_cache,
     model_mod._in_detection_context) = coordinator._base_model_state
    # the next origin-less solve starts on a fresh process-wide blaster
    # rather than the last origin's private one
    frontend._global_blaster = None
    frontend._global_blaster_generation = -1

"""Interleaved corpus analysis: N contracts' analyses coexist in one
process so their sibling solve queries can share ONE device stream.

Why this exists: every device launch used to pack cones from exactly one
contract's coalescing window, so corpus throughput was bounded by the
per-contract query arrival rate rather than device occupancy — while
nothing in the ragged paged layout (tpu/circuit.RaggedStream) requires
cones to share a parent query, let alone a parent contract. The missing
piece was a driver that makes queries from DIFFERENT contracts coexist
in time. This module is that driver's machinery:

  baton        N analyses run on N threads, but only ONE thread executes
               at any instant — a baton (condition variable + current
               slot id) is handed off cooperatively at explicit yield
               points. The engine's process-global state (term intern
               table, shared blaster AIG, module singletons, solver
               caches) is therefore never mutated concurrently: the
               scheduling is cooperative round-robin, not parallelism.
               The win is windows that MIX origins, not CPU overlap.
  yield points (a) every `quantum` exec-loop iterations (laser/svm.py
               calls tick() — fairness: a stress_dispatch-class contract
               cannot starve 2 s contracts of engine time), and (b) the
               coalescing scheduler's solve seam: an analysis whose
               sibling-query bundle was buffered PARKS instead of
               demanding a flush, the baton passes to another analysis,
               and only when every live analysis is parked (or none can
               make progress) does the window flush — carrying queries
               from every parked origin in ONE batched router dispatch.
  contexts     the per-analysis slices of process-global engine state
               are context-switched at every handoff: the wall-clock
               budget (paused while the origin is off-baton), the tx-id
               counter, the keccak/exponent function managers, every
               detection module's issue/cache state, the in-memory
               result tier + quick-sat model deque (per-origin — the
               cross-contract reuse boundary is the content-addressed
               persistent tier, whose replay-verified fingerprints are
               origin-blind by design), and the ambient
               detection-context flag. Isolation is what makes
               per-contract findings independent of the schedule: the
               interleaved run's findings are byte-identical to the
               sequential (interleave=1) run's.

Knobs: MYTHRIL_TPU_CORPUS_INTERLEAVE / --corpus-interleave selects the
driver (core.MythrilAnalyzer._fire_lasers_interleaved);
MYTHRIL_TPU_INTERLEAVE_QUANTUM sets the exec iterations per turn.
"""

import copy
import logging
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import List, Optional

log = logging.getLogger(__name__)

DEFAULT_QUANTUM = 16  # exec-loop iterations per baton turn

_active: Optional["Coordinator"] = None

# origin -> (Blaster or None, term generation): each contract's private
# blaster/AIG. The shared strashed AIG assigns node ids in first-use
# order and the dense CNF sorts by id, so a process-wide blaster makes
# the CDCL's branching — and hence which valid witness model it returns
# — depend on which sibling contract blasted a common subterm first.
# Per-origin blasters reproduce the solo-process id space exactly: the
# property that makes interleaved findings BYTE-identical to the
# sequential schedule. (None = lazily recreated on first use.)
_blasters: dict = {}


def active() -> Optional["Coordinator"]:
    """The live coordinator, or None outside an interleaved corpus run."""
    return _active


def current_origin() -> Optional[str]:
    """Origin tag (contract identity) of the analysis holding the baton.
    None outside an interleaved run — single-contract invocations and
    the legacy sequential path are origin-less by construction."""
    coordinator = _active
    return coordinator._current_origin if coordinator is not None else None


def tick() -> None:
    """Exec-loop yield point (laser/svm.py): hand the baton to the next
    runnable analysis every `quantum` iterations. One global load + a
    None check when no coordinator is live — the cost discipline every
    always-on crossing in this codebase follows."""
    coordinator = _active
    if coordinator is not None:
        coordinator.maybe_switch()


def _install_blaster(origin) -> None:
    from mythril_tpu.smt.solver import frontend

    (frontend._global_blaster,
     frontend._global_blaster_generation) = _blasters.get(origin,
                                                          (None, -1))


def _stash_blaster(origin) -> None:
    from mythril_tpu.smt.solver import frontend

    _blasters[origin] = (frontend._global_blaster,
                         frontend._global_blaster_generation)


@contextmanager
def blaster_scope(origin):
    """Temporarily install `origin`'s blaster over the ambient one — the
    per-QUERY seam get_models_batch uses during a mixed window flush,
    where one baton holder prepares several origins' queries: blasting a
    sibling contract's terms into the flusher's AIG would re-couple the
    id spaces the per-origin blasters exist to keep apart. No-op outside
    the coordinator, for untagged queries, and when `origin` already
    holds the baton."""
    if _active is None or origin is None or origin == current_origin():
        yield
        return
    from mythril_tpu.smt.solver import frontend

    saved = (frontend._global_blaster, frontend._global_blaster_generation)
    _install_blaster(origin)
    try:
        yield
    finally:
        _stash_blaster(origin)
        (frontend._global_blaster,
         frontend._global_blaster_generation) = saved


class _EngineContext:
    """One origin's slice of the process-global engine state.

    install_fresh() gives a starting analysis pristine state (the same
    state a solo-process analysis of the contract would see); save()
    captures the live globals when the origin loses the baton; restore()
    reinstalls them when it gets the baton back. State swapped by
    object-identity-preserving `__dict__` replacement where the global
    is a singleton other modules hold references to (function managers,
    detection modules), and by module-attribute rebinding where call
    sites re-read the attribute (support.model's memory tiers)."""

    def __init__(self, origin: str, module_templates):
        self.origin = origin
        self._templates = module_templates
        self._saved = None

    @staticmethod
    def capture_module_templates():
        """Pristine per-module state snapshots, taken once at driver
        start (right after core.fire_lasers reset every module): each
        origin's fresh install copies from these, so a module attribute
        added mid-run by one origin can never leak into another's."""
        from mythril_tpu.analysis.module import ModuleLoader

        return [
            (module, {key: copy.copy(value)
                      for key, value in module.__dict__.items()})
            for module in ModuleLoader().get_detection_modules()
        ]

    def install_fresh(self) -> None:
        from mythril_tpu.laser.function_managers import (
            exponent_function_manager,
            keccak_function_manager,
        )
        from mythril_tpu.laser.transaction.models import tx_id_manager
        from mythril_tpu.smt.solver import frontend
        from mythril_tpu.support import model as model_mod
        from mythril_tpu.support.time_handler import time_handler

        time_handler._start = None
        time_handler._timeout = None
        tx_id_manager._next = 0
        # fresh per-origin blaster (see the _blasters registry note): a
        # starting contract gets an empty AIG, exactly like a solo
        # process (None = lazily recreated on first use)
        _blasters[self.origin] = (None, -1)
        frontend._global_blaster = None
        frontend._global_blaster_generation = -1
        keccak_function_manager.__dict__ = (
            type(keccak_function_manager)().__dict__)
        exponent_function_manager.__dict__ = (
            type(exponent_function_manager)().__dict__)
        for module, template in self._templates:
            module.__dict__ = {key: copy.copy(value)
                               for key, value in template.items()}
        # the origin's memory tiers live in model.py's per-origin
        # registry (get_models_batch resolves them PER QUERY during
        # mixed flushes); installing them into the module globals serves
        # the ambient call sites — get_model, the engine's direct
        # quick-sat probes — while this origin holds the baton. Starting
        # a contract drops any stale registry pair so each analysis
        # starts as cold as a solo process would.
        model_mod._origin_caches.pop(self.origin, None)
        tier, quick_cache = model_mod.caches_for_origin(self.origin)
        model_mod._result_cache = tier
        model_mod.model_cache = quick_cache
        model_mod._in_detection_context = False

    def save(self) -> None:
        from mythril_tpu.laser.function_managers import (
            exponent_function_manager,
            keccak_function_manager,
        )
        from mythril_tpu.laser.transaction.models import tx_id_manager
        from mythril_tpu.support import model as model_mod
        from mythril_tpu.support.time_handler import time_handler

        # the execution-timeout clock PAUSES while the origin is
        # off-baton: store elapsed-so-far, not the absolute start, so a
        # contract's budget measures its own engine time — siblings'
        # quanta must not burn it (and must not make the interleaved
        # run's timeout behavior diverge from the sequential run's)
        elapsed = (time.monotonic() - time_handler._start
                   if time_handler._start is not None else None)
        _stash_blaster(self.origin)
        self._saved = {
            "time": (elapsed, time_handler._timeout),
            "txid": tx_id_manager._next,
            "keccak": keccak_function_manager.__dict__,
            "exponent": exponent_function_manager.__dict__,
            "modules": [module.__dict__ for module, _t in self._templates],
            "result_cache": model_mod._result_cache,
            "model_cache": model_mod.model_cache,
            "detection": model_mod._in_detection_context,
        }

    def restore(self) -> None:
        from mythril_tpu.laser.function_managers import (
            exponent_function_manager,
            keccak_function_manager,
        )
        from mythril_tpu.laser.transaction.models import tx_id_manager
        from mythril_tpu.support import model as model_mod
        from mythril_tpu.support.time_handler import time_handler

        saved = self._saved
        self._saved = None
        elapsed, timeout = saved["time"]
        time_handler._timeout = timeout
        time_handler._start = (time.monotonic() - elapsed
                               if elapsed is not None else None)
        tx_id_manager._next = saved["txid"]
        _install_blaster(self.origin)
        keccak_function_manager.__dict__ = saved["keccak"]
        exponent_function_manager.__dict__ = saved["exponent"]
        for (module, _t), state in zip(self._templates, saved["modules"]):
            module.__dict__ = state
        model_mod._result_cache = saved["result_cache"]
        model_mod.model_cache = saved["model_cache"]
        model_mod._in_detection_context = saved["detection"]


class Coordinator:
    """Cooperative round-robin scheduler over N analysis slots.

    Exactly one slot holds the baton (self._current); the rest wait on
    the shared condition. All queue/flag state is guarded by the
    condition; engine-context save/restore runs inside the handoff while
    the world is stopped (the old holder has not released the baton yet,
    the new holder has not started), so the swap itself needs no extra
    locking."""

    def __init__(self, tasks, quantum: Optional[int] = None):
        """`tasks`: list of (idx, contract) in corpus order. Origin tags
        are minted here (index-qualified — corpus contracts loaded from
        bytecode all share the name MAIN)."""
        from mythril_tpu.support.env import env_float as _env_float

        self._cond = threading.Condition()
        self._tasks = deque(
            (idx, contract, f"{idx}:{getattr(contract, 'name', '?')}")
            for idx, contract in tasks)
        self._waitq: deque = deque()
        self._live = set()
        self._current: Optional[int] = None
        self._contexts = {}          # slot id -> _EngineContext or None
        self._wants_flush = set()    # slots parked awaiting a window flush
        self._parked_handles = {}    # slot id -> handles it is parked on
        self._tls = threading.local()
        self._current_origin: Optional[str] = None
        self._ticks = 0
        self.quantum = max(1, int(quantum if quantum is not None
                                  else _env_float(
                                      "MYTHRIL_TPU_INTERLEAVE_QUANTUM",
                                      DEFAULT_QUANTUM)))
        self._module_templates = _EngineContext.capture_module_templates()
        # the pre-driver module globals, restored by uninstall() so the
        # process's later origin-less work sees its own caches again
        from mythril_tpu.support import model as model_mod

        self._base_model_state = (model_mod._result_cache,
                                  model_mod.model_cache,
                                  model_mod._in_detection_context)

    # -- slot lifecycle ------------------------------------------------------

    def run_slot(self, slot_id: int, analyze_one) -> None:
        """Slot thread main: claim the baton, then loop over corpus
        tasks — fresh engine context per contract, a fairness yield
        between contracts. `analyze_one(idx, contract)` is the driver's
        per-contract closure (it must not raise; core's
        _analyze_one_contract captures exceptions per contract)."""
        self._attach(slot_id)
        try:
            while True:
                if not self._tasks:
                    return
                idx, contract, origin = self._tasks.popleft()
                context = _EngineContext(origin, self._module_templates)
                with self._cond:
                    self._contexts[slot_id] = context
                context.install_fresh()
                self._current_origin = origin
                self._ticks = 0
                try:
                    analyze_one(idx, contract)
                finally:
                    with self._cond:
                        self._contexts[slot_id] = None
                    self._current_origin = None
                # rotate between contracts so one slot cannot drain the
                # whole task queue while siblings wait
                self._handoff(ready_only=True)
        finally:
            self._detach(slot_id)

    def _attach(self, slot_id: int) -> None:
        self._tls.slot = slot_id
        with self._cond:
            self._live.add(slot_id)
            if self._current is None:
                self._current = slot_id
                return
            self._waitq.append(slot_id)
            while self._current != slot_id:
                self._cond.wait()
            self._restore(slot_id)

    def _detach(self, slot_id: int) -> None:
        with self._cond:
            self._live.discard(slot_id)
            self._wants_flush.discard(slot_id)
            self._parked_handles.pop(slot_id, None)
            if self._current == slot_id:
                self._current = None
                if self._waitq:
                    # any waiter may run next — a flush-parked slot that
                    # wakes with no ready siblings flushes for itself
                    self._current = self._waitq.popleft()
                    self._cond.notify_all()

    # -- baton handoff -------------------------------------------------------

    def _pick_next(self, ready_only: bool) -> Optional[int]:
        """Pop the next runnable slot off the wait queue (caller holds
        the condition). ready_only skips flush-parked slots — handing
        them the baton before their window flushed would just bounce it
        back — UNLESS their parked handles have since resolved (a
        sibling's flush, or a count/age-triggered one, already carried
        their queries): those slots can make progress again."""
        for _ in range(len(self._waitq)):
            candidate = self._waitq.popleft()
            if ready_only and candidate in self._wants_flush \
                    and not all(handle.done for handle in
                                self._parked_handles.get(candidate, ())):
                self._waitq.append(candidate)
                continue
            return candidate
        return None

    def _handoff(self, ready_only: bool) -> bool:
        """Give the baton to the next runnable slot and wait to be
        rescheduled. Returns False (without switching) when no eligible
        slot is waiting. Caller must hold the baton."""
        me = self._tls.slot
        with self._cond:
            next_id = self._pick_next(ready_only)
            if next_id is None:
                return False
            self._save(me)
            self._waitq.append(me)
            self._current = next_id
            self._cond.notify_all()
            while self._current != me:
                self._cond.wait()
            self._restore(me)
        return True

    def _save(self, slot_id: int) -> None:
        context = self._contexts.get(slot_id)
        if context is not None:
            context.save()
        self._current_origin = None

    def _restore(self, slot_id: int) -> None:
        context = self._contexts.get(slot_id)
        if context is not None:
            context.restore()
            self._current_origin = context.origin
        else:
            self._current_origin = None
        self._ticks = 0

    def maybe_switch(self) -> None:
        """Quantum yield point (module-level tick()). Only the baton
        holder executes engine code, so no lock is needed for the tick
        counter itself."""
        self._ticks += 1
        if self._ticks < self.quantum:
            return
        self._ticks = 0
        self._handoff(ready_only=True)

    # -- solve-seam parking (service/scheduler.py) ---------------------------

    def park_for_results(self, scheduler, handles: List) -> None:
        """An analysis buffered a sibling-query bundle: instead of
        demanding an immediate flush (which would make every window
        single-origin), park and let other analyses run up to THEIR
        solve seams. When no sibling can make engine progress — all
        parked or none left — whoever holds the baton flushes the
        window, which now carries every parked origin's queries: the
        cross-contract mixed window the ragged stream packs as one
        launch."""
        me = self._tls.slot
        while True:
            if all(handle.done for handle in handles):
                return
            with self._cond:
                self._wants_flush.add(me)
                self._parked_handles[me] = handles
            try:
                switched = self._handoff(ready_only=True)
            finally:
                with self._cond:
                    self._wants_flush.discard(me)
                    self._parked_handles.pop(me, None)
            if not switched:
                # nobody else can progress: this window is as mixed as
                # it is going to get — flush it ourselves
                self._flush_safely(scheduler, handles)

    @staticmethod
    def _flush_safely(scheduler, handles) -> None:
        """Flush the shared window; a flush that dies wholesale (beyond
        the per-query isolation scheduler._solve_group already provides)
        must still resolve every parked origin's handles — an unresolved
        handle would deadlock a SIBLING contract's analysis, which is
        exactly the cross-origin fault leak the interleaved driver must
        never allow. Leftovers degrade to unknown (possibly feasible):
        precision on this window, never a missed finding, never a stuck
        sibling."""
        try:
            scheduler.flush()
        except Exception:
            log.exception("interleaved window flush failed; degrading "
                          "unresolved handles to unknown")
            from mythril_tpu import resilience

            resilience.record_event("scheduler.flush", "degraded")
            scheduler.clear()


def install(coordinator: Coordinator) -> None:
    global _active
    _active = coordinator


def uninstall() -> None:
    global _active
    coordinator, _active = _active, None
    _blasters.clear()
    if coordinator is None:
        return
    from mythril_tpu.smt.solver import frontend
    from mythril_tpu.support import model as model_mod

    (model_mod._result_cache, model_mod.model_cache,
     model_mod._in_detection_context) = coordinator._base_model_state
    # the next origin-less solve starts on a fresh process-wide blaster
    # rather than the last origin's private one
    frontend._global_blaster = None
    frontend._global_blaster_generation = -1

"""Persistent on-disk solve-result tier.

One JSON file per entry under <MYTHRIL_TPU_CACHE_DIR>/solve-cache, named
by the instance fingerprint (fingerprint.py). The store is shared across
--jobs worker processes and repeated CLI invocations:

  writes    temp-file + atomic rename under a file lock (support/lock.py),
            so concurrent workers never observe a torn entry
  reads     lock-free (rename is atomic); a hit touches the entry's mtime,
            which is the LRU recency signal
  eviction  size-capped two ways, both LRU by mtime and enforced under
            the lock after every write: by entry count
            (MYTHRIL_TPU_CACHE_MAX_ENTRIES, default 4096) and by total
            byte size (MYTHRIL_TPU_CACHE_MAX_BYTES, default unlimited) —
            oldest entries are unlinked until both caps hold, so a few
            mega-assignment SAT entries cannot silently blow the disk
            budget the entry-count cap was sized for
  schema    a VERSION stamp file; a mismatch (new code, old store) wipes
            every entry instead of trusting stale formats

Entry trust model:
  corrupt  any malformed entry (truncated write, garbage bytes, wrong
         schema stamp, undecodable blob) is QUARANTINED on lookup —
         moved to a `.quarantined` sibling, counted as a
         persistent_verify_reject + a resilience quarantine event — and
         the lookup degrades to a safe miss (the oracle recomputes)
  SAT    stores the satisfying assignment bits (packed, base64). A hit is
         NEVER trusted as-is — the caller replays the bits through
         Solver._reconstruct, which validates the rebuilt model against
         the ORIGINAL constraints, so a fingerprint collision or a
         corrupted file degrades to a safe miss, not a wrong verdict.
  UNSAT  stores crosscheck provenance (did the verdict carry the
         permuted-instance second opinion?). Detection-path lookups only
         trust provenance-carrying entries; engine-path lookups (where a
         wrong prune costs coverage, not a false "safe") trust either.
"""

import base64
import json
import logging
import os
import tempfile
from typing import List, Optional

from mythril_tpu.support.lock import LockFile

log = logging.getLogger(__name__)

STORE_SCHEMA_VERSION = 1
DEFAULT_MAX_ENTRIES = 4096
# assignments for cones past this many CNF vars are not worth the disk
# traffic (125 KB+ per entry); the memory tier still serves them in-process
STORE_VAR_CAP = 1 << 20


def _default_root() -> str:
    from mythril_tpu.service import cache_dir

    return os.path.join(cache_dir(), "solve-cache")


def atomic_write_json(path: str, payload: dict) -> bool:
    """Temp-file + atomic-rename JSON write in `path`'s directory (the
    caller holds whatever lock the destination needs). Shared by the
    result store and the calibration cache."""
    fd, tmp_path = tempfile.mkstemp(dir=os.path.dirname(path),
                                    suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as handle:
            json.dump(payload, handle)
        os.replace(tmp_path, path)
        return True
    except OSError:
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        return False


class StoreEntry:
    __slots__ = ("verdict", "bits", "num_vars", "crosschecked")

    def __init__(self, verdict: str, bits=None, num_vars: int = 0,
                 crosschecked: bool = False):
        self.verdict = verdict
        self.bits = bits
        self.num_vars = num_vars
        self.crosschecked = crosschecked


def _pack_bits(bits: List[bool]) -> str:
    import numpy as np

    packed = np.packbits(np.asarray(bits, dtype=bool))
    return base64.b64encode(packed.tobytes()).decode("ascii")


def _unpack_bits(blob: str, num_vars: int) -> Optional[List[bool]]:
    import numpy as np

    try:
        raw = base64.b64decode(blob.encode("ascii"), validate=True)
    except (ValueError, AttributeError):
        return None
    unpacked = np.unpackbits(np.frombuffer(raw, dtype=np.uint8))
    if len(unpacked) < num_vars + 1:
        return None
    return unpacked[: num_vars + 1].astype(bool).tolist()


class PersistentResultStore:
    """File-per-entry result store; every method is total (I/O failures
    degrade to miss/no-op — the store must never break a solve)."""

    # the shared network tier (fleet/netstore.py) subclasses this with
    # is_network=True and its own fault site; model.py keys the
    # net_tier_* counters off the flag so fleet-wide hits/stores are
    # visible separately from a private local disk tier
    is_network = False
    entry_site = "disk.entry"

    def __init__(self, root: Optional[str] = None,
                 max_entries: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        self.root = root or _default_root()
        if max_entries is None:
            try:
                max_entries = int(
                    os.environ.get("MYTHRIL_TPU_CACHE_MAX_ENTRIES", ""))
            except ValueError:
                max_entries = 0
        self.max_entries = max_entries if max_entries and max_entries > 0 \
            else DEFAULT_MAX_ENTRIES
        if max_bytes is None:
            try:
                max_bytes = int(
                    os.environ.get("MYTHRIL_TPU_CACHE_MAX_BYTES", ""))
            except ValueError:
                max_bytes = 0
        # 0 = no byte cap (the entry-count cap still applies)
        self.max_bytes = max_bytes if max_bytes and max_bytes > 0 else 0
        # approximate local entry count/bytes: full directory scans per
        # write would serialize --jobs workers behind O(entries) stats
        # under the store lock; both are re-synced periodically to bound
        # drift from sibling workers' writes
        self._approx_count: Optional[int] = None
        self._approx_bytes: Optional[int] = None
        self._writes_since_sync = 0
        self._ok = self._bootstrap()

    # -- lifecycle ----------------------------------------------------------

    def _lock(self) -> LockFile:
        return LockFile(os.path.join(self.root, ".lock"))

    def _bootstrap(self) -> bool:
        try:
            os.makedirs(self.root, exist_ok=True)
            stamp = os.path.join(self.root, "VERSION")
            want = str(STORE_SCHEMA_VERSION)
            current = None
            try:
                with open(stamp) as fd:
                    current = fd.read().strip()
            except OSError:
                pass
            if current == want:
                return True
            with self._lock():
                # re-read under the lock: a sibling worker may have
                # restamped while this one waited
                try:
                    with open(stamp) as fd:
                        if fd.read().strip() == want:
                            return True
                except OSError:
                    pass
                for name in os.listdir(self.root):
                    if name.endswith(".json") \
                            or name.endswith(".quarantined"):
                        try:
                            os.unlink(os.path.join(self.root, name))
                        except OSError:
                            pass
                with open(stamp, "w") as fd:
                    fd.write(want)
            return True
        except OSError as error:
            log.warning("persistent solve store unavailable at %s (%s); "
                        "running memory-only", self.root, error)
            return False

    @property
    def available(self) -> bool:
        return self._ok

    def _path(self, fingerprint: str) -> str:
        return os.path.join(self.root, fingerprint + ".json")

    # -- reads --------------------------------------------------------------

    def lookup(self, fingerprint: str) -> Optional[StoreEntry]:
        """Read one entry; every malformed entry (truncated write,
        garbage bytes, wrong schema stamp, undecodable assignment blob)
        is QUARANTINED — moved aside so it is never re-read — counted as
        a persistent_verify_reject, and the lookup proceeds as a safe
        miss. A missing file is a plain miss (nothing to quarantine)."""
        if not self._ok or not fingerprint:
            return None
        path = self._path(fingerprint)
        try:
            with open(path) as fd:
                text = fd.read()
        except OSError:
            return None  # no entry: plain miss
        from mythril_tpu.resilience import InjectedFault

        try:
            payload = json.loads(self._entry_guard(text))
        except (InjectedFault, ValueError):
            return self._quarantine(path, "unparseable entry")
        if not isinstance(payload, dict) \
                or payload.get("schema") != STORE_SCHEMA_VERSION:
            return self._quarantine(path, "wrong schema stamp")
        verdict = payload.get("verdict")
        if verdict == "sat":
            num_vars = payload.get("num_vars")
            blob = payload.get("bits")
            if not isinstance(num_vars, int) or not isinstance(blob, str):
                return self._quarantine(path, "malformed sat payload")
            bits = _unpack_bits(blob, num_vars)
            if bits is None:
                return self._quarantine(path, "undecodable assignment")
            entry = StoreEntry("sat", bits=bits, num_vars=num_vars)
        elif verdict == "unsat":
            entry = StoreEntry(
                "unsat", crosschecked=bool(payload.get("crosschecked")))
        else:
            return self._quarantine(path, "unknown verdict")
        try:
            os.utime(path, None)  # LRU recency
        except OSError:
            pass
        return entry

    def _entry_guard(self, text: str) -> str:
        """Fault-harness crossing on the entry read path. The site name
        stays a LITERAL (the check_fault_sites wiring lint matches
        literal strings only); the network-tier subclass overrides with
        its own literal site (netstore.entry)."""
        from mythril_tpu.resilience import corrupt_text, maybe_inject

        maybe_inject("disk.entry")
        return corrupt_text("disk.entry", text)

    # quarantined corpses kept for forensics; beyond this the oldest are
    # dropped — a recurring corruption source (flaky disk, mixed-version
    # writers) must not grow the cache dir past its caps through files
    # the eviction sweep does not see
    _QUARANTINE_KEEP = 32

    def _quarantine(self, path: str, reason: str) -> None:
        """Move a corrupt/unverifiable entry aside (never re-read; the
        newest _QUARANTINE_KEEP are kept for forensics — the
        `.quarantined` suffix excludes them from lookups, counts and
        eviction) and degrade to a safe miss. The oracle recomputes the
        verdict; a corrupt entry can cost a solve, never a finding."""
        from mythril_tpu.resilience import record_event
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        log.warning("quarantining corrupt solve-cache entry %s (%s)",
                    os.path.basename(path), reason)
        stats = SolverStatistics()
        stats.add_persistent_verify_reject()
        if self.is_network:
            stats.add_net_tier_verify_reject()
        record_event(self.entry_site, "quarantine")
        try:
            os.replace(path, path + ".quarantined")
        except OSError:
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        self._prune_quarantined()
        return None

    def _prune_quarantined(self) -> None:
        """Drop the oldest quarantined corpses beyond the forensics cap
        (unlink races with sibling processes are benign: someone pruned)."""
        try:
            corpses = []
            for name in os.listdir(self.root):
                if not name.endswith(".quarantined"):
                    continue
                corpse = os.path.join(self.root, name)
                try:
                    corpses.append((os.path.getmtime(corpse), corpse))
                except OSError:
                    continue
            corpses.sort()
            for _mtime, corpse in corpses[:-self._QUARANTINE_KEEP]:
                try:
                    os.unlink(corpse)
                except OSError:
                    pass
        except OSError:
            pass

    # -- writes -------------------------------------------------------------

    def store_sat(self, fingerprint: str, num_vars: int,
                  bits: List[bool]) -> bool:
        if bits is None or num_vars > STORE_VAR_CAP:
            return False
        return self._write(fingerprint, {
            "schema": STORE_SCHEMA_VERSION,
            "verdict": "sat",
            "num_vars": num_vars,
            "bits": _pack_bits(bits),
        })

    def store_unsat(self, fingerprint: str, crosschecked: bool) -> bool:
        return self._write(fingerprint, {
            "schema": STORE_SCHEMA_VERSION,
            "verdict": "unsat",
            "crosschecked": bool(crosschecked),
        })

    _COUNT_SYNC_INTERVAL = 256

    def _write(self, fingerprint: str, payload: dict) -> bool:
        """Write one entry, retrying a transient IO failure once with
        jittered backoff (resilience registry: the disk.write fault
        site); a persistent failure degrades to not-persisted — reads
        simply re-solve, never a wrong verdict."""
        if not self._ok or not fingerprint:
            return False
        from mythril_tpu.resilience import record_event, with_retries

        try:
            return with_retries(
                "disk.write",
                lambda: self._write_locked(fingerprint, payload))
        except Exception:
            record_event("disk.write", "degraded")
            return False

    def _write_locked(self, fingerprint: str, payload: dict) -> bool:
        """One locked write attempt; RAISES on IO failure so the retry
        wrapper in _write sees it (the pre-resilience silent False made
        every transient failure permanent)."""
        from mythril_tpu.resilience import maybe_inject

        with self._lock():
            maybe_inject("disk.write")
            path = self._path(fingerprint)
            # overwrite of an existing fingerprint (e.g. a provenance
            # upgrade of an UNSAT entry) replaces, not adds: count the
            # old file out first or the approximations inflate and
            # trigger spurious O(entries) eviction scans under the lock
            old_size = None
            try:
                old_size = os.path.getsize(path)
            except OSError:
                pass
            if not atomic_write_json(path, payload):
                raise OSError("atomic entry write failed")
            if self._approx_count is None:
                self._approx_count = self.entry_count()
            elif old_size is None:
                self._approx_count += 1
            if self.max_bytes:
                if self._approx_bytes is None:
                    self._approx_bytes = self.total_bytes()
                else:
                    try:
                        self._approx_bytes += (
                            os.path.getsize(path) - (old_size or 0))
                    except OSError:
                        pass
            self._writes_since_sync += 1
            if self._writes_since_sync >= self._COUNT_SYNC_INTERVAL:
                # re-sync against sibling workers' writes
                self._approx_count = self.entry_count()
                if self.max_bytes:
                    self._approx_bytes = self.total_bytes()
                self._writes_since_sync = 0
            if self._approx_count > self.max_entries or (
                    self.max_bytes
                    and (self._approx_bytes or 0) > self.max_bytes):
                # eviction walks the directory once and returns the
                # exact post-eviction figures — re-scanning here would
                # triple the O(entries) stat sweeps under the lock
                self._approx_count, self._approx_bytes = \
                    self._evict_locked()
        return True

    def _evict_locked(self):
        """LRU eviction by mtime until BOTH caps hold (entry count, and —
        when configured — total bytes); caller holds the store lock. The
        most recent entry is never evicted: a byte cap smaller than one
        entry is a misconfiguration, and deleting the entry that was just
        written would make every write a no-op. Returns the exact
        post-eviction (entry count, total bytes) so the caller can refresh
        its approximations without another directory sweep."""
        try:
            stamped = []  # (mtime, size, path), oldest first
            total_size = 0
            for name in os.listdir(self.root):
                if not name.endswith(".json"):
                    continue
                path = os.path.join(self.root, name)
                try:
                    stat = os.stat(path)
                except OSError:
                    continue
                stamped.append((stat.st_mtime, stat.st_size, path))
                total_size += stat.st_size
            stamped.sort()
            count = len(stamped)
            for _mtime, size, path in stamped[:-1]:
                over_count = count > self.max_entries
                over_bytes = self.max_bytes and total_size > self.max_bytes
                if not over_count and not over_bytes:
                    break
                try:
                    os.unlink(path)
                except OSError:
                    continue
                count -= 1
                total_size -= size
            return count, total_size
        except OSError:
            return self._approx_count, self._approx_bytes

    def entry_count(self) -> int:
        if not self._ok:
            return 0
        try:
            return sum(1 for name in os.listdir(self.root)
                       if name.endswith(".json"))
        except OSError:
            return 0

    def total_bytes(self) -> int:
        """Sum of entry file sizes (the quantity MYTHRIL_TPU_CACHE_MAX_BYTES
        caps)."""
        if not self._ok:
            return 0
        total = 0
        try:
            for name in os.listdir(self.root):
                if not name.endswith(".json"):
                    continue
                try:
                    total += os.path.getsize(os.path.join(self.root, name))
                except OSError:
                    pass
        except OSError:
            return 0
        return total


_store: Optional[PersistentResultStore] = None


def get_result_store() -> PersistentResultStore:
    """Process-wide store handle (re-reads MYTHRIL_TPU_CACHE_DIR and
    MYTHRIL_TPU_NET_TIER_DIR on first access after reset_result_store).
    With a network-tier directory mounted, every shard in the fleet
    shares one object-store-style tier instead of a private disk tier —
    safe because entries are replay-verified on every hit."""
    global _store
    if _store is None:
        net_root = os.environ.get("MYTHRIL_TPU_NET_TIER_DIR")
        if net_root:
            # lazy import: fleet/ imports service/, not vice versa
            from mythril_tpu.fleet.netstore import NetworkResultStore

            _store = NetworkResultStore(net_root)
        else:
            _store = PersistentResultStore()
    return _store


def reset_result_store() -> None:
    global _store
    _store = None

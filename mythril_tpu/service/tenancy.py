"""Per-origin engine-context registry — the tenancy layer shared by the
two multi-analysis drivers (`--corpus-interleave` and `mythril_tpu
serve`).

PR 12 built this machinery inside service/interleave.py, reachable only
through the corpus driver's entry point; the serve daemon needs the SAME
context-switch discipline for its per-tenant request batches, and two
private copies would drift (the exact bug class the isolation audit
exists to catch). This module is the single home for:

  origins      an origin tag is one analysis's identity: for the corpus
               driver `"{idx}:{basename}"`, for the serve daemon
               `"{tenant}:{code digest}"` — ALWAYS tenant/slot-qualified,
               never a bare contract basename, so two tenants submitting
               files that happen to share a name can never share a
               memory tier, a quick-sat deque, or a blaster id space.
               `origin_in_session(origin, session)` is the one predicate
               that maps origins back to their owning session/tenant
               (eviction, isolation audits).
  blasters     the per-origin private blaster/AIG registry: the shared
               strashed AIG assigns node ids in first-use order and the
               dense CNF sorts by id, so a process-wide blaster makes
               the CDCL's branching — and hence which valid witness
               model it returns — depend on which sibling analysis
               blasted a common subterm first. Per-origin blasters
               reproduce the solo-process id space exactly: the property
               that makes interleaved/served findings BYTE-identical to
               the solo schedule, witnesses included.
  EngineContext  one origin's slice of the process-global engine state
               (wall budget, tx ids, keccak/exponent managers, module
               issue state, memory/quick-sat solve tiers, detection
               flag), context-switched at every baton handoff.
               install_fresh(preserve_caches=True) is the serve daemon's
               WARM start: engine state (modules, tx ids, clocks) resets
               per request, but the origin's solve memos — memory tier,
               quick-sat deque, private blaster AIG — survive across
               requests, which is what makes a repeat request on a warm
               daemon record strictly fewer cdcl_settles.
  eviction     evict_session(session): drop ONE session's origins —
               memory tiers, quick-sat deques, blasters, and its prefix
               snapshots (smt/solver/incremental.py) — without flushing
               the shared strash table, the disk tier, or any other
               tenant's warmth (the all-or-nothing clear_caches would
               cold-start every tenant on any one tenant's
               invalidation).
"""

import copy
import time
from typing import Dict, Optional, Tuple

# origin -> (Blaster or None, term generation): each analysis's private
# blaster/AIG (None = lazily recreated on first use).
_blasters: Dict[str, Tuple[object, int]] = {}


def encode_session(session: str) -> str:
    """Injective colon-free encoding of an arbitrary session/tenant id.
    Origins are minted as "<session>:<qualifier>" and
    origin_in_session() splits on the FIRST colon, so a raw tenant id
    containing ':' (they arrive from HTTP bodies) would let tenant
    "alice" evict "alice:prod"'s memos — the exact cross-tenant reach
    the predicate exists to forbid. Percent-escaping keeps distinct ids
    distinct."""
    return str(session).replace("%", "%25").replace(":", "%3A")


def origin_in_session(origin: Optional[str], session: str) -> bool:
    """Does `origin` belong to `session` (an encode_session()-ed tenant
    id, a raw colon-free one, or a full origin tag)? Origins are minted
    as "<session>:<qualifier>", so the owning session is everything
    before the first colon; an exact match accepts a full origin tag as
    its own session."""
    if origin is None:
        return False
    return origin == session or origin.split(":", 1)[0] == session


def install_blaster(origin) -> None:
    """Install `origin`'s private blaster over the process globals."""
    from mythril_tpu.smt.solver import frontend

    (frontend._global_blaster,
     frontend._global_blaster_generation) = _blasters.get(origin,
                                                          (None, -1))


def stash_blaster(origin) -> None:
    """Capture the live process-global blaster as `origin`'s."""
    from mythril_tpu.smt.solver import frontend

    _blasters[origin] = (frontend._global_blaster,
                         frontend._global_blaster_generation)


def reset_blaster(origin) -> None:
    """Give `origin` an empty blaster (cold start), installing it."""
    from mythril_tpu.smt.solver import frontend

    _blasters[origin] = (None, -1)
    frontend._global_blaster = None
    frontend._global_blaster_generation = -1


def clear_blasters() -> None:
    _blasters.clear()


def capture_module_templates():
    """Pristine per-module state snapshots, taken once at driver start
    (right after every module was reset): each origin's fresh install
    copies from these, so a module attribute added mid-run by one origin
    can never leak into another's."""
    from mythril_tpu.analysis.module import ModuleLoader

    return [
        (module, {key: copy.copy(value)
                  for key, value in module.__dict__.items()})
        for module in ModuleLoader().get_detection_modules()
    ]


class EngineContext:
    """One origin's slice of the process-global engine state.

    install_fresh() gives a starting analysis pristine engine state (the
    same state a solo-process analysis of the contract would see);
    save() captures the live globals when the origin loses the baton;
    restore() reinstalls them when it gets the baton back. State swapped
    by object-identity-preserving `__dict__` replacement where the
    global is a singleton other modules hold references to (function
    managers, detection modules), and by module-attribute rebinding
    where call sites re-read the attribute (support.model's memory
    tiers).

    `preserve_caches=True` (the serve daemon's warm start) keeps the
    origin's existing solve memos — memory tier, quick-sat deque, and
    private blaster — across requests; the engine state (clocks, tx
    ids, keccak/exponent managers, module issue lists) still resets per
    request, exactly as a fresh solo analysis would see it."""

    def __init__(self, origin: str, module_templates):
        self.origin = origin
        self._templates = module_templates
        self._saved = None

    def install_fresh(self, preserve_caches: bool = False) -> None:
        from mythril_tpu.laser.function_managers import (
            exponent_function_manager,
            keccak_function_manager,
        )
        from mythril_tpu.laser.transaction.models import tx_id_manager
        from mythril_tpu.support import model as model_mod
        from mythril_tpu.support.time_handler import time_handler

        time_handler._start = None
        time_handler._timeout = None
        tx_id_manager._next = 0
        if preserve_caches:
            # warm start: the origin's private blaster (and below, its
            # memory tiers) survive from its earlier requests — the
            # cross-request memo reuse the serve daemon exists for
            install_blaster(self.origin)
        else:
            # fresh per-origin blaster: a starting contract gets an
            # empty AIG, exactly like a solo process
            reset_blaster(self.origin)
        keccak_function_manager.__dict__ = (
            type(keccak_function_manager)().__dict__)
        exponent_function_manager.__dict__ = (
            type(exponent_function_manager)().__dict__)
        for module, template in self._templates:
            module.__dict__ = {key: copy.copy(value)
                               for key, value in template.items()}
        # the origin's memory tiers live in model.py's per-origin
        # registry (get_models_batch resolves them PER QUERY during
        # mixed flushes); installing them into the module globals serves
        # the ambient call sites — get_model, the engine's direct
        # quick-sat probes — while this origin holds the baton. A cold
        # start drops any stale registry pair so the analysis starts as
        # cold as a solo process would; a warm start keeps it.
        if not preserve_caches:
            model_mod._origin_caches.pop(self.origin, None)
        tier, quick_cache = model_mod.caches_for_origin(self.origin)
        model_mod._result_cache = tier
        model_mod.model_cache = quick_cache
        model_mod._in_detection_context = False

    def save(self) -> None:
        from mythril_tpu.laser.function_managers import (
            exponent_function_manager,
            keccak_function_manager,
        )
        from mythril_tpu.laser.transaction.models import tx_id_manager
        from mythril_tpu.support import model as model_mod
        from mythril_tpu.support.time_handler import time_handler

        # the execution-timeout clock PAUSES while the origin is
        # off-baton: store elapsed-so-far, not the absolute start, so a
        # contract's budget measures its own engine time — siblings'
        # quanta must not burn it (and must not make the interleaved
        # run's timeout behavior diverge from the sequential run's)
        elapsed = (time.monotonic() - time_handler._start
                   if time_handler._start is not None else None)
        stash_blaster(self.origin)
        self._saved = {
            "time": (elapsed, time_handler._timeout),
            "txid": tx_id_manager._next,
            "keccak": keccak_function_manager.__dict__,
            "exponent": exponent_function_manager.__dict__,
            "modules": [module.__dict__ for module, _t in self._templates],
            "result_cache": model_mod._result_cache,
            "model_cache": model_mod.model_cache,
            "detection": model_mod._in_detection_context,
        }

    def restore(self) -> None:
        from mythril_tpu.laser.function_managers import (
            exponent_function_manager,
            keccak_function_manager,
        )
        from mythril_tpu.laser.transaction.models import tx_id_manager
        from mythril_tpu.support import model as model_mod
        from mythril_tpu.support.time_handler import time_handler

        saved = self._saved
        self._saved = None
        elapsed, timeout = saved["time"]
        time_handler._timeout = timeout
        time_handler._start = (time.monotonic() - elapsed
                               if elapsed is not None else None)
        tx_id_manager._next = saved["txid"]
        install_blaster(self.origin)
        keccak_function_manager.__dict__ = saved["keccak"]
        exponent_function_manager.__dict__ = saved["exponent"]
        for (module, _t), state in zip(self._templates, saved["modules"]):
            module.__dict__ = state
        model_mod._result_cache = saved["result_cache"]
        model_mod.model_cache = saved["model_cache"]
        model_mod._in_detection_context = saved["detection"]


def evict_session(session: str) -> int:
    """Session-scoped eviction: drop every memo belonging to ONE
    session/tenant — its per-origin memory tiers and quick-sat deques,
    its private blasters, and its prefix snapshots — WITHOUT flushing
    the shared strash table, the disk tier, other tenants' tiers, the
    scheduler, or the session fuses (the all-or-nothing clear_caches()
    would cold-start every tenant on any one tenant's invalidation).
    Returns the number of evicted origins."""
    from collections import OrderedDict

    from mythril_tpu.smt.solver import incremental
    from mythril_tpu.support import model as model_mod

    # iterate over SNAPSHOTS: eviction may run on an HTTP handler
    # thread while another tenant's batch inserts fresh origins — a
    # live-dict iteration would raise mid-eviction
    doomed = [origin for origin in list(model_mod._origin_caches)
              if origin_in_session(origin, session)]
    for origin in doomed:
        pair = model_mod._origin_caches.pop(origin, None)
        if pair is None:
            continue
        tier, quick_cache = pair
        # the evicted pair may be INSTALLED in the module globals (the
        # session's context was live): replace with fresh empties so
        # ambient call sites cannot keep serving the evicted memos
        if model_mod._result_cache is tier:
            model_mod._result_cache = OrderedDict()
        if model_mod.model_cache is quick_cache:
            model_mod.model_cache = model_mod.ModelCache()
    for origin in [o for o in list(_blasters)
                   if origin_in_session(o, session)]:
        _blasters.pop(origin, None)
        if origin not in doomed:
            doomed.append(origin)
    # the session's prefix snapshots (incremental prepare memos) go with
    # it; the id-keyed simplify/free-symbol memos stay — they are
    # content-addressed over the shared term table, not per-origin state
    incremental.evict_session(session)
    return len(doomed)

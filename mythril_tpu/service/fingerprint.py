"""Canonical content fingerprint of a blasted solver instance.

The persistent result tier (store.py) is keyed by the *blasted* form of a
query — the dense-renumbered CNF plus the AIG root literals mapped into
the same dense numbering — not by constraint-term identity: term objects
do not survive a process boundary, while the dense cone is a canonical
per-problem artifact (the blaster renumbers every problem's cone compactly
regardless of where it sits in the shared global AIG).

Normalization: literals are sorted within each clause (the Tseitin
exporters emit deterministic but representation-specific literal orders),
clause order is kept as emitted (deterministic for a given cone). A
fingerprint collision can never alias a verdict — SAT entries are
replay-verified against the ORIGINAL constraints on every hit
(support/model._probe_persistent) and a failed replay is a safe miss.
"""

import hashlib
import struct
from typing import Optional

# bump on ANY change to the fingerprint recipe or the blasting pipeline's
# canonical form — stale entries must miss, never alias
# v2: instances are fingerprinted AFTER static CNF preprocessing
# (preanalysis/cnf_prep.py) — the same query now hashes its simplified
# clause form, so v1 entries (keyed by the raw Tseitin form) must miss,
# never alias. Note this does NOT make differently-spelled but
# propagation-equal constraint sets share an entry: the AIG roots (hashed
# below) still reflect the original structure.
FINGERPRINT_SCHEMA = 2


def instance_fingerprint(prep) -> Optional[str]:
    """sha256 hex digest of `prep`'s blasted instance in canonical form,
    or None when the instance has no blasted CNF (trivial verdicts)."""
    clauses = getattr(prep, "clauses", None)
    if clauses is None or getattr(prep, "blaster", None) is None:
        return None
    digest = hashlib.sha256()
    digest.update(b"mythril-tpu-solve-v%d:" % FINGERPRINT_SCHEMA)
    digest.update(struct.pack("<q", prep.num_vars))
    if hasattr(clauses, "lits"):
        import numpy as np

        lits = np.asarray(clauses.lits, dtype=np.int64)
        offsets = np.asarray(clauses.offsets, dtype=np.int64)
        lengths = offsets[1:] - offsets[:-1]
        clause_ids = np.repeat(
            np.arange(len(lengths), dtype=np.int64), lengths)
        # within-clause literal sort, clause order preserved: one lexsort
        # over (clause id, literal) — no per-clause Python loop
        order = np.lexsort((lits, clause_ids))
        digest.update(
            np.ascontiguousarray(lits[order].astype(np.int32)).tobytes())
        digest.update(np.ascontiguousarray(offsets).tobytes())
    else:
        for clause in clauses:
            for lit in sorted(clause):
                digest.update(struct.pack("<i", lit))
            digest.update(b";")
    # AIG roots, mapped global var -> dense var (the cone's canonical
    # numbering); constant/outside-cone roots hash as 0
    if prep.aig_roots is not None:
        _aig, roots, dense = prep.aig_roots
        for lit in roots:
            dense_var = dense.get(lit >> 1) or 0
            digest.update(struct.pack("<q", (dense_var << 1) | (lit & 1)))
    return digest.hexdigest()

"""Canonical content fingerprint of a blasted solver instance.

The persistent result tier (store.py) is keyed by the *blasted* form of a
query — the dense-renumbered CNF plus the AIG root literals mapped into
the same dense numbering — not by constraint-term identity: term objects
do not survive a process boundary, while the dense cone is a canonical
per-problem artifact (the blaster renumbers every problem's cone compactly
regardless of where it sits in the shared global AIG).

Normalization: literals are sorted within each clause (the Tseitin
exporters emit deterministic but representation-specific literal orders),
clause order is kept as emitted (deterministic for a given cone). A
fingerprint collision can never alias a verdict — SAT entries are
replay-verified against the ORIGINAL constraints on every hit
(support/model._probe_persistent) and a failed replay is a safe miss.

Partitioned instances (preanalysis/aig_partition.py) additionally
fingerprint each variable-disjoint component as its OWN sub-instance
(component_fingerprint): a sub-cone shared by different parent queries
hashes identically in both, so the disk tier hits across parents even
when the monolithic fingerprints differ.
"""

import hashlib
import struct
from typing import Optional

# bump on ANY change to the fingerprint recipe or the blasting pipeline's
# canonical form — stale entries must miss, never alias
# v2: instances are fingerprinted AFTER static CNF preprocessing
# (preanalysis/cnf_prep.py) — the same query now hashes its simplified
# clause form, so v1 entries (keyed by the raw Tseitin form) must miss,
# never alias.
# v3: instances are fingerprinted AFTER the AIG structural rewrite
# (preanalysis/aig_opt.py): the canonical form is now the swept/strashed
# cone's dense CNF + rewritten roots, so v2 entries (keyed by the raw
# blasted form) must miss, never alias. Per-component sub-instance
# fingerprints share this version stamp (they flow into the same store).
FINGERPRINT_SCHEMA = 3


def _digest_cnf(digest, num_vars: int, clauses) -> None:
    """Feed (num_vars, canonicalized clauses) into `digest`."""
    digest.update(struct.pack("<q", num_vars))
    if hasattr(clauses, "lits"):
        import numpy as np

        lits = np.asarray(clauses.lits, dtype=np.int64)
        offsets = np.asarray(clauses.offsets, dtype=np.int64)
        lengths = offsets[1:] - offsets[:-1]
        clause_ids = np.repeat(
            np.arange(len(lengths), dtype=np.int64), lengths)
        # within-clause literal sort, clause order preserved: one lexsort
        # over (clause id, literal) — no per-clause Python loop
        order = np.lexsort((lits, clause_ids))
        digest.update(
            np.ascontiguousarray(lits[order].astype(np.int32)).tobytes())
        digest.update(np.ascontiguousarray(offsets).tobytes())
    else:
        for clause in clauses:
            for lit in sorted(clause):
                digest.update(struct.pack("<i", lit))
            digest.update(b";")


def _digest_roots(digest, roots, dense) -> None:
    """AIG roots, mapped global var -> dense var (the cone's canonical
    numbering); constant/outside-cone roots hash as 0."""
    for lit in roots:
        dense_var = dense.get(lit >> 1) or 0
        digest.update(struct.pack("<q", (dense_var << 1) | (lit & 1)))


def instance_fingerprint(prep) -> Optional[str]:
    """sha256 hex digest of `prep`'s blasted instance in canonical form,
    or None when the instance has no blasted CNF (trivial verdicts)."""
    clauses = getattr(prep, "clauses", None)
    if clauses is None or getattr(prep, "blaster", None) is None:
        return None
    digest = hashlib.sha256()
    digest.update(b"mythril-tpu-solve-v%d:" % FINGERPRINT_SCHEMA)
    _digest_cnf(digest, prep.num_vars, clauses)
    if prep.aig_roots is not None:
        _aig, roots, dense = prep.aig_roots
        _digest_roots(digest, roots, dense)
    return digest.hexdigest()


def component_fingerprint(num_vars: int, clauses, roots, dense) -> str:
    """sha256 hex digest of ONE partitioned component's sub-instance
    (its dense-renumbered CNF + projected roots in the same numbering).
    Domain-separated from whole-instance fingerprints so a monolithic
    entry can never alias a component of the same shape."""
    digest = hashlib.sha256()
    digest.update(b"mythril-tpu-component-v%d:" % FINGERPRINT_SCHEMA)
    _digest_cnf(digest, num_vars, clauses)
    _digest_roots(digest, roots, dense)
    return digest.hexdigest()

"""Coalescing solve scheduler.

`submit(constraints) -> SolveHandle` buffers eligible single-query solve
traffic; a flush hands EVERY buffered query to support/model's
get_models_batch in one call, which level-buckets the eligible cones into
padded router dispatches (tpu/router.py) — one multi-query device fan-out
instead of N solo host solves, raising device occupancy.

Flush triggers (bounded window):
  demand   the first handle whose result is demanded flushes the whole
           buffer (single-threaded callers can never deadlock on a
           buffered handle)
  count    the buffer reaching MYTHRIL_TPU_COALESCE_MAX (default 16)
  age      a submit arriving after the oldest buffered entry has waited
           MYTHRIL_TPU_COALESCE_MS (default 6 ms)

The engine's natural seams (fork feasibility in laser/svm.py, the
pending-state drain in strategy/constraint_strategy.py, open-state
reachability, and the potential_issues confirmation pre-filter) route
their sibling-query bundles through solve_batch(), so every one of those
erstwhile per-query solves joins a window. Honest scope note: the engine
is synchronous and demands each bundle before proceeding, so today a
window holds one seam's bundle plus whatever direct submit() traffic was
buffered since the last flush — the count/age triggers matter for
submit()-without-demand callers (async frontends, tests), and the
facade is the seam future traffic sources plug into.
MYTHRIL_TPU_COALESCE_MS=0 disables coalescing entirely: solve_batch
degrades to a direct get_models_batch call and submit() solves
immediately — bit-identical to the pre-service path.

Every flush is counted in SolverStatistics (window_flushes,
coalesced_queries; coalesce_occupancy = queries per flush).
"""

import logging
import os
import time
from typing import List, Optional, Tuple

log = logging.getLogger(__name__)

DEFAULT_COALESCE_MS = 6.0
DEFAULT_COALESCE_MAX = 16
# with ragged paged dispatch live, ONE kernel launch covers a whole
# window regardless of shape (tpu/circuit.RaggedStream), so a wider
# default window buys amortization instead of padding waste — the
# bucketed path keeps the narrow default because its cost scales with
# the padded slot count, not the window's summed gates
DEFAULT_COALESCE_MAX_RAGGED = 64


from mythril_tpu.support.env import env_float as _env_float


class SolveHandle:
    """Future-like result of one submitted query. result() returns the
    get_models_batch outcome tuple: ("sat", Model) / ("unsat", None) /
    ("unknown", None)."""

    __slots__ = ("_scheduler", "_outcome", "_done")

    def __init__(self, scheduler: "CoalescingScheduler"):
        self._scheduler = scheduler
        self._outcome = None
        self._done = False

    @property
    def done(self) -> bool:
        return self._done

    def result(self) -> Tuple[str, Optional[object]]:
        if not self._done:
            self._scheduler.flush()
        return self._outcome

    def _resolve(self, outcome) -> None:
        self._outcome = outcome
        self._done = True


class CoalescingScheduler:
    def __init__(self):
        self.window_ms = _env_float(
            "MYTHRIL_TPU_COALESCE_MS", DEFAULT_COALESCE_MS)
        default_max = DEFAULT_COALESCE_MAX
        try:
            from mythril_tpu.support.args import args
            from mythril_tpu.tpu.router import ragged_enabled

            # widen only when ragged dispatch can actually engage: on
            # the host-only CDCL backend one launch never covers the
            # window, so the wider buffer would just add flush latency
            if (ragged_enabled()
                    and getattr(args, "solver_backend", None) == "tpu"):
                default_max = DEFAULT_COALESCE_MAX_RAGGED
        except Exception:  # router import must never break the scheduler
            pass
        self.max_batch = max(
            1, int(_env_float("MYTHRIL_TPU_COALESCE_MAX", default_max)))
        self._buffer: List[tuple] = []  # (handle, constraint list, crosscheck)
        self._oldest: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return self.window_ms > 0

    def pending(self) -> int:
        return len(self._buffer)

    def submit(self, constraints, crosscheck: Optional[bool] = None
               ) -> SolveHandle:
        """Buffer one query; returns a handle. With coalescing disabled the
        query is solved immediately (pass-through)."""
        handle = SolveHandle(self)
        if not self.enabled:
            from mythril_tpu.support.model import get_models_batch

            handle._resolve(
                get_models_batch([constraints], crosscheck=crosscheck)[0])
            return handle
        self._buffer_one(handle, constraints, crosscheck)
        if len(self._buffer) >= self.max_batch:
            self.flush()
        return handle

    def _buffer_one(self, handle, constraints, crosscheck) -> None:
        now = time.monotonic()
        if (self._buffer and self._oldest is not None
                and (now - self._oldest) * 1000.0 >= self.window_ms):
            # the window expired while nobody demanded a result: flush the
            # stale cohort before starting a new one
            self.flush()
            now = time.monotonic()
        if not self._buffer:
            self._oldest = now
        self._buffer.append((handle, list(constraints), crosscheck))

    def solve_batch(self, constraint_sets,
                    crosscheck: Optional[bool] = None) -> List:
        """Seam entry point: buffer every sibling query, then demand all
        results — the whole bundle (plus anything already buffered) rides
        ONE window flush regardless of max_batch (the bundle size is
        already bounded by the caller; splitting it across dispatches
        would halve bucket occupancy at exactly the seams routing exists
        for). Degrades to a direct get_models_batch call when coalescing
        is disabled (bit-identical to the pre-service path)."""
        if not self.enabled:
            from mythril_tpu.support.model import get_models_batch

            return get_models_batch(constraint_sets, crosscheck=crosscheck)
        handles = []
        for constraints in constraint_sets:
            handle = SolveHandle(self)
            self._buffer_one(handle, constraints, crosscheck)
            handles.append(handle)
        return [handle.result() for handle in handles]

    def solve_fork_batch(self, constraint_sets, pairs,
                         crosscheck: Optional[bool] = False) -> List:
        """Fork-bundle seam (laser/frontier/stepper.py fork epilogue):
        the taken/fall-through sibling feasibility checks of ONE batched
        JUMPI fork, handed to get_models_batch as a single coalesced
        bundle with `pairs` — (i, j) index pairs marking two sides of
        the same row — forwarded to the router's fork lane, which packs
        a pair's shared cone once and rides both sides on one ragged
        stream with the fork literals as extra assumption roots. Any
        already-buffered traffic flushes first so the pair indices stay
        aligned with the bundle."""
        if not self.enabled:
            from mythril_tpu.support.model import get_models_batch

            return get_models_batch(constraint_sets, crosscheck=crosscheck,
                                    fork_pairs=pairs)
        self.flush()
        from mythril_tpu.smt.solver.statistics import SolverStatistics
        from mythril_tpu.support.model import get_models_batch

        SolverStatistics().add_window_flush(len(constraint_sets))
        return get_models_batch(constraint_sets, crosscheck=crosscheck,
                                fork_pairs=pairs)

    def flush(self) -> None:
        """Solve everything buffered: one _solve_group per distinct
        crosscheck flag (submission order preserved per group; the group
        solve and its per-query failure isolation live in _solve_group)."""
        if not self._buffer:
            return
        from mythril_tpu.observe.tracer import span as trace_span
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        buffered, self._buffer = self._buffer, []
        self._oldest = None
        SolverStatistics().add_window_flush(len(buffered))
        groups = {}
        for entry in buffered:
            groups.setdefault(entry[2], []).append(entry)
        with trace_span("scheduler.flush", cat="service",
                        queries=len(buffered), groups=len(groups)):
            for flag, entries in groups.items():
                outcomes = self._solve_group(flag, entries)
                for (handle, _c, _f), outcome in zip(entries, outcomes):
                    handle._resolve(outcome)

    def _solve_group(self, flag, entries) -> List:
        """Solve one crosscheck-group of a window flush. Registered fault
        site scheduler.flush (retry action): a query raising inside the
        coalesced batch must fail ONLY its own handle — the batched call
        is retried query-by-query so the buffered siblings that happened
        to share the window still get their real verdicts, and only a
        query that fails ALONE degrades to unknown (possibly-feasible —
        a handle must never dangle, and unknown can cost precision on
        that one query, never a missed finding on its siblings)."""
        from mythril_tpu.resilience import maybe_inject, record_event
        from mythril_tpu.support.model import get_models_batch

        try:
            maybe_inject("scheduler.flush")
            return get_models_batch(
                [constraints for _h, constraints, _f in entries],
                crosscheck=flag,
            )
        except Exception:
            log.warning("coalesced solve flush failed; retrying the %d "
                        "buffered quer(ies) individually",
                        len(entries), exc_info=True)
            record_event("scheduler.flush", "retry")
        outcomes = []
        for _handle, constraints, _f in entries:
            try:
                outcomes.append(
                    get_models_batch([constraints], crosscheck=flag)[0])
            except Exception:
                log.exception("query failed alone after a flush failure; "
                              "degrading it (only) to unknown")
                record_event("scheduler.flush", "degraded")
                outcomes.append(("unknown", None))
        return outcomes

    def clear(self) -> None:
        """Discard buffered state WITHOUT solving (clear_caches/test
        isolation); unresolved handles degrade to unknown."""
        buffered, self._buffer = self._buffer, []
        self._oldest = None
        for handle, _c, _f in buffered:
            handle._resolve(("unknown", None))


_scheduler: Optional[CoalescingScheduler] = None


def get_scheduler() -> CoalescingScheduler:
    global _scheduler
    if _scheduler is None:
        _scheduler = CoalescingScheduler()
    return _scheduler


def reset_scheduler() -> None:
    """Drop the singleton (env is re-read on next access); buffered
    queries degrade to unknown rather than solving during teardown."""
    global _scheduler
    if _scheduler is not None:
        _scheduler.clear()
    _scheduler = None

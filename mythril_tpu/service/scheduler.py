"""Coalescing solve scheduler.

`submit(constraints) -> SolveHandle` buffers eligible single-query solve
traffic; a flush hands EVERY buffered query to support/model's
get_models_batch in one call, which level-buckets the eligible cones into
padded router dispatches (tpu/router.py) — one multi-query device fan-out
instead of N solo host solves, raising device occupancy.

Flush triggers (bounded window):
  demand   the first handle whose result is demanded flushes the whole
           buffer (single-threaded callers can never deadlock on a
           buffered handle)
  count    the buffer reaching MYTHRIL_TPU_COALESCE_MAX (default 16)
  age      a submit arriving after the oldest buffered entry has waited
           MYTHRIL_TPU_COALESCE_MS (default 6 ms)

The engine's natural seams (fork feasibility in laser/svm.py, the
pending-state drain in strategy/constraint_strategy.py, open-state
reachability, and the potential_issues confirmation pre-filter) route
their sibling-query bundles through solve_batch(), so every one of those
erstwhile per-query solves joins a window. Honest scope note: the engine
is synchronous and demands each bundle before proceeding, so today a
window holds one seam's bundle plus whatever direct submit() traffic was
buffered since the last flush — the count/age triggers matter for
submit()-without-demand callers (async frontends, tests), and the
facade is the seam future traffic sources plug into.
MYTHRIL_TPU_COALESCE_MS=0 disables coalescing entirely: solve_batch
degrades to a direct get_models_batch call and submit() solves
immediately — bit-identical to the pre-service path.

Every flush is counted in SolverStatistics (window_flushes,
coalesced_queries; coalesce_occupancy = queries per flush).

Cross-contract windows (service/interleave.py): the window is
PROCESS-GLOBAL and every buffered entry carries an ORIGIN tag (the
contract identity minted by the interleaved corpus driver; None outside
it). Under the interleave coordinator, solve_batch PARKS its bundle
instead of demanding an immediate flush, so bundles from DIFFERENT
contracts accumulate in one window and ride one batched router dispatch
— the origins thread through get_models_batch to the ragged stream
packer, which counts mixed-origin launches (xcontract_windows). Fair
admission: when a window holds >= 2 origins, each flush group caps any
single origin's share at MYTHRIL_TPU_ORIGIN_BUDGET queries and
round-robins the origins, so a stress_dispatch-class contract's flood
of sibling queries cannot push a 2 s contract's two cones out of the
first dispatch (excess entries flush in follow-on groups of the same
flush() call — nothing is dropped, only ordered).
"""

import logging
import os
import time
from typing import List, Optional, Tuple

log = logging.getLogger(__name__)

DEFAULT_COALESCE_MS = 6.0
DEFAULT_COALESCE_MAX = 16
# with ragged paged dispatch live, ONE kernel launch covers a whole
# window regardless of shape (tpu/circuit.RaggedStream), so a wider
# default window buys amortization instead of padding waste — the
# bucketed path keeps the narrow default because its cost scales with
# the padded slot count, not the window's summed gates
DEFAULT_COALESCE_MAX_RAGGED = 64
# per-origin share of one flush group when the window mixes origins:
# bounds how much of a single batched dispatch one contract may occupy
DEFAULT_ORIGIN_BUDGET = 32


from mythril_tpu.support.env import env_float as _env_float


class SolveHandle:
    """Future-like result of one submitted query. result() returns the
    get_models_batch outcome tuple: ("sat", Model) / ("unsat", None) /
    ("unknown", None)."""

    __slots__ = ("_scheduler", "_outcome", "_done")

    def __init__(self, scheduler: "CoalescingScheduler"):
        self._scheduler = scheduler
        self._outcome = None
        self._done = False

    @property
    def done(self) -> bool:
        return self._done

    def result(self) -> Tuple[str, Optional[object]]:
        if not self._done:
            self._scheduler.flush()
        return self._outcome

    def _resolve(self, outcome) -> None:
        self._outcome = outcome
        self._done = True


class CoalescingScheduler:
    def __init__(self):
        self.window_ms = _env_float(
            "MYTHRIL_TPU_COALESCE_MS", DEFAULT_COALESCE_MS)
        default_max = DEFAULT_COALESCE_MAX
        try:
            from mythril_tpu.support.args import args
            from mythril_tpu.tpu.router import ragged_enabled

            # widen only when ragged dispatch can actually engage: on
            # the host-only CDCL backend one launch never covers the
            # window, so the wider buffer would just add flush latency
            if (ragged_enabled()
                    and getattr(args, "solver_backend", None) == "tpu"):
                default_max = DEFAULT_COALESCE_MAX_RAGGED
        except Exception:  # router import must never break the scheduler
            pass
        self.max_batch = max(
            1, int(_env_float("MYTHRIL_TPU_COALESCE_MAX", default_max)))
        self.origin_budget = max(
            1, int(_env_float("MYTHRIL_TPU_ORIGIN_BUDGET",
                              DEFAULT_ORIGIN_BUDGET)))
        # entries: (handle, constraint list, crosscheck, origin tag,
        # pair token) — the pair token is one shared object per fork
        # pair (both sides of one batched JUMPI fork), None for plain
        # traffic; flush rebuilds the router's fork_pairs hint from it
        self._buffer: List[tuple] = []
        self._oldest: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return self.window_ms > 0

    def pending(self) -> int:
        return len(self._buffer)

    def submit(self, constraints, crosscheck: Optional[bool] = None
               ) -> SolveHandle:
        """Buffer one query; returns a handle. With coalescing disabled the
        query is solved immediately (pass-through)."""
        handle = SolveHandle(self)
        if not self.enabled:
            from mythril_tpu.support.model import get_models_batch

            handle._resolve(
                get_models_batch([constraints], crosscheck=crosscheck)[0])
            return handle
        self._buffer_one(handle, constraints, crosscheck)
        if len(self._buffer) >= self.max_batch:
            self.flush()
        return handle

    def _buffer_one(self, handle, constraints, crosscheck,
                    pair_key=None) -> None:
        from mythril_tpu.service import interleave

        now = time.monotonic()
        if (self._buffer and self._oldest is not None
                and interleave.active() is None
                and (now - self._oldest) * 1000.0 >= self.window_ms):
            # the window expired while nobody demanded a result: flush the
            # stale cohort before starting a new one. Under the interleave
            # coordinator the age trigger is suspended: parked bundles WAIT
            # for sibling contracts' queries by design (wall-clock age
            # mostly measures the siblings' engine quanta), and the
            # coordinator flushes the window the moment no analysis can
            # make progress — parked handles can never go stale
            self.flush()
            now = time.monotonic()
        if not self._buffer:
            self._oldest = now
        self._buffer.append((handle, list(constraints), crosscheck,
                             interleave.current_origin(), pair_key))

    def solve_batch(self, constraint_sets,
                    crosscheck: Optional[bool] = None) -> List:
        """Seam entry point: buffer every sibling query, then demand all
        results — the whole bundle (plus anything already buffered) rides
        ONE window flush regardless of max_batch (the bundle size is
        already bounded by the caller; splitting it across dispatches
        would halve bucket occupancy at exactly the seams routing exists
        for). Degrades to a direct get_models_batch call when coalescing
        is disabled (bit-identical to the pre-service path).

        Under the interleave coordinator (service/interleave.py) the
        bundle PARKS instead of demanding immediately: the baton passes
        to sibling analyses, whose bundles join the same window, and the
        eventual flush carries queries from every parked contract — the
        cross-contract mixed window the ragged packer turns into one
        launch."""
        if not self.enabled:
            from mythril_tpu.support.model import get_models_batch

            return get_models_batch(constraint_sets, crosscheck=crosscheck)
        handles = []
        for constraints in constraint_sets:
            handle = SolveHandle(self)
            self._buffer_one(handle, constraints, crosscheck)
            handles.append(handle)
        from mythril_tpu.service import interleave

        coordinator = interleave.active()
        if coordinator is not None and handles:
            coordinator.park_for_results(self, handles)
        return [handle.result() for handle in handles]

    def solve_fork_batch(self, constraint_sets, pairs,
                         crosscheck: Optional[bool] = False) -> List:
        """Fork-bundle seam (laser/frontier/stepper.py fork epilogue):
        the taken/fall-through sibling feasibility checks of ONE batched
        JUMPI fork, handed to get_models_batch as a single coalesced
        bundle with `pairs` — (i, j) index pairs marking two sides of
        the same row — forwarded to the router's fork lane, which packs
        a pair's shared cone once and rides both sides on one ragged
        stream with the fork literals as extra assumption roots.

        Outside the interleave coordinator, any already-buffered traffic
        flushes first so the pair indices stay aligned with the bundle
        (the pre-interleave behavior, bit-identical). UNDER the
        coordinator the bundle joins the shared window like any other
        traffic — fork feasibility is the dominant solve stream on
        branch-heavy contracts, so excluding it would leave mixed
        windows starved — with each pair tagged by a shared token the
        flush turns back into the router's fork_pairs hint (pairs are
        kept atomic across fair-admission sub-groups)."""
        if not self.enabled:
            from mythril_tpu.support.model import get_models_batch

            return get_models_batch(constraint_sets, crosscheck=crosscheck,
                                    fork_pairs=pairs)
        from mythril_tpu.service import interleave

        coordinator = interleave.active()
        if coordinator is None:
            self.flush()
            from mythril_tpu.smt.solver.statistics import SolverStatistics
            from mythril_tpu.support.model import get_models_batch

            SolverStatistics().add_window_flush(len(constraint_sets))
            return get_models_batch(constraint_sets, crosscheck=crosscheck,
                                    fork_pairs=pairs)
        pair_keys = {}
        for i, j in pairs or ():
            token = object()
            pair_keys[i] = token
            pair_keys[j] = token
        handles = []
        for index, constraints in enumerate(constraint_sets):
            handle = SolveHandle(self)
            self._buffer_one(handle, constraints, crosscheck,
                             pair_key=pair_keys.get(index))
            handles.append(handle)
        if handles:
            coordinator.park_for_results(self, handles)
        return [handle.result() for handle in handles]

    def flush(self) -> None:
        """Solve everything buffered: one _solve_group per distinct
        crosscheck flag (submission order preserved per group; the group
        solve and its per-query failure isolation live in _solve_group).
        Crosscheck groups holding >= 2 origins additionally split into
        fair-admission sub-groups (_origin_groups) so no single contract
        monopolizes one batched dispatch."""
        if not self._buffer:
            return
        from mythril_tpu.observe.tracer import span as trace_span
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        buffered, self._buffer = self._buffer, []
        self._oldest = None
        SolverStatistics().add_window_flush(len(buffered))
        groups = {}
        for entry in buffered:
            groups.setdefault(entry[2], []).append(entry)
        try:
            with trace_span("scheduler.flush", cat="service",
                            queries=len(buffered), groups=len(groups)):
                for flag, entries in groups.items():
                    for group in self._origin_groups(entries):
                        outcomes = self._solve_group(flag, group)
                        for (handle, _c, _f, _o, _p), outcome in zip(
                                group, outcomes):
                            handle._resolve(outcome)
        finally:
            # the buffer was popped above, so an exception escaping the
            # group loop (beyond _solve_group's per-query isolation —
            # e.g. MemoryError mid-flush) would otherwise strand every
            # popped handle unresolved FOREVER: no later flush can see
            # them, and a parked interleaved analysis would spin on a
            # handle nothing can complete. Any handle still pending
            # degrades to unknown — precision, never a stuck caller.
            for entry in buffered:
                if not entry[0].done:
                    entry[0]._resolve(("unknown", None))

    def _origin_groups(self, entries: List[tuple]) -> List[List[tuple]]:
        """Fair window-share admission: with >= 2 distinct origins in a
        flush group, round-robin the origins with at most origin_budget
        entries each per sub-group — every origin present in the window
        lands in the FIRST dispatch, and a flood origin's overflow rides
        follow-on sub-groups of the same flush. Fork pairs travel as one
        atom so the router's shared-cone pair packing survives the
        slicing. Single-origin (and untagged) windows pass through
        untouched: bundles keep their one dispatch, exactly the
        pre-interleave behavior."""
        origins = {entry[3] for entry in entries}
        if len(origins) < 2:
            return [entries]
        queues = {}   # origin -> list of atoms (1 entry, or a fork pair)
        order = []
        pending_pair = {}  # pair token -> atom awaiting its second side
        for entry in entries:
            origin = entry[3]
            if origin not in queues:
                queues[origin] = []
                order.append(origin)
            token = entry[4]
            if token is not None and token in pending_pair:
                pending_pair.pop(token).append(entry)
                continue
            atom = [entry]
            queues[origin].append(atom)
            if token is not None:
                pending_pair[token] = atom
        cursors = {origin: 0 for origin in order}
        groups: List[List[tuple]] = []
        while any(cursors[o] < len(queues[o]) for o in order):
            group: List[tuple] = []
            for origin in order:
                queue, cursor = queues[origin], cursors[origin]
                taken = 0
                while cursor < len(queue) and taken < self.origin_budget:
                    atom = queue[cursor]
                    group.extend(atom)
                    taken += len(atom)
                    cursor += 1
                cursors[origin] = cursor
            groups.append(group)
        return groups

    def _solve_group(self, flag, entries) -> List:
        """Solve one crosscheck-group of a window flush. Registered fault
        site scheduler.flush (retry action): a query raising inside the
        coalesced batch must fail ONLY its own handle — the batched call
        is retried query-by-query so the buffered siblings that happened
        to share the window still get their real verdicts, and only a
        query that fails ALONE degrades to unknown (possibly-feasible —
        a handle must never dangle, and unknown can cost precision on
        that one query, never a missed finding on its siblings)."""
        from mythril_tpu.resilience import maybe_inject, record_event
        from mythril_tpu.support.model import get_models_batch

        # rebuild the router's fork-pair hint from the pair tokens (both
        # sides of a pair always land in one group — _origin_groups
        # slices atoms). Purely a packing hint: losing it costs page
        # sharing, never a verdict, so the per-query retry path below
        # simply drops it.
        fork_pairs = []
        first_side = {}
        for position, entry in enumerate(entries):
            token = entry[4]
            if token is None:
                continue
            if token in first_side:
                fork_pairs.append((first_side.pop(token), position))
            else:
                first_side[token] = position
        try:
            maybe_inject("scheduler.flush")
            return get_models_batch(
                [constraints for _h, constraints, _f, _o, _p in entries],
                crosscheck=flag,
                origins=[origin for _h, _c, _f, origin, _p in entries],
                fork_pairs=fork_pairs or None,
            )
        except Exception:
            log.warning("coalesced solve flush failed; retrying the %d "
                        "buffered quer(ies) individually",
                        len(entries), exc_info=True)
            record_event("scheduler.flush", "retry")
        outcomes = []
        for _handle, constraints, _f, origin, _p in entries:
            try:
                outcomes.append(get_models_batch(
                    [constraints], crosscheck=flag,
                    origins=[origin])[0])
            except Exception:
                log.exception("query failed alone after a flush failure; "
                              "degrading it (only) to unknown")
                record_event("scheduler.flush", "degraded")
                outcomes.append(("unknown", None))
        return outcomes

    def clear(self) -> None:
        """Discard buffered state WITHOUT solving (clear_caches/test
        isolation); unresolved handles degrade to unknown."""
        buffered, self._buffer = self._buffer, []
        self._oldest = None
        for handle, _c, _f, _o, _p in buffered:
            handle._resolve(("unknown", None))


_scheduler: Optional[CoalescingScheduler] = None


def get_scheduler() -> CoalescingScheduler:
    global _scheduler
    if _scheduler is None:
        _scheduler = CoalescingScheduler()
    return _scheduler


def reset_scheduler() -> None:
    """Drop the singleton (env is re-read on next access); buffered
    queries degrade to unknown rather than solving during teardown."""
    global _scheduler
    if _scheduler is not None:
        _scheduler.clear()
    _scheduler = None

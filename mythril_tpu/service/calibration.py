"""Persistent router micro-calibration cache.

The adaptive router (tpu/router.py) derives its device eligibility caps
from a one-shot per-process measurement of per-cell ministep latency —
which previously left every CLI invocation paying the measurement round
(kernel compile + two timed rounds) before its first device dispatch.
With the disk tier enabled, the measured latency persists beside the
result store, keyed by (platform, restart lanes, round steps) — the cell
profile that determines what the measurement actually timed — so repeated
invocations skip the round entirely.

Entries carry a schema stamp and a measurement timestamp; a schema bump
or a malformed file degrades to re-measurement, never to a wrong cap.
"""

import json
import logging
import os
import time
from typing import Optional

from mythril_tpu.support.lock import LockFile

log = logging.getLogger(__name__)

CALIBRATION_SCHEMA_VERSION = 1
# schema of the per-platform `tuned` section the autotune search persists
# beside the measurement entries (mythril_tpu/tune/search.py) — bumped
# independently of the calibration schema: a stale tuned layout must be
# ignored (with a counted event) without invalidating the measurements
TUNED_SCHEMA_VERSION = 1
_FILENAME = "calibration.json"

# stage speed-of-light rates persisted beside per_cell_s (additive keys —
# same schema version; old entries without them simply report no ceiling
# for those stages until the next fresh measurement. ragged_bytes_s was
# added with the ragged paged dispatch, pallas_cells_s with the
# shape-polymorphic Pallas kernel — its ceiling in block-aligned
# real-gate cells/s, so roofline/sol_gaps rank the kernel stage against
# whichever backend MYTHRIL_TPU_KERNEL resolves to: the router
# re-measures just the stage rates — no XLA kernel round — when a cached
# entry predates a key)
STAGE_RATE_KEYS = ("pack_bytes_s", "ship_bytes_s", "ragged_bytes_s",
                   "settle_clauses_s", "pallas_cells_s")


def _path() -> str:
    from mythril_tpu.service import cache_dir

    return os.path.join(cache_dir(), _FILENAME)


def _key(platform: str, restarts: int, steps: int) -> str:
    return f"{platform}|r{restarts}|s{steps}"


def _enabled() -> bool:
    from mythril_tpu.service import disk_tier_enabled

    return disk_tier_enabled()


def load_profile(platform: Optional[str], restarts: int,
                 steps: int) -> Optional[dict]:
    """The cached measurement entry for this platform + cell profile —
    {"per_cell_s": float, optional stage rates (STAGE_RATE_KEYS)} — or
    None (measure). A valid per_cell_s gates the whole entry: the cap
    sizing must never run off a corrupt measurement. A 0.0 stage rate
    is a persisted "measured, unavailable" sentinel — passed through so
    the router's staleness check sees the attempt (and doesn't re-pay
    the measurement every process start); ceiling consumers filter
    > 0 before use."""
    if not platform or not _enabled():
        return None
    try:
        with open(_path()) as fd:
            payload = json.load(fd)
    except (OSError, ValueError):
        return None
    if payload.get("schema") != CALIBRATION_SCHEMA_VERSION:
        return None
    entry = payload.get("entries", {}).get(_key(platform, restarts, steps))
    if not isinstance(entry, dict):
        return None
    value = entry.get("per_cell_s")
    if not isinstance(value, (int, float)) or value <= 0:
        return None
    out = {"per_cell_s": float(value)}
    for key in STAGE_RATE_KEYS:
        rate = entry.get(key)
        if isinstance(rate, (int, float)) and rate >= 0:
            out[key] = float(rate)
    # measured first-call XLA compile cost of the calibration round
    # (seconds, not a rate): feeds the evidence-mode ragged-chunk auto
    # default (router._auto_chunk_cones). Entries that predate it simply
    # lack the key — consumers fall back to the measured-in-PR-12 floor.
    compile_s = entry.get("compile_s")
    if isinstance(compile_s, (int, float)) and compile_s >= 0:
        out["compile_s"] = float(compile_s)
    return out


def load_per_cell_latency(platform: Optional[str], restarts: int,
                          steps: int) -> Optional[float]:
    """Cached seconds per (cell x step) for this platform + cell profile,
    or None (measure)."""
    profile = load_profile(platform, restarts, steps)
    return profile["per_cell_s"] if profile else None


def save_profile(platform: Optional[str], restarts: int, steps: int,
                 profile: dict) -> None:
    """Persist a measurement entry (per_cell_s + any stage rates)."""
    if not platform or not _enabled() or not profile.get("per_cell_s"):
        return
    path = _path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with LockFile(path + ".lock"):
            payload = {"schema": CALIBRATION_SCHEMA_VERSION, "entries": {}}
            try:
                with open(path) as fd:
                    existing = json.load(fd)
                if existing.get("schema") == CALIBRATION_SCHEMA_VERSION:
                    payload = existing
                    payload.setdefault("entries", {})
            except (OSError, ValueError):
                pass
            payload["entries"][_key(platform, restarts, steps)] = {
                **{key: value for key, value in profile.items()
                   if isinstance(value, (int, float))
                   and (value > 0 or (value == 0
                                      and key in STAGE_RATE_KEYS))},
                "measured_at": int(time.time()),
            }
            from mythril_tpu.service.store import atomic_write_json

            atomic_write_json(path, payload)
    except OSError as error:
        log.info("could not persist calibration (%s)", error)


def save_per_cell_latency(platform: Optional[str], restarts: int,
                          steps: int, per_cell_s: float) -> None:
    save_profile(platform, restarts, steps, {"per_cell_s": per_cell_s})


# -- tuned profiles (mythril_tpu/tune/) ---------------------------------------
#
# The autotune search persists its measured winner as a per-platform
# `tuned` section in the same file, beside the calibration entries it was
# searched against. Unlike the measurement entries, the tuned section is
# an explicit operator artifact, not a cache tier: load/save are NOT
# gated on disk_tier_enabled(), so a profile tuned once applies to every
# later run regardless of --solve-cache mode, and clear_caches() (which
# only drops in-process state) can never lose it.


def _read_payload() -> dict:
    try:
        with open(_path()) as fd:
            payload = json.load(fd)
    except (OSError, ValueError):
        return {}
    return payload if isinstance(payload, dict) else {}


def tuned_platforms() -> list:
    """Platform keys with a present (not necessarily valid) tuned entry
    — the platform-guess fallback for unpinned processes that have not
    initialized jax yet (tune.default_platform)."""
    section = _read_payload().get("tuned")
    if not isinstance(section, dict):
        return []
    return sorted(name for name in section if isinstance(name, str))


def measured_platforms() -> list:
    """Platforms this machine's calibration MEASUREMENTS were taken on
    (entry keys are "platform|rN|sM", written only by processes whose
    jax actually initialized here) — the ground truth a platform guess
    can be checked against."""
    entries = _read_payload().get("entries")
    if not isinstance(entries, dict):
        return []
    return sorted({key.split("|", 1)[0] for key in entries
                   if isinstance(key, str) and "|" in key})


def load_tuned(platform: Optional[str]):
    """(tuned profile dict, None) for this platform, (None, reject
    reason) for a present-but-unusable section (corrupt file, stale
    schema, malformed knobs — the caller counts the event), or
    (None, None) when nothing was ever tuned."""
    if not platform:
        return None, None
    path = _path()
    if not os.path.isfile(path):
        return None, None
    try:
        with open(path) as fd:
            payload = json.load(fd)
    except (OSError, ValueError):
        return None, "unreadable"
    section = payload.get("tuned") if isinstance(payload, dict) else None
    if not isinstance(section, dict):
        return None, None if section is None else "malformed"
    entry = section.get(platform)
    if entry is None:
        return None, None
    if not isinstance(entry, dict):
        return None, "malformed"
    if entry.get("schema") != TUNED_SCHEMA_VERSION:
        return None, "stale-schema"
    knobs = entry.get("knobs")
    if not isinstance(knobs, dict) or not knobs or not all(
            isinstance(name, str) and isinstance(value, (int, float, str))
            and not isinstance(value, bool)
            for name, value in knobs.items()):
        return None, "malformed"
    return entry, None


def save_tuned(platform: Optional[str], entry: dict) -> bool:
    """Persist one platform's tuned profile (schema stamp added here).
    Read-modify-write under the same lock as the measurement entries so
    a concurrent calibration save cannot tear the section."""
    if not platform or not isinstance(entry.get("knobs"), dict):
        return False
    path = _path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with LockFile(path + ".lock"):
            payload = {"schema": CALIBRATION_SCHEMA_VERSION, "entries": {}}
            try:
                with open(path) as fd:
                    existing = json.load(fd)
                if existing.get("schema") == CALIBRATION_SCHEMA_VERSION:
                    payload = existing
                    payload.setdefault("entries", {})
                elif isinstance(existing.get("tuned"), dict):
                    # the tuned section is versioned INDEPENDENTLY
                    # (TUNED_SCHEMA_VERSION): a calibration-schema bump
                    # drops the measurement entries, never the other
                    # platforms' still-valid tuned profiles
                    payload["tuned"] = existing["tuned"]
            except (OSError, ValueError):
                pass
            tuned = payload.get("tuned")
            if not isinstance(tuned, dict):
                tuned = {}
            tuned[platform] = {**entry, "schema": TUNED_SCHEMA_VERSION,
                               "tuned_at": int(time.time())}
            payload["tuned"] = tuned
            from mythril_tpu.service.store import atomic_write_json

            atomic_write_json(path, payload)
        return True
    except OSError as error:
        log.info("could not persist tuned profile (%s)", error)
        return False

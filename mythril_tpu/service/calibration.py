"""Persistent router micro-calibration cache.

The adaptive router (tpu/router.py) derives its device eligibility caps
from a one-shot per-process measurement of per-cell ministep latency —
which previously left every CLI invocation paying the measurement round
(kernel compile + two timed rounds) before its first device dispatch.
With the disk tier enabled, the measured latency persists beside the
result store, keyed by (platform, restart lanes, round steps) — the cell
profile that determines what the measurement actually timed — so repeated
invocations skip the round entirely.

Entries carry a schema stamp and a measurement timestamp; a schema bump
or a malformed file degrades to re-measurement, never to a wrong cap.
"""

import json
import logging
import os
import time
from typing import Optional

from mythril_tpu.support.lock import LockFile

log = logging.getLogger(__name__)

CALIBRATION_SCHEMA_VERSION = 1
_FILENAME = "calibration.json"

# stage speed-of-light rates persisted beside per_cell_s (additive keys —
# same schema version; old entries without them simply report no ceiling
# for those stages until the next fresh measurement. ragged_bytes_s was
# added with the ragged paged dispatch: the router re-measures just the
# stage rates — no kernel round — when a cached entry predates it)
STAGE_RATE_KEYS = ("pack_bytes_s", "ship_bytes_s", "ragged_bytes_s",
                   "settle_clauses_s")


def _path() -> str:
    from mythril_tpu.service import cache_dir

    return os.path.join(cache_dir(), _FILENAME)


def _key(platform: str, restarts: int, steps: int) -> str:
    return f"{platform}|r{restarts}|s{steps}"


def _enabled() -> bool:
    from mythril_tpu.service import disk_tier_enabled

    return disk_tier_enabled()


def load_profile(platform: Optional[str], restarts: int,
                 steps: int) -> Optional[dict]:
    """The cached measurement entry for this platform + cell profile —
    {"per_cell_s": float, optional stage rates (STAGE_RATE_KEYS)} — or
    None (measure). A valid per_cell_s gates the whole entry: the cap
    sizing must never run off a corrupt measurement. A 0.0 stage rate
    is a persisted "measured, unavailable" sentinel — passed through so
    the router's staleness check sees the attempt (and doesn't re-pay
    the measurement every process start); ceiling consumers filter
    > 0 before use."""
    if not platform or not _enabled():
        return None
    try:
        with open(_path()) as fd:
            payload = json.load(fd)
    except (OSError, ValueError):
        return None
    if payload.get("schema") != CALIBRATION_SCHEMA_VERSION:
        return None
    entry = payload.get("entries", {}).get(_key(platform, restarts, steps))
    if not isinstance(entry, dict):
        return None
    value = entry.get("per_cell_s")
    if not isinstance(value, (int, float)) or value <= 0:
        return None
    out = {"per_cell_s": float(value)}
    for key in STAGE_RATE_KEYS:
        rate = entry.get(key)
        if isinstance(rate, (int, float)) and rate >= 0:
            out[key] = float(rate)
    return out


def load_per_cell_latency(platform: Optional[str], restarts: int,
                          steps: int) -> Optional[float]:
    """Cached seconds per (cell x step) for this platform + cell profile,
    or None (measure)."""
    profile = load_profile(platform, restarts, steps)
    return profile["per_cell_s"] if profile else None


def save_profile(platform: Optional[str], restarts: int, steps: int,
                 profile: dict) -> None:
    """Persist a measurement entry (per_cell_s + any stage rates)."""
    if not platform or not _enabled() or not profile.get("per_cell_s"):
        return
    path = _path()
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with LockFile(path + ".lock"):
            payload = {"schema": CALIBRATION_SCHEMA_VERSION, "entries": {}}
            try:
                with open(path) as fd:
                    existing = json.load(fd)
                if existing.get("schema") == CALIBRATION_SCHEMA_VERSION:
                    payload = existing
                    payload.setdefault("entries", {})
            except (OSError, ValueError):
                pass
            payload["entries"][_key(platform, restarts, steps)] = {
                **{key: value for key, value in profile.items()
                   if isinstance(value, (int, float))
                   and (value > 0 or (value == 0
                                      and key in STAGE_RATE_KEYS))},
                "measured_at": int(time.time()),
            }
            from mythril_tpu.service.store import atomic_write_json

            atomic_write_json(path, payload)
    except OSError as error:
        log.info("could not persist calibration (%s)", error)


def save_per_cell_latency(platform: Optional[str], restarts: int,
                          steps: int, per_cell_s: float) -> None:
    save_profile(platform, restarts, steps, {"per_cell_s": per_cell_s})

"""Tiered solve-result service — durable infrastructure in front of the
solver seam (support/model.py).

Three cooperating parts (the TVM pattern of reusing tuned results across
compilations, and SOLAR's premise that measured evidence should persist
across runs):

  store.py       persistent on-disk result tier keyed by a canonical
                 content fingerprint of the blasted instance; SAT entries
                 are replay-verified on every hit, UNSAT entries carry
                 crosscheck provenance (fingerprint.py builds the key)
  scheduler.py   coalescing solve scheduler: a submit() -> handle facade
                 with a bounded window that flushes buffered single-query
                 traffic as ONE level-bucketed router dispatch
  calibration.py persistent router micro-calibration cache (per platform +
                 cell profile), so repeated CLI invocations skip the
                 startup measurement round

Tier selection rides the --solve-cache CLI flag (support/args.py):
  off     no result caching at all (debugging)
  memory  the in-memory term-keyed tier only — the pre-service behavior
  disk    memory tier + the persistent cross-run store under
          MYTHRIL_TPU_CACHE_DIR
"""

import os

from mythril_tpu.support.args import args

_MODES = ("off", "memory", "disk")


def solve_cache_mode() -> str:
    mode = getattr(args, "solve_cache", "memory")
    return mode if mode in _MODES else "memory"


def memory_tier_enabled() -> bool:
    return solve_cache_mode() != "off"


def disk_tier_enabled() -> bool:
    return solve_cache_mode() == "disk"


def cache_dir() -> str:
    """Root of every persistent service artifact (result store,
    calibration cache, and — via tpu/backend — the XLA compile cache)."""
    return os.environ.get("MYTHRIL_TPU_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "mythril_tpu")


def reset_service_state() -> None:
    """Drop process-local service handles: buffered scheduler state is
    discarded (unresolved handles degrade to unknown) and the store handle
    is released so the next access re-opens from disk. clear_caches() calls
    this so tests and --jobs workers start clean — a cleared process
    re-populates from the durable tier, never from stale memory."""
    from mythril_tpu.service.scheduler import reset_scheduler
    from mythril_tpu.service.store import reset_result_store

    reset_scheduler()
    reset_result_store()

"""The serve daemon: a request queue in front of MythrilAnalyzer whose
failure envelope is typed like everything else in this repo.

Request lifecycle:

  submit      admission control under one lock: a draining daemon and a
              full queue answer `rejected` IMMEDIATELY (explicit
              backpressure — bounded queue depth instead of unbounded
              latency), and a per-tenant budget caps how much of the
              queue one tenant may occupy, so a flood tenant is the one
              that hears `overloaded`, not its neighbors.
  batch       the worker pops up to MYTHRIL_TPU_SERVE_BATCH admitted
              requests — round-robin across tenants in arrival order
              (registered fault site serve.admission: a fault in the
              fair ordering degrades to plain FIFO for the session,
              nothing dropped) — and runs them as ONE interleaved
              cohort on the PR-12 baton coordinator with
              tenant-qualified origins. Their sibling solve queries park
              in the process-global coalescing window and ride mixed
              ragged streams: the cross-request multi-tenant batcher.
  contexts    per-tenant engine contexts (service/tenancy.py) start
              WARM: a tenant's memory tier, quick-sat deque, private
              blaster AIG, and prefix snapshots survive across its
              requests (term-generation invalidation applies as ever),
              so a repeat request on a warm daemon records strictly
              fewer cdcl_settles. Cross-TENANT reuse flows only through
              the content-addressed, replay-verified disk tier.
  deadlines   each batch executes on a DEDICATED PR-8 runner thread
              (resilience/deadline.new_runner — the shared runner would
              self-deadlock under the nested device-dispatch deadline)
              bounded by the largest per-request deadline. A wedged
              batch is abandoned (serve.worker `deadline` event), its
              cancel token stops the abandoned body at its next check,
              parked scheduler handles are unwound (the PR-12
              _flush_safely finally-resolution generalized to request
              teardown: every buffered handle resolves, a sibling can
              never hang on one), and the batch's unfinished requests
              requeue ONCE into a fresh batch — a second failure
              answers `incomplete`, never hangs.
  poisoning   serve.request (quarantine): a request that fails alone —
              injected fault or a genuinely poisoned input — answers
              `error` by itself; batch siblings keep their results and
              their findings stay byte-identical to a no-fault run
              (per-origin isolation is what makes that a theorem rather
              than a hope).
  drain       SIGTERM: stop admitting, finish everything already
              admitted, write the final reconciled heartbeat, stop the
              listener. The drain wall is counted (serve_drain_wall).

Knobs (all env; see README "Serve daemon"):
  MYTHRIL_TPU_SERVE_QUEUE_MAX      bounded queue depth (64)
  MYTHRIL_TPU_SERVE_TENANT_BUDGET  queued requests per tenant (8)
  MYTHRIL_TPU_SERVE_BATCH          requests per interleaved batch (4)
  MYTHRIL_TPU_SERVE_DEADLINE      per-request hard deadline seconds (120)
  MYTHRIL_TPU_SERVE_DRAIN_TIMEOUT  drain wait before leftovers answer
                                   `incomplete` (60)
  MYTHRIL_TPU_SERVE_PORT           CLI default listener port (8311)
"""

import hashlib
import json
import logging
import threading
import time
from typing import Dict, List, Optional

from mythril_tpu.support.env import env_float

log = logging.getLogger(__name__)

QUEUE_MAX_ENV = "MYTHRIL_TPU_SERVE_QUEUE_MAX"
TENANT_BUDGET_ENV = "MYTHRIL_TPU_SERVE_TENANT_BUDGET"
BATCH_ENV = "MYTHRIL_TPU_SERVE_BATCH"
DEADLINE_ENV = "MYTHRIL_TPU_SERVE_DEADLINE"
DRAIN_TIMEOUT_ENV = "MYTHRIL_TPU_SERVE_DRAIN_TIMEOUT"
PORT_ENV = "MYTHRIL_TPU_SERVE_PORT"

DEFAULT_QUEUE_MAX = 64
DEFAULT_TENANT_BUDGET = 8
DEFAULT_BATCH = 4
DEFAULT_DEADLINE_S = 120.0
DEFAULT_DRAIN_TIMEOUT_S = 60.0
DEFAULT_PORT = 8311


def _env_int(name: str, default: int) -> int:
    return max(1, int(env_float(name, default)))


class ServeRequest:
    """One tenant's analysis request, resolved to a terminal outcome
    dict exactly once:

      {"status": "ok", "issues": [...], "exceptions": [...]}
      {"status": "error", "reason": ...}        poisoned request, alone
      {"status": "rejected", "reason": "overloaded" | "draining"}
      {"status": "incomplete", "reason": ...}   answered, never hung
    """

    _seq = [0]
    _seq_lock = threading.Lock()

    def __init__(self, tenant: str, code: str, name: Optional[str] = None,
                 tx_count: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 bin_runtime: bool = False,
                 modules: Optional[List[str]] = None):
        with self._seq_lock:
            self._seq[0] += 1
            self.request_id = self._seq[0]
        self.tenant = str(tenant)
        self.code = code
        self.name = name
        self.tx_count = tx_count
        self.deadline_s = deadline_s
        self.bin_runtime = bin_runtime
        self.modules = modules
        # tenant-qualified, content-addressed origin: the SAME tenant
        # resubmitting the SAME bytecode reuses its warm tiers; two
        # tenants submitting files that share a basename can never
        # share one (the isolation-audit property). The tenant id is
        # colon-escaped so origin_in_session's first-colon split cannot
        # be confused by an adversarial tenant string.
        from mythril_tpu.service.tenancy import encode_session

        digest = hashlib.sha256(code.encode()).hexdigest()[:12]
        self.origin = f"{encode_session(self.tenant)}:{digest}"
        self.contract = None          # built at admission
        self.requeues = 0
        self.submitted_at = None      # monotonic, set at admission
        self.wait_s = None            # queue latency, set at batch pop
        self.outcome: Optional[dict] = None
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def resolve(self, outcome: dict) -> bool:
        """First resolve wins; returns whether THIS call resolved (the
        caller may count a terminal-outcome stat only when it did — a
        drain-resolved `incomplete` must not also count `completed`
        when its abandoned analysis eventually finishes)."""
        if self._done.is_set():
            return False
        outcome.setdefault("request_id", self.request_id)
        outcome.setdefault("tenant", self.tenant)
        if self.wait_s is not None:
            outcome.setdefault("wait_s", round(self.wait_s, 4))
        self.outcome = outcome
        self._done.set()
        return True

    def wait(self, timeout: Optional[float] = None) -> Optional[dict]:
        self._done.wait(timeout)
        return self.outcome


class ServeDaemon:
    def __init__(self, tx_count: int = 1,
                 modules: Optional[List[str]] = None,
                 http_port: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 tenant_budget: Optional[int] = None,
                 batch_max: Optional[int] = None,
                 deadline_s: Optional[float] = None):
        self.tx_count = tx_count
        self.modules = modules
        self.http_port = http_port   # None = no listener (in-process API)
        self.port = None             # bound port, set by start()
        self.queue_max = queue_max or _env_int(QUEUE_MAX_ENV,
                                               DEFAULT_QUEUE_MAX)
        self.tenant_budget = tenant_budget or _env_int(
            TENANT_BUDGET_ENV, DEFAULT_TENANT_BUDGET)
        self.batch_max = batch_max or _env_int(BATCH_ENV, DEFAULT_BATCH)
        self.deadline_s = deadline_s or env_float(DEADLINE_ENV,
                                                  DEFAULT_DEADLINE_S)
        self.drain_timeout_s = env_float(DRAIN_TIMEOUT_ENV,
                                         DEFAULT_DRAIN_TIMEOUT_S)
        self._cv = threading.Condition()
        self._queue: List[ServeRequest] = []   # arrival order
        self._inflight: List[ServeRequest] = []
        self._evicting: set = set()            # sessions mid-eviction
        self._draining = False
        self._stopping = False
        self.drained = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._runner = None
        self._templates = None
        self._heartbeat = None
        self._http = None
        self._analyzer = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServeDaemon":
        """Warm the engine plumbing once (the per-request cost a CLI
        invocation pays every time) and start the worker + listener."""
        from mythril_tpu.analysis.module import ModuleLoader
        from mythril_tpu.core import MythrilAnalyzer, MythrilDisassembler
        from mythril_tpu.observe import flightrec, metrics
        from mythril_tpu.resilience import deadline as deadline_mod
        from mythril_tpu.resilience import faults
        from mythril_tpu.service import tenancy
        from mythril_tpu.smt.solver.statistics import SolverStatistics
        from mythril_tpu.support.args import args

        for module in ModuleLoader().get_detection_modules():
            module.reset_module()
            module.reset_cache()
        stats = SolverStatistics()
        stats.enabled = True
        faults.configure_from_env(getattr(args, "inject_fault", None))
        flightrec.install()
        self._heartbeat = metrics.start_heartbeat(
            getattr(args, "heartbeat", None))
        # pristine module templates captured ONCE: batch N's contexts
        # must never inherit batch N-1's module state
        self._templates = tenancy.capture_module_templates()
        self._analyzer = MythrilAnalyzer(MythrilDisassembler())
        self._runner = deadline_mod.new_runner()
        self._worker = threading.Thread(
            target=self._worker_loop, name="mythril-serve-worker",
            daemon=True)
        self._worker.start()
        if self.http_port is not None:
            from mythril_tpu.serve.httpd import ServeHTTP

            self._http = ServeHTTP(self, self.http_port)
            self._http.start()
            self.port = self._http.port
        log.info("serve daemon up: queue_max=%d tenant_budget=%d "
                 "batch=%d deadline=%.0fs port=%s",
                 self.queue_max, self.tenant_budget, self.batch_max,
                 self.deadline_s, self.port)
        return self

    # -- admission -----------------------------------------------------------

    def submit(self, tenant: str, code: str, name: Optional[str] = None,
               tx_count: Optional[int] = None,
               deadline_s: Optional[float] = None,
               bin_runtime: bool = False,
               modules: Optional[List[str]] = None) -> ServeRequest:
        """Admit (or reject) one request. Always returns a request whose
        outcome WILL resolve — rejected ones resolve immediately."""
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        request = ServeRequest(tenant, code, name=name, tx_count=tx_count,
                               deadline_s=deadline_s,
                               bin_runtime=bin_runtime, modules=modules)
        stats = SolverStatistics()
        # parse the bytecode BEFORE taking the admission lock: a
        # malformed request is answered now instead of poisoning a
        # batch later, and a large contract's disassembly must not
        # serialize every concurrent admission/healthz/batch-pop
        # behind it
        try:
            request.contract = self._build_contract(request)
        except Exception as error:
            stats.add_serve_admission(False)
            request.resolve({"status": "rejected",
                             "reason": f"bad request: {error}"})
            return request
        with self._cv:
            if self._draining or self._stopping:
                stats.add_serve_admission(False)
                request.resolve({"status": "rejected",
                                 "reason": "draining"})
                return request
            from mythril_tpu.service.tenancy import origin_in_session

            if any(origin_in_session(request.origin, session)
                   for session in self._evicting):
                # the tenant's memos are mid-eviction: admitting now
                # would run a live context whose save/restore could
                # reinstall the evicted tiers
                stats.add_serve_admission(False)
                request.resolve({"status": "rejected",
                                 "reason": "evicting"})
                return request
            depth = len(self._queue) + len(self._inflight)
            if depth >= self.queue_max:
                stats.add_serve_admission(False)
                request.resolve({"status": "rejected",
                                 "reason": "overloaded"})
                return request
            tenant_depth = sum(
                1 for r in self._queue + self._inflight
                if r.tenant == request.tenant)
            if tenant_depth >= self.tenant_budget:
                stats.add_serve_admission(False)
                request.resolve({"status": "rejected",
                                 "reason": "overloaded"})
                return request
            request.submitted_at = time.monotonic()
            stats.add_serve_admission(True)
            self._queue.append(request)
            self._cv.notify_all()
        return request

    @staticmethod
    def _build_contract(request: ServeRequest):
        from mythril_tpu.ethereum.evmcontract import EVMContract

        name = request.name or "MAIN"
        if request.bin_runtime:
            return EVMContract(code=request.code, name=name)
        return EVMContract(creation_code=request.code, name=name)

    # -- batching ------------------------------------------------------------

    def _next_batch(self) -> List[ServeRequest]:
        """Pop the next cross-request batch (caller holds the lock).

        Fair admission: tenants rotate in the arrival order of their
        oldest queued request, one request per tenant per round, so one
        tenant's backlog cannot monopolize a batch while another tenant
        waits. Two requests sharing an ORIGIN (same tenant, same
        bytecode) never share a batch — their warm context is one
        object. Registered fault site serve.admission (disable): any
        fault in the fair ordering — injected or real — degrades to
        plain FIFO for the session; requests are only ever reordered,
        never dropped."""
        from mythril_tpu import resilience
        from mythril_tpu.resilience import maybe_inject

        batch: List[ServeRequest] = []
        if not resilience.fuse_blown("serve.admission"):
            try:
                maybe_inject("serve.admission")
                tenants: List[str] = []
                for request in self._queue:
                    if request.tenant not in tenants:
                        tenants.append(request.tenant)
                taken = set()
                progressed = True
                while len(batch) < self.batch_max and progressed:
                    progressed = False
                    for tenant in tenants:
                        if len(batch) >= self.batch_max:
                            break
                        for request in self._queue:
                            if id(request) in taken \
                                    or request.tenant != tenant:
                                continue
                            if any(request.origin == b.origin
                                   for b in batch):
                                continue
                            batch.append(request)
                            taken.add(id(request))
                            progressed = True
                            break
            except Exception:
                resilience.note_stage_failure("serve.admission")
                batch = []
        if not batch:
            # FIFO degradation (and the trivial single-tenant case):
            # first-come first-served, distinct origins per batch
            for request in self._queue:
                if len(batch) >= self.batch_max:
                    break
                if any(request.origin == b.origin for b in batch):
                    continue
                batch.append(request)
        for request in batch:
            self._queue.remove(request)
            self._inflight.append(request)
        return batch

    # -- worker --------------------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopping:
                    self._cv.wait(0.2)
                if self._stopping and not self._queue:
                    return
                batch = self._next_batch()
            if batch:
                try:
                    self._execute_batch(batch)
                finally:
                    with self._cv:
                        for request in batch:
                            if request in self._inflight:
                                self._inflight.remove(request)
                        self._cv.notify_all()

    def _execute_batch(self, batch: List[ServeRequest]) -> None:
        from mythril_tpu.resilience import record_event
        from mythril_tpu.resilience.deadline import StageDeadlineExceeded
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        stats = SolverStatistics()
        now = time.monotonic()
        for request in batch:
            if request.submitted_at is not None and request.wait_s is None:
                request.wait_s = now - request.submitted_at
                stats.add_serve_wait_seconds(request.wait_s)
        deadline = max(
            (r.deadline_s or self.deadline_s) for r in batch)
        shared = {"cancelled": False, "coordinator": None}

        def body():
            from mythril_tpu.resilience import maybe_inject

            # the serve.worker crossing sits BEFORE any engine state is
            # touched: an injected hang wedges the runner here, the
            # deadline abandons it, and the cancel token stops the
            # abandoned body cold when the hang finally wakes — it never
            # races the requeued batch over the engine globals
            maybe_inject("serve.worker")
            if shared["cancelled"]:
                return
            self._run_batch_body(batch, shared)

        try:
            self._runner.call(body, deadline)
        except StageDeadlineExceeded:
            self._abandon(shared)
            record_event("serve.worker", "deadline")
            log.warning("serve batch exceeded its %.1fs deadline; "
                        "abandoning the wedged worker", deadline)
            from mythril_tpu.resilience import deadline as deadline_mod

            self._runner = deadline_mod.new_runner()
            self._requeue_or_incomplete(batch, "deadline")
        except Exception as error:
            self._abandon(shared)
            log.warning("serve batch failed (%r); requeueing its "
                        "unfinished requests once", error)
            self._requeue_or_incomplete(batch, repr(error))
        finally:
            self._teardown_batch()

    @staticmethod
    def _abandon(shared: dict) -> None:
        """Stop an abandoned batch's slot threads: the cancel flag stops
        the pre-coordinator body, and Coordinator.cancel() raises
        BatchCancelled at every abandoned thread's next yield point —
        abandoned analyses DIE instead of racing the requeued batch
        over the process-global engine state."""
        shared["cancelled"] = True
        coordinator = shared.get("coordinator")
        if coordinator is not None:
            coordinator.cancel()

    def _run_batch_body(self, batch: List[ServeRequest],
                        shared: Optional[dict] = None) -> None:
        """Run one admitted batch as an interleaved cohort (executes on
        the dedicated runner thread). Width-1 batches ride the same
        coordinator: identical per-origin isolation, identical code
        path, just no sibling to mix windows with."""
        from mythril_tpu.service import interleave
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        SolverStatistics().add_serve_batch(
            len(batch), len({r.tenant for r in batch}))
        tasks = [(idx, request.contract)
                 for idx, request in enumerate(batch)]
        coordinator = interleave.Coordinator(
            tasks, origins=[request.origin for request in batch],
            warm=True, module_templates=self._templates)
        if shared is not None:
            shared["coordinator"] = coordinator
            if shared["cancelled"]:
                return
        interleave.install(coordinator)
        threads = []

        def slot_main(slot_id):
            try:
                coordinator.run_slot(slot_id,
                                     self._make_analyze_one(batch))
            except interleave.BatchCancelled:
                pass  # abandoned batch: dying quietly is the contract

        try:
            for slot_id in range(len(batch)):
                thread = threading.Thread(
                    target=slot_main, args=(slot_id,),
                    name=f"mythril-serve-slot-{slot_id}")
                thread.start()
                threads.append(thread)
            for thread in threads:
                thread.join()
        finally:
            # compare-and-swap teardown: if this body was abandoned and
            # a NEWER batch installed its own coordinator, leave it
            # alone (the check-and-pop is atomic inside uninstall)
            interleave.uninstall(keep_tenancy=True, expected=coordinator)

    def _make_analyze_one(self, batch: List[ServeRequest]):
        def analyze_one(idx, contract):
            self._analyze_request(batch[idx])

        return analyze_one

    def _analyze_request(self, request: ServeRequest) -> None:
        """One request, inside its own engine context (the coordinator
        installed it). Registered fault site serve.request (quarantine):
        ANY failure here — injected or a genuinely poisoned contract —
        answers `error` for this request alone; batch siblings are
        isolated by construction."""
        from mythril_tpu import resilience
        from mythril_tpu.analysis.report import Report
        from mythril_tpu.resilience import maybe_inject
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        stats = SolverStatistics()
        settles_before = stats.cdcl_settles
        memory_before = stats.memory_hits + stats.quick_sat_hits
        try:
            maybe_inject("serve.request")
            issues, exceptions = self._analyzer._analyze_one_contract(
                request.contract, request.modules or self.modules,
                request.tx_count or self.tx_count, stats=stats)
            report = Report(contracts=[request.contract],
                            exceptions=exceptions)
            for issue in issues:
                report.append_issue(issue)
            resolved = request.resolve({
                "status": "ok",
                "issues": json.loads(report.as_json())["issues"],
                "exceptions": list(exceptions),
                "origin": request.origin,
                # per-request settle/memo deltas (exact for width-1
                # batches; interleaved siblings' settles fold in for
                # mixed ones — still the warm-vs-cold signal)
                "cdcl_settles": stats.cdcl_settles - settles_before,
                "memo_hits": (stats.memory_hits + stats.quick_sat_hits
                              - memory_before),
            })
            if resolved:
                stats.add_serve_outcome("completed")
        except Exception as error:
            resilience.record_event("serve.request", "quarantine")
            log.warning("request %d (tenant %s) poisoned: %r — failing "
                        "it alone", request.request_id, request.tenant,
                        error)
            if request.resolve({"status": "error",
                                "reason": repr(error)}):
                stats.add_serve_outcome("completed")

    def _requeue_or_incomplete(self, batch: List[ServeRequest],
                               reason: str) -> None:
        """Batch-level failure disposition: every UNFINISHED request goes
        around once more (fresh batch, fresh runner); a request that
        already failed a batch answers `incomplete` — the typed
        never-hung guarantee. Finished siblings keep their results."""
        from mythril_tpu.resilience import record_event
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        stats = SolverStatistics()
        for request in batch:
            if request.done:
                continue
            if request.requeues == 0 and not self._stopping:
                request.requeues += 1
                record_event("serve.worker", "worker_requeue")
                stats.add_serve_outcome("requeued")
                with self._cv:
                    self._inflight.remove(request)
                    self._queue.insert(0, request)
                    self._cv.notify_all()
            elif request.resolve({"status": "incomplete",
                                  "reason": reason}):
                record_event("serve.worker", "degraded")
                stats.add_serve_outcome("incomplete")

    @staticmethod
    def _teardown_batch() -> None:
        """Request-teardown unwind (the PR-12 _flush_safely
        finally-resolution generalized): an abandoned batch may have
        left queries parked in the process-global coalescing window —
        resolve every buffered handle (to unknown) so nothing the next
        batch does can hang on a handle nobody will ever flush."""
        from mythril_tpu.service.scheduler import get_scheduler

        scheduler = get_scheduler()
        if scheduler.pending():
            log.warning("unwinding %d parked scheduler handle(s) from "
                        "an abandoned serve batch", scheduler.pending())
            scheduler.clear()

    # -- eviction ------------------------------------------------------------

    def evict_tenant(self, tenant: str, wait_timeout: float = 60.0
                     ) -> bool:
        """Session-scoped invalidation: drop ONE tenant's warm memos
        (memory tiers, quick-sat deques, private blasters, prefix
        snapshots) without flushing the shared strash table, the disk
        tier, or any other tenant's warmth. Waits for the tenant's OWN
        queued/in-flight requests to finish first — evicting under a
        live context would let the context's save/restore reinstall the
        supposedly-evicted memos. Returns False if the tenant stayed
        busy past the wait (nothing evicted; retry later)."""
        from mythril_tpu.service.tenancy import encode_session
        from mythril_tpu.support.model import clear_caches

        from mythril_tpu.service.tenancy import origin_in_session

        session = encode_session(tenant)
        deadline = time.monotonic() + wait_timeout
        with self._cv:
            while any(origin_in_session(request.origin, session)
                      for request in self._queue + self._inflight):
                if time.monotonic() >= deadline:
                    return False
                self._cv.wait(0.2)
            # close the admission window BEFORE releasing the lock: a
            # same-tenant submit landing between the emptiness check
            # and the clear would run a live context during eviction
            self._evicting.add(session)
        try:
            clear_caches(session=session)
        finally:
            with self._cv:
                self._evicting.discard(session)
                self._cv.notify_all()
        return True

    # -- drain ---------------------------------------------------------------

    def healthz(self) -> dict:
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        stats = SolverStatistics()
        with self._cv:
            queued, inflight = len(self._queue), len(self._inflight)
            draining = self._draining or self._stopping
        return {
            "status": "draining" if draining else "ok",
            "queued": queued,
            "in_flight": inflight,
            "queue_max": self.queue_max,
            "requests": {
                "admitted": stats.serve_requests_admitted,
                "rejected": stats.serve_requests_rejected,
                "requeued": stats.serve_requests_requeued,
                "incomplete": stats.serve_requests_incomplete,
                "completed": stats.serve_requests_completed,
            },
        }

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admitting, finish everything already
        admitted, write the final reconciled heartbeat, stop the
        listener. Returns True on a clean drain; on timeout the
        leftovers answer `incomplete` (answered, never hung) and False
        comes back."""
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        start = time.monotonic()
        budget = timeout if timeout is not None else self.drain_timeout_s
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        clean = True
        with self._cv:
            while self._queue or self._inflight:
                if time.monotonic() - start >= budget:
                    clean = False
                    break
                self._cv.wait(0.2)
            self._stopping = True
            self._cv.notify_all()
        if not clean:
            with self._cv:
                leftovers = list(self._queue) + list(self._inflight)
                self._queue.clear()
            stats = SolverStatistics()
            for request in leftovers:
                if request.resolve({"status": "incomplete",
                                    "reason": "drain timeout"}):
                    stats.add_serve_outcome("incomplete")
        if self._worker is not None:
            self._worker.join(timeout=5.0)
        SolverStatistics().add_serve_drain_seconds(
            time.monotonic() - start)
        if self._heartbeat is not None:
            self._heartbeat.stop(final=True)
            self._heartbeat = None
        if self._http is not None:
            self._http.stop()
            self._http = None
        self.drained.set()
        log.info("serve daemon drained in %.2fs (clean=%s)",
                 time.monotonic() - start, clean)
        return clean


def install_signal_handlers(daemon: ServeDaemon) -> None:
    """SIGTERM/SIGINT -> graceful drain (main thread only)."""
    import signal

    def _handler(_signum, _frame):
        threading.Thread(target=daemon.drain, daemon=True).start()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)


def serve_forever(daemon: ServeDaemon) -> int:
    """CLI entry: start, announce the endpoints, block until drained."""
    daemon.start()
    install_signal_handlers(daemon)
    print(f"mythril_tpu serve listening on http://127.0.0.1:{daemon.port}"
          f" (POST /analyze, POST /evict, GET /healthz, GET /metrics);"
          f" SIGTERM drains", flush=True)
    daemon.drained.wait()
    return 0

"""Localhost HTTP listener for the serve daemon.

Endpoints (loopback only — the daemon is an in-datacenter sidecar, not
an internet service; put real auth/TLS termination in front of it):

  GET  /healthz   liveness + drain state + queue depth + the request
                  admission/disposition counters
  GET  /metrics   PR 10's Prometheus text exposition rendered from a
                  FRESH live registry snapshot at scrape time — never
                  the last heartbeat file write, so scrape freshness is
                  independent of MYTHRIL_TPU_HEARTBEAT_INTERVAL (the
                  mythril_tpu_snapshot_ts gauge pins it)
  GET  /snapshot  the raw live snapshot as JSON (metrics.snapshot()) —
                  what the fleet supervisor's per-shard /metrics rollup
                  fetches and merges
  POST /analyze   {"tenant": ..., "code": "0x...", "name"?, "tx_count"?,
                  "deadline_s"?, "bin_runtime"?} -> the request's
                  terminal outcome JSON. Backpressure is an HTTP answer:
                  429 overloaded, 503 draining — never unbounded queue
                  latency.
  POST /evict     {"tenant": ...} -> session-scoped memo eviction.

ThreadingHTTPServer: each client holds one handler thread while its
request is in flight, so N concurrent clients drive the daemon's queue
exactly like the soak harness does.
"""

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

log = logging.getLogger(__name__)

STATUS_CODES = {
    "ok": 200,
    "error": 200,        # answered: the error is the tenant's payload
    "incomplete": 504,
}
REJECT_CODES = {
    "overloaded": 429,
    "draining": 503,
    "evicting": 503,   # transient: retry once the eviction lands
}


def status_code(outcome: dict) -> int:
    """HTTP code for a terminal outcome: rejections map by reason
    (overloaded/draining backpressure; anything else — e.g. malformed
    bytecode — is the client's 400), answered outcomes by status."""
    if outcome.get("status") == "rejected":
        return REJECT_CODES.get(outcome.get("reason"), 400)
    return STATUS_CODES.get(outcome.get("status"), 200)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def daemon(self):
        return self.server.serve_daemon

    def log_message(self, fmt, *args):  # quiet: route through logging
        log.debug("http: " + fmt, *args)

    def _send_json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str,
                   content_type: str = "text/plain; version=0.0.4"
                   ) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> Optional[dict]:
        try:
            length = int(self.headers.get("Content-Length", "0"))
            return json.loads(self.rfile.read(length) or b"{}")
        except Exception:
            return None

    def do_GET(self):
        if self.path == "/healthz":
            health = self.daemon.healthz()
            code = 200 if health["status"] == "ok" else 503
            self._send_json(code, health)
            return
        if self.path == "/metrics":
            # a fresh snapshot per scrape (prometheus_text defaults to
            # one): freshness never depends on the heartbeat cadence
            from mythril_tpu.observe.metrics import prometheus_text

            self._send_text(200, prometheus_text(scrape_stamp=True))
            return
        if self.path == "/snapshot":
            from mythril_tpu.observe.metrics import snapshot

            self._send_json(200, snapshot())
            return
        self._send_json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        if self.path == "/analyze":
            payload = self._read_body()
            if not payload or "code" not in payload:
                self._send_json(400, {"error": "body must be JSON with "
                                               "at least a `code` key"})
                return
            request = self.daemon.submit(
                tenant=payload.get("tenant", "anonymous"),
                code=payload["code"],
                name=payload.get("name"),
                tx_count=payload.get("tx_count"),
                deadline_s=payload.get("deadline_s"),
                bin_runtime=bool(payload.get("bin_runtime", False)),
                modules=payload.get("modules"),
            )
            # wait for the DAEMON's terminal answer rather than
            # fabricating one on a guessed bound: queue wait under load
            # can legitimately exceed any per-request deadline multiple
            # (the daemon's own deadline/requeue/drain machinery is
            # what guarantees resolution). The only synthesized answer
            # is for a daemon that drained away underneath the wait.
            outcome = None
            while outcome is None:
                outcome = request.wait(timeout=30.0)
                if outcome is None and self.daemon.drained.is_set():
                    outcome = request.wait(timeout=5.0) or {
                        "status": "incomplete",
                        "reason": "daemon drained",
                        "request_id": request.request_id}
            self._send_json(status_code(outcome), outcome)
            return
        if self.path == "/evict":
            payload = self._read_body()
            if not payload or "tenant" not in payload:
                self._send_json(400, {"error": "body must be JSON with "
                                               "a `tenant` key"})
                return
            if self.daemon.evict_tenant(payload["tenant"]):
                self._send_json(200, {"status": "ok",
                                      "evicted": payload["tenant"]})
            else:
                self._send_json(409, {"status": "busy",
                                      "tenant": payload["tenant"]})
            return
        self._send_json(404, {"error": f"unknown path {self.path}"})


class ServeHTTP:
    """The daemon's listener: loopback-bound, port 0 = ephemeral (tests
    read `.port` after start)."""

    def __init__(self, daemon, port: int):
        self._server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._server.daemon_threads = True
        self._server.serve_daemon = daemon
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="mythril-serve-http", daemon=True)

    def start(self) -> "ServeHTTP":
        self._thread.start()
        return self

    def stop(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)

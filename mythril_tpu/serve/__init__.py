"""`mythril_tpu serve`: the fault-contained multi-tenant analyzer daemon.

Every warm asset the stack builds — router calibration, XLA compile
cache, disk result tier, session strash table, prefix-snapshot memos —
used to be per-process, so each CLI invocation re-warmed from scratch.
This package is the long-lived loop that amortizes them across requests:

  daemon.py   the request queue in front of MythrilAnalyzer — bounded
              admission with per-tenant budgets and explicit
              `overloaded` backpressure, the PR-12 origin-tagged
              coalescing window promoted to a cross-request multi-tenant
              batcher (per-tenant engine contexts via
              service/tenancy.py), per-request hard deadlines on a
              dedicated runner thread with requeue-once-then-incomplete
              semantics, graceful SIGTERM drain, and the three
              registered fault sites (serve.request / serve.admission /
              serve.worker).
  httpd.py    the localhost HTTP listener: POST /analyze, POST /evict,
              GET /healthz, GET /metrics (PR 10's Prometheus text
              writer as a real endpoint).

Restart posture is crash-only: the daemon persists nothing of its own —
a restarted process re-warms from the durable tiers (disk result store,
router calibration profile, XLA compile cache) under
MYTHRIL_TPU_CACHE_DIR, exactly like any cold CLI invocation, just
faster.
"""

from mythril_tpu.serve.daemon import ServeDaemon, ServeRequest  # noqa: F401

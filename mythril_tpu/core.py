"""Orchestration: per-contract analysis driver
(reference mythril/mythril/mythril_analyzer.py:201 +
mythril_disassembler.py:411, merged into one module — the solc/RPC loading
paths live in solidity/ and ethereum/ and are dispatched from here)."""

import logging
import time
import traceback
from typing import List, Optional

from mythril_tpu.analysis.report import Issue, Report
from mythril_tpu.analysis.security import fire_lasers, retrieve_callback_issues
from mythril_tpu.analysis.symbolic import SymExecWrapper
from mythril_tpu.ethereum.evmcontract import EVMContract
from mythril_tpu.smt.solver.statistics import SolverStatistics
from mythril_tpu.support.args import args
from mythril_tpu.laser.transaction.models import tx_id_manager

log = logging.getLogger(__name__)

ANALYSIS_ADDRESS = 0x901D12EBE1B195E5AA8748E62BD7734AE19B51F  # well-known probe address


class MythrilDisassembler:
    """Loads bytecode into EVMContract objects."""

    def __init__(self, eth=None, enable_online_lookup: bool = False):
        self.eth = eth
        self.contracts: List[EVMContract] = []
        self.enable_online_lookup = enable_online_lookup

    def load_from_bytecode(self, code: str, bin_runtime: bool = False,
                           address: Optional[str] = None,
                           name: Optional[str] = None) -> EVMContract:
        if bin_runtime:
            contract = EVMContract(code=code, name=name or "MAIN")
        else:
            contract = EVMContract(creation_code=code, name=name or "MAIN")
        self.contracts.append(contract)
        return contract

    def load_from_address(self, address: str) -> EVMContract:
        if self.eth is None:
            raise ValueError("no RPC client configured (use --rpc)")
        code = self.eth.eth_getCode(address)
        contract = EVMContract(code=code, name=address)
        self.contracts.append(contract)
        return contract

    def load_from_solidity(self, solidity_files: List[str],
                           solc_version: Optional[str] = None,
                           solc_args: Optional[List[str]] = None):
        from mythril_tpu.solidity.soliditycontract import (
            find_solc_version,
            get_contracts_from_file,
        )

        solc_binary = (
            find_solc_version(solc_version) if solc_version else None
        )
        contracts = []
        for file in solidity_files:
            contracts.extend(
                get_contracts_from_file(file, solc_binary, solc_args))
        self.contracts.extend(contracts)
        return contracts

    def load_from_foundry(self, project_root: Optional[str] = None,
                          run_forge: bool = True):
        """Analyze a foundry project: run `forge build --build-info` and load
        every contract from the build-info artifacts (reference
        mythril_disassembler.py:160-217). With run_forge=False only existing
        artifacts are read — the offline-test path."""
        import json
        import os
        import shutil
        import subprocess

        from mythril_tpu.solidity.soliditycontract import (
            get_contracts_from_foundry,
        )

        project_root = project_root or os.getcwd()
        if run_forge:
            forge = shutil.which("forge")
            if forge is None:
                raise ValueError(
                    "forge binary not found (install foundry or pass "
                    "pre-built artifacts)"
                )
            proc = subprocess.run(
                [forge, "build", "--build-info", "--force"],
                capture_output=True, text=True, cwd=project_root,
            )
            if proc.stderr:
                log.error(proc.stderr)
            if proc.returncode:
                # stale artifacts would silently analyze the OLD bytecode
                raise ValueError(
                    f"forge build failed (rc={proc.returncode}); refusing to "
                    "analyze stale artifacts"
                )
        build_dir = None
        for candidate in (
            os.path.join(project_root, "artifacts", "contracts", "build-info"),
            os.path.join(project_root, "out", "build-info"),
        ):
            if os.path.isdir(candidate):
                build_dir = candidate
                break
        if build_dir is None:
            raise ValueError(
                f"no foundry build-info directory under {project_root} "
                "(did `forge build --build-info` run?)"
            )
        files = sorted(
            (f for f in os.listdir(build_dir) if f.endswith(".json")),
            key=lambda f: os.path.getmtime(os.path.join(build_dir, f)),
        )
        if not files:
            raise ValueError(f"{build_dir} has no build-info artifacts")
        contracts = []
        for file in files:
            with open(os.path.join(build_dir, file), encoding="utf8") as fd:
                build_info = json.load(fd)
            contracts.extend(get_contracts_from_foundry(build_info))
        self.contracts.extend(contracts)
        return contracts

    def get_state_variable_from_storage(
        self, address: str, params: Optional[List[str]] = None
    ) -> str:
        """Read storage slots over RPC, including solidity layout math for
        arrays and mappings (reference mythril_disassembler.py:330-410):
        `[position, length]` reads consecutive slots, `[pos, len, "array"]`
        starts at keccak(pos), `["mapping", pos, key...]` reads
        keccak(key ++ pos) per key."""
        from mythril_tpu.utils.keccak import keccak256

        if self.eth is None:
            raise ValueError("no RPC client configured (use --rpc)")
        params = params or []
        position, length, mappings = 0, 1, []

        def slot_of(data: bytes) -> int:
            return int.from_bytes(keccak256(data), byteorder="big")

        try:
            if params and params[0] == "mapping":
                if len(params) < 3:
                    raise ValueError("mapping requires a position and keys")
                position = int(params[1])
                position_bytes = int(position).to_bytes(32, "big")
                for raw_key in params[2:]:
                    key = raw_key.encode("utf8").ljust(32, b"\x00")
                    mappings.append(slot_of(key + position_bytes))
                length = len(mappings)
                if length == 1:
                    position = mappings[0]
            else:
                if len(params) >= 4:
                    raise ValueError("too many parameters")
                if len(params) >= 1:
                    position = int(params[0])
                if len(params) >= 2:
                    length = int(params[1])
                if len(params) == 3 and params[2] == "array":
                    position = slot_of(int(position).to_bytes(32, "big"))
        except ValueError as error:
            raise ValueError(f"invalid storage index: {error}") from None

        lines = []
        if length == 1:
            lines.append(
                f"{position}: {self.eth.eth_getStorageAt(address, position)}"
            )
        elif mappings:
            for slot in mappings:
                lines.append(
                    f"{hex(slot)}: {self.eth.eth_getStorageAt(address, slot)}"
                )
        else:
            for slot in range(position, position + length):
                lines.append(
                    f"{hex(slot)}: {self.eth.eth_getStorageAt(address, slot)}"
                )
        return "\n".join(lines)


class MythrilAnalyzer:
    """Runs symbolic execution + modules per contract, renders the Report."""

    def __init__(
        self,
        disassembler: MythrilDisassembler,
        cmd_args=None,
        strategy: str = "bfs",
        address: Optional[int] = None,
    ):
        self.contracts = disassembler.contracts
        self.strategy = strategy
        self.eth = disassembler.eth
        self.address = address if address is not None else ANALYSIS_ADDRESS
        # copy CLI args into the global singleton (reference :65-76)
        if cmd_args is not None:
            for field in (
                "solver_timeout", "execution_timeout", "create_timeout",
                "max_depth", "loop_bound", "transaction_count",
                "pruning_factor", "call_depth_limit", "solver_log",
                "unconstrained_storage", "parallel_solving", "disable_iprof",
                "disable_mutation_pruner", "disable_dependency_pruning",
                "enable_state_merging", "enable_summaries", "solver_backend",
                "solve_cache", "transaction_sequences", "beam_width",
                "disable_coverage_strategy", "jobs", "corpus_interleave",
                "no_preanalysis",
                "no_aig_opt", "no_incremental_prep", "no_vmap_frontier",
                "no_ragged", "no_frontier_fork", "trace", "heartbeat",
                "inject_fault",
            ):
                if hasattr(cmd_args, field) and getattr(cmd_args, field) is not None:
                    setattr(args, field, getattr(cmd_args, field))
            if getattr(cmd_args, "disable_incremental_txs", False):
                args.incremental_txs = False
        # auto pruning factor (reference :78-82)
        if args.pruning_factor is None:
            args.pruning_factor = 1.0 if args.execution_timeout > 300 else 0.0

    def fire_lasers(self, modules: Optional[List[str]] = None,
                    transaction_count: Optional[int] = None) -> Report:
        import os

        from mythril_tpu.analysis.module import ModuleLoader
        from mythril_tpu.observe import TRACE_ENV, get_tracer
        from mythril_tpu.observe import flightrec, metrics

        for module in ModuleLoader().get_detection_modules():
            module.reset_module()
            module.reset_cache()
        stats = SolverStatistics()
        stats.enabled = True
        # tuned schedule profile (mythril_tpu/tune/): install the
        # persisted per-platform winner as the knob tuned tier BEFORE
        # any consumer (router, scheduler, backend, frontier) reads its
        # knobs — one-shot per process, explicit env always wins,
        # MYTHRIL_TPU_AUTOTUNE=0 disables
        from mythril_tpu.tune import apply_tuned_profile

        apply_tuned_profile()
        # fault-injection harness (resilience/faults.py): armed from
        # MYTHRIL_TPU_FAULTS or --inject-fault, disarmed when neither is
        # set — one configure per run so crossing counters start fresh
        from mythril_tpu.resilience import faults

        faults.configure_from_env(getattr(args, "inject_fault", None))
        # always-on flight recorder: instantiate the tracer so the span
        # ring records even with --trace unarmed (MYTHRIL_TPU_FLIGHTREC=0
        # opts out and restores the pure no-op span path)
        flightrec.install()
        trace_path = getattr(args, "trace", None) \
            or os.environ.get(TRACE_ENV)
        if trace_path:
            get_tracer().enable(trace_path)
        # live heartbeat stream (--heartbeat / MYTHRIL_TPU_HEARTBEAT):
        # periodic JSONL metrics snapshots while the run is in flight
        heartbeat = metrics.start_heartbeat(
            getattr(args, "heartbeat", None))
        tx_count = transaction_count or args.transaction_count

        # telemetry must survive the run that produced it: stats JSON and
        # the trace are written from the finally, so an execution timeout
        # or a module exception that escapes the per-contract capture no
        # longer loses the whole run's telemetry (the `completed` tag in
        # the JSON says which case the reader is looking at)
        completed = False
        all_issues: List[Issue] = []
        exceptions: List[str] = []
        try:
            interleave_n = self._corpus_interleave_n()
            if args.jobs > 1 and len(self.contracts) > 1 \
                    and self.eth is None:
                if interleave_n >= 1:
                    # worker processes cannot share a coalescing window,
                    # so no cross-contract stream can ever form there —
                    # say so instead of letting xcontract_windows read 0
                    # with no hint why
                    log.warning(
                        "--corpus-interleave is ignored under --jobs > 1 "
                        "(process isolation precludes cross-contract "
                        "windows); drop --jobs to interleave")
                all_issues, exceptions = self._fire_lasers_parallel(
                    modules, tx_count)
            elif interleave_n >= 1 and len(self.contracts) > 1 \
                    and self.eth is None:
                all_issues, exceptions = self._fire_lasers_interleaved(
                    modules, tx_count, stats, interleave_n)
            else:
                for contract in self.contracts:
                    issues, contract_exceptions = \
                        self._analyze_one_contract(
                            contract, modules, tx_count, stats=stats)
                    all_issues.extend(issues)
                    exceptions.extend(contract_exceptions)
            completed = True
        finally:
            if not completed:
                # the run died with work in flight: dump the flight
                # recorder BEFORE the tracer resets, so even a
                # --trace-unarmed crash leaves a diagnosable timeline
                flightrec.notify_run_incomplete()
            if heartbeat is not None:
                # the reconciling final beat: same singleton, same
                # finally as the stats JSON below, so the two agree
                heartbeat.stop(final=True)
            self._dump_stats_json(stats, completed=completed)
            if trace_path:
                tracer = get_tracer()
                tracer.write()
                # a later fire_lasers in this process starts clean: leaving
                # the tracer enabled would keep every span site allocating
                # (and re-export this run's events into the next trace)
                tracer.reset()

        report = Report(
            contracts=self.contracts,
            exceptions=exceptions,
        )
        for issue in all_issues:
            report.append_issue(issue)
        return report

    @staticmethod
    def _dump_stats_json(stats, completed: bool = True) -> None:
        """MYTHRIL_TPU_STATS_JSON=<path>: write the run's SolverStatistics
        (routing counters, device hits/cap-rejects, batch occupancy,
        per-route wall, the roofline section) as one JSON object —
        bench.py reads this from each analyze subprocess so BENCH_r0N.json
        can report where queries actually went. `completed` distinguishes
        a clean run from telemetry salvaged by the finally path."""
        import json
        import os

        path = os.environ.get("MYTHRIL_TPU_STATS_JSON")
        if not path:
            return
        from mythril_tpu.observe import metrics

        payload = stats.as_dict()
        payload["completed"] = bool(completed)
        # self-describing artifact: schema_version + git rev + jax
        # platform, so committed BENCH_r*.json rounds say what built them
        payload.update(metrics.stamp())
        try:
            with open(path, "w") as fd:
                json.dump(payload, fd)
        except OSError:
            log.warning("could not write solver stats to %s", path)

    def _analyze_one_contract(self, contract, modules, tx_count, stats=None):
        """Symbolic execution + modules for ONE contract (the loop body the
        corpus fan-out distributes). Returns (issues, exceptions)."""
        exceptions: List[str] = []
        tx_id_manager.restart_counter()
        from mythril_tpu.laser.function_managers import (
            keccak_function_manager,
        )

        keccak_function_manager.reset()
        contract_start = time.monotonic()
        solver_before = stats.solver_time if stats else 0.0
        device_before = stats.device_stats() if stats else {}
        dynloader = None
        if self.eth is not None:
            from mythril_tpu.support.loader import DynLoader

            dynloader = DynLoader(self.eth)
        from mythril_tpu.observe import span as trace_span

        try:
            with trace_span("analyze.contract", cat="analyze",
                            contract=contract.name):
                sym = SymExecWrapper(
                    contract,
                    self.address,
                    self.strategy,
                    dynloader=dynloader,
                    max_depth=args.max_depth,
                    execution_timeout=args.execution_timeout,
                    loop_bound=args.loop_bound,
                    create_timeout=args.create_timeout,
                    transaction_count=tx_count,
                    modules=modules,
                    compulsory_statespace=False,
                )
                issues = fire_lasers(sym, white_list=modules)
        except KeyboardInterrupt:
            log.critical("keyboard interrupt: retrieving partial results")
            issues = retrieve_callback_issues(modules)
        except Exception:
            log.exception("exception during analysis of %s", contract.name)
            exceptions.append(traceback.format_exc())
            issues = retrieve_callback_issues(modules)
        for issue in issues:
            issue.add_code_info(contract)
            issue.resolve_function_name(_signature_db())
        if stats is not None:
            log.info(str(stats))
            log.info(self._phase_split(contract.name, contract_start,
                                       solver_before, device_before, stats))
        return issues, exceptions

    def _fire_lasers_parallel(self, modules, tx_count):
        """Corpus-level parallelism (reference mythril_analyzer.py:150 is
        the stated fan-out point; BASELINE config 5): independent contracts
        analyzed in -j worker PROCESSES. Process isolation is the correct
        boundary — the engine's process-global state (term intern table,
        shared blaster/AIG, model caches, keccak manager, module
        singletons) makes in-process threading unsound and would serialize
        on the GIL anyway. Spawn (not fork): the parent may hold a jax
        runtime whose threads a fork would deadlock.

        Results stream back via imap_unordered, so a KeyboardInterrupt or a
        worker failure keeps every contract already completed (the old
        pool.map was all-or-nothing: one failure re-ran the WHOLE corpus
        sequentially, potentially doubling wall). Worker failures fall back
        to sequential analysis of ONLY the incomplete contracts; per-worker
        SolverStatistics snapshots are folded into the parent singleton.

        Worker DEATH (a killed/OOMed/crashed worker process, the
        registered jobs.worker fault site) is detected by a liveness
        watchdog while waiting on results — a lost task would otherwise
        hang the imap iterator forever, since the pool silently respawns
        the worker without resubmitting its work. The dead worker's
        pending contracts are requeued into a FRESH pool once; a second
        death degrades the rest to in-process sequential analysis."""
        import multiprocessing as mp

        workers = min(args.jobs, len(self.contracts))
        payloads = [
            (idx, contract, self.address, self.strategy, modules, tx_count,
             dict(args.__dict__))
            for idx, contract in enumerate(self.contracts)
        ]
        from mythril_tpu.observe import get_tracer

        context = mp.get_context("spawn")
        stats = SolverStatistics()
        tracer = get_tracer()
        done = {}  # contract idx -> (issues, exceptions)
        interrupted = False
        try:
            pending = payloads
            requeued = False
            while True:
                try:
                    self._consume_pool(context, workers, pending, done,
                                       stats, tracer)
                    break
                except _PoolWorkerDied:
                    from mythril_tpu import resilience

                    pending = [p for p in payloads if p[0] not in done]
                    if not pending:
                        # the dead worker had nothing in flight (its
                        # results were already consumed): the corpus is
                        # complete, nothing degraded
                        break
                    if not requeued:
                        requeued = True
                        resilience.record_event(
                            "jobs.worker", "worker_requeue", len(pending))
                        log.warning(
                            "a --jobs worker died; requeuing %d pending "
                            "contract(s) into a fresh pool",
                            len(pending))
                        workers = min(workers, len(pending))
                        continue
                    # second death (or nothing left): the in-process
                    # sequential completion below analyzes the rest
                    resilience.record_event("jobs.worker", "degraded")
                    log.warning(
                        "worker died again after the requeue; analyzing "
                        "the %d incomplete contract(s) in-process",
                        len(pending))
                    break
        except KeyboardInterrupt:
            interrupted = True
            log.critical(
                "keyboard interrupt: keeping %d/%d completed contracts",
                len(done), len(payloads))
        except Exception:
            from mythril_tpu import resilience

            resilience.record_event("jobs.worker", "degraded")
            log.exception(
                "parallel corpus analysis failed; sequential fallback for "
                "the %d incomplete contracts", len(payloads) - len(done))
        if interrupted:
            # a report missing contracts must never read as "those were
            # safe": surface each unanalyzed contract as an exception row
            # (Report renders them), mirroring the per-contract capture of
            # the sequential path
            for idx, contract in enumerate(self.contracts):
                if idx not in done:
                    done[idx] = ([], [
                        f"analysis of {contract.name} interrupted before "
                        f"completion (--jobs run): no findings recorded"
                    ])
        else:
            for idx, contract in enumerate(self.contracts):
                if idx not in done:
                    done[idx] = self._analyze_one_contract(
                        contract, modules, tx_count, stats=stats)
        all_issues: List[Issue] = []
        exceptions: List[str] = []
        for idx in range(len(self.contracts)):
            if idx in done:
                issues, contract_exceptions = done[idx]
                all_issues.extend(issues)
                exceptions.extend(contract_exceptions)
        return all_issues, exceptions

    @staticmethod
    def _corpus_interleave_n() -> int:
        """Interleave width for the round-robin corpus driver: env
        override first (MYTHRIL_TPU_CORPUS_INTERLEAVE), then the
        --corpus-interleave flag. 0 = the legacy sequential path;
        1 = the sequential BASELINE (same driver, same per-origin
        isolation, one analysis at a time) the interleaved run's
        findings are compared against; >= 2 = true interleaving."""
        import os

        env = os.environ.get("MYTHRIL_TPU_CORPUS_INTERLEAVE", "")
        if env:
            try:
                return max(0, int(env))
            except ValueError:
                pass
        return max(0, int(getattr(args, "corpus_interleave", 0) or 0))

    def _fire_lasers_interleaved(self, modules, tx_count, stats, slots):
        """Interleaved corpus driver (ROADMAP cross-contract packing):
        up to `slots` contracts' analyses stepped round-robin in ONE
        process on baton-passing threads (service/interleave.py — only
        one thread executes at a time; the win is solve windows that MIX
        origins, not CPU overlap). Each contract's slice of the
        process-global engine state (wall budget, tx ids, keccak state,
        module issue lists, memory/quick-sat solve tiers) is context-
        switched at every handoff, so per-contract findings are
        byte-identical to the sequential (interleave=1) schedule —
        cross-contract reuse flows ONLY through the content-addressed
        persistent tier, whose hits are replay-verified. Sibling queries
        from different contracts park in the coalescing scheduler's
        process-global window and ride ONE ragged device stream
        (xcontract_windows counts the mixed launches)."""
        import threading

        from mythril_tpu.service import interleave

        slots = max(1, min(slots, len(self.contracts)))
        done = {}

        def analyze_one(idx, contract):
            done[idx] = self._analyze_one_contract(
                contract, modules, tx_count, stats=stats)

        coordinator = interleave.Coordinator(
            list(enumerate(self.contracts)))
        interleave.install(coordinator)
        log.info("interleaved corpus driver: %d contracts over %d "
                 "slot(s), quantum %d exec iterations",
                 len(self.contracts), slots, coordinator.quantum)
        threads = []
        try:
            for slot_id in range(slots):
                thread = threading.Thread(
                    target=coordinator.run_slot,
                    args=(slot_id, analyze_one),
                    name=f"mythril-interleave-{slot_id}")
                thread.start()
                threads.append(thread)
            for thread in threads:
                thread.join()
        finally:
            interleave.uninstall()
        all_issues: List[Issue] = []
        exceptions: List[str] = []
        for idx, contract in enumerate(self.contracts):
            if idx not in done:
                # a slot thread died outside the per-contract capture
                # (should not happen — _analyze_one_contract catches):
                # surface the gap instead of reading as "safe"
                exceptions.append(
                    f"analysis of {contract.name} never completed "
                    f"(interleaved corpus run)")
                continue
            issues, contract_exceptions = done[idx]
            all_issues.extend(issues)
            exceptions.extend(contract_exceptions)
        return all_issues, exceptions

    @staticmethod
    def _consume_pool(context, workers, payloads, done, stats, tracer):
        """One pool generation: stream results off imap_unordered into
        `done`, folding worker stats/trace snapshots into the parent.
        Raises _PoolWorkerDied when the liveness watchdog sees a worker
        process die (its in-flight task is lost — the pool respawns the
        worker but never resubmits the work, so waiting would hang)."""
        with context.Pool(processes=workers) as pool:
            iterator = pool.imap_unordered(_corpus_worker, payloads)
            watchdog = _PoolWatchdog(pool)
            while True:
                try:
                    result = MythrilAnalyzer._next_result(
                        iterator, watchdog)
                except StopIteration:
                    return
                idx, issues, contract_exceptions, stats_snapshot, \
                    trace_events = result
                done[idx] = (issues, contract_exceptions)
                stats.absorb(stats_snapshot)
                # worker spans carry their own pid: each worker gets
                # its own process lane in the merged timeline
                tracer.absorb_events(trace_events)

    _POOL_POLL_S = 0.25

    @staticmethod
    def _next_result(iterator, watchdog):
        """Next streamed result, polling so the watchdog can observe
        worker death between waits. Iterators without a timeout-taking
        .next (plain generators — scripted pools in tests) are consumed
        directly; multiprocessing's IMapUnorderedIterator exposes one."""
        timed_next = getattr(iterator, "next", None)
        if timed_next is None:
            return next(iterator)
        import multiprocessing as mp

        while True:
            try:
                return timed_next(timeout=MythrilAnalyzer._POOL_POLL_S)
            except mp.TimeoutError:
                watchdog.check()

    @staticmethod
    def _phase_split(name, contract_start, solver_before, device_before,
                     stats) -> str:
        """Per-contract wall-clock split: interpreter / host solver / device
        pack+ship / device solve. The architecture dial for batching work:
        whichever phase dominates is what the next kernel targets."""
        wall = time.monotonic() - contract_start
        solver_s = stats.solver_time - solver_before
        device = stats.device_stats()

        def delta(key):
            return device.get(key, 0.0) - device_before.get(key, 0.0)

        pack_s = delta("pack_seconds")
        ship_s = delta("ship_seconds")
        solve_s = delta("solve_seconds")
        # solver_time already folds in the device phases (add_batch records
        # the full get_models_batch wall) — subtract once, not twice
        interp_s = max(wall - solver_s, 0.0)
        host_solver_s = max(solver_s - pack_s - ship_s - solve_s, 0.0)
        return (
            f"phase split [{name}]: wall={wall:.2f}s "
            f"interpreter={interp_s:.2f}s host-solver={host_solver_s:.2f}s "
            f"device-pack={pack_s:.2f}s device-ship={ship_s:.2f}s "
            f"device-solve={solve_s:.2f}s"
        )

    def dump_statespace(self, contract=None) -> str:
        """JSON statespace dump (reference mythril_analyzer.py:84)."""
        from mythril_tpu.analysis.traceexplore import get_serializable_statespace

        contract = contract or self.contracts[0]
        sym = SymExecWrapper(
            contract,
            self.address,
            self.strategy,
            max_depth=args.max_depth,
            execution_timeout=args.execution_timeout,
            transaction_count=args.transaction_count,
            compulsory_statespace=True,
        )
        import json

        return json.dumps(get_serializable_statespace(sym))

    def graph_html(self, contract=None, enable_physics: bool = False) -> str:
        """Interactive vis.js CFG html (reference mythril_analyzer.py:105)."""
        from mythril_tpu.analysis.callgraph import generate_graph

        contract = contract or self.contracts[0]
        sym = SymExecWrapper(
            contract,
            self.address,
            self.strategy,
            max_depth=args.max_depth,
            execution_timeout=args.execution_timeout,
            transaction_count=args.transaction_count,
            compulsory_statespace=True,
        )
        return generate_graph(sym, physics=enable_physics)


class _PoolWorkerDied(Exception):
    """A --jobs worker process died with work in flight."""


class _PoolWatchdog:
    """Detects worker-process death in a multiprocessing.Pool. Two
    observable signatures, either sufficient: a worker with an exitcode
    (died, not yet reaped by the pool's maintenance thread), or the
    worker pid set changing (the pool silently respawned a replacement —
    which is exactly the case that loses the in-flight task)."""

    def __init__(self, pool):
        self._pool = pool
        self._pids = self._snapshot()

    def _snapshot(self):
        return frozenset(
            worker.pid for worker in getattr(self._pool, "_pool", ()))

    def check(self) -> None:
        workers = getattr(self._pool, "_pool", ())
        if any(worker.exitcode is not None for worker in workers) \
                or self._snapshot() != self._pids:
            raise _PoolWorkerDied("a --jobs worker process died")


def _corpus_worker(payload):
    """Spawn-process entry for one contract of a parallel corpus run.

    Rebuilds the args singleton from the parent's snapshot (spawn starts
    from a fresh interpreter), resets the per-process module/solver state,
    and runs the standard single-contract path. Returns (idx, issues,
    exceptions, stats snapshot, trace events) — all plain data, pickles
    back to the parent, which aggregates the solver statistics and merges
    the trace spans (pid-lane per worker) across workers."""
    import os

    from mythril_tpu.observe import TRACE_ENV, get_tracer

    idx, contract, address, strategy, modules, tx_count, args_state = payload
    args.__dict__.update(args_state)
    args.jobs = 1  # workers never re-fan-out
    # each spawn worker re-arms the fault harness from the same spec the
    # parent read (fresh interpreter, fresh crossing counters) and crosses
    # the jobs.worker site once — `exit` plans kill the worker here, the
    # shape a crashed/OOMed worker presents to the parent's watchdog
    from mythril_tpu.resilience import faults, maybe_inject

    faults.configure_from_env(getattr(args, "inject_fault", None))
    maybe_inject("jobs.worker")
    from mythril_tpu.analysis.module import ModuleLoader

    for module in ModuleLoader().get_detection_modules():
        module.reset_module()
        module.reset_cache()
    stats = SolverStatistics()
    stats.enabled = True
    # workers resolve knobs through the same tuned tier as the parent
    # (spawn starts a fresh interpreter — the parent's applied profile
    # does not cross the process boundary by itself)
    from mythril_tpu.tune import apply_tuned_profile

    apply_tuned_profile()
    # always-on ring in the worker too: a worker that trips a breaker or
    # a deadline dumps its own flight-recorder artifact (per-pid files)
    from mythril_tpu.observe import flightrec

    flightrec.install()
    if getattr(args, "trace", None) or os.environ.get(TRACE_ENV):
        # collect-only: the parent writes the merged timeline
        get_tracer().enable(None)
    disassembler = MythrilDisassembler()
    disassembler.contracts.append(contract)
    analyzer = MythrilAnalyzer(disassembler, strategy=strategy,
                               address=address)
    issues, exceptions = analyzer._analyze_one_contract(
        contract, modules, tx_count, stats=stats)
    return (idx, issues, exceptions, stats.as_dict(),
            get_tracer().drain_events())


def _signature_db():
    try:
        from mythril_tpu.support.signatures import SignatureDB

        return SignatureDB()
    except Exception:
        return None

"""Always-on flight recorder: a bounded ring of recent spans + resilience
events, auto-dumped as a post-mortem artifact when something trips.

The span tracer (tracer.py) answers "what happened" only when
MYTHRIL_TPU_TRACE was armed BEFORE the run — so the wedged-device round
that most needs a timeline is exactly the one that has none. The flight
recorder closes that gap the way avionics do: a fixed-size ring buffer
records the most recent spans at all times (the tracer feeds it whether
or not full tracing is armed, inside the same <10 µs/site budget the
tier-1 overhead guard enforces), and the ring is dumped to disk
automatically at the first sign of trouble:

  trigger                       where it fires
  breaker_trip                  resilience/breaker.py _trip (any site)
  deadline                      resilience/deadline.py run_with_deadline
  run incomplete                fire_lasers' finally with completed=False
                                (module exception / execution timeout)

Each dump is a self-describing JSON artifact (metrics.stamp(): schema
version, git rev, platform) carrying the trigger, the ring contents in
time order, and the per-site resilience event counts at dump time. Dumps
are capped per process (MAX_DUMPS) so a flapping stage cannot fill the
disk; the FIRST dumps are the interesting ones anyway — the ring at the
first trip shows what led up to it.

Knobs: MYTHRIL_TPU_FLIGHTREC=0 disables the recorder entirely (span()
reverts to the shared no-op object); MYTHRIL_TPU_FLIGHTREC_DIR picks the
dump directory (default: the system temp dir); MYTHRIL_TPU_FLIGHTREC_CAP
sizes the ring (default 512 events).
"""

import json
import logging
import os
import tempfile
import time
from typing import Optional

log = logging.getLogger(__name__)

FLIGHTREC_ENV = "MYTHRIL_TPU_FLIGHTREC"
DIR_ENV = "MYTHRIL_TPU_FLIGHTREC_DIR"
CAP_ENV = "MYTHRIL_TPU_FLIGHTREC_CAP"
DEFAULT_CAP = 512
MAX_DUMPS = 4

# resilience event names that auto-dump the ring; the lint
# (tools/check_fault_sites.py) pins this as a subset of the registered
# resilience event vocabulary so a renamed event cannot silently
# disconnect the recorder
TRIGGER_EVENTS = ("breaker_trip", "deadline")
RUN_INCOMPLETE = "run_incomplete"

_dumps_written = 0


def enabled() -> bool:
    return os.environ.get(FLIGHTREC_ENV, "1") != "0"


def ring_capacity() -> int:
    """Ring size in events; 0 disables the recorder (and restores the
    tracer's pure no-op disabled path)."""
    if not enabled():
        return 0
    try:
        return max(int(os.environ.get(CAP_ENV, DEFAULT_CAP)), 0)
    except ValueError:
        return DEFAULT_CAP


def install() -> None:
    """Ensure the tracer singleton (and with it the ring) exists — called
    at analyzer start (fire_lasers) and in every --jobs worker. Without
    this, span() short-circuits on Tracer._instance is None and the ring
    never sees a single event."""
    from mythril_tpu.observe.tracer import get_tracer

    get_tracer()


def notify(site: str, event: str) -> Optional[str]:
    """Resilience-event hook (called from resilience.record_event AFTER
    the event itself entered the ring): dump the ring when `event` is a
    registered trigger. Returns the dump path when one was written."""
    if event not in TRIGGER_EVENTS:
        return None
    return _dump({"site": site, "event": event})


def notify_run_incomplete() -> Optional[str]:
    """fire_lasers' finally saw completed=False: the run died with work
    in flight — dump whatever the ring holds before the tracer resets."""
    return _dump({"site": "analyze.run", "event": RUN_INCOMPLETE})


def dump_now(reason: str = "manual") -> Optional[str]:
    """Operator hook: dump the ring on demand."""
    return _dump({"site": "operator", "event": reason})


def _dump(trigger: dict) -> Optional[str]:
    global _dumps_written
    # ring_capacity() folds both knobs: FLIGHTREC=0 and FLIGHTREC_CAP=0
    # each disable the recorder — a dump with no ring is an empty file
    if ring_capacity() <= 0 or _dumps_written >= MAX_DUMPS:
        return None
    # the recorder must never turn a degradation into a failure: this is
    # called from INSIDE resilience.record_event while a breaker/deadline
    # is mid-degradation, so nothing here may escape — including a
    # snapshot racing another thread's first event at a new site
    try:
        from mythril_tpu.observe import metrics
        from mythril_tpu.observe.tracer import Tracer
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        tracer = Tracer._instance
        events = tracer.ring_events() if tracer is not None else []
        stats = SolverStatistics()
        payload = metrics.stamp()
        payload.update({
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "trigger": trigger,
            "ring_capacity": ring_capacity(),
            "events": events,
            "resilience": {site: dict(site_events) for site, site_events
                           in list(stats.resilience_events.items())},
        })
        directory = os.environ.get(DIR_ENV) or tempfile.gettempdir()
        path = os.path.join(
            directory,
            f"mythril_tpu_flightrec_{os.getpid()}_{_dumps_written}.json")
        os.makedirs(directory, exist_ok=True)
        # O_EXCL: the default dir is the world-writable system temp dir
        # and the name is predictable — never follow a pre-planted
        # symlink (CWE-377); if the name is taken, fall back to a
        # randomized one from mkstemp
        try:
            handle = os.open(path,
                             os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
        except OSError:
            handle, path = tempfile.mkstemp(
                prefix=f"mythril_tpu_flightrec_{os.getpid()}_",
                suffix=".json", dir=directory)
        with os.fdopen(handle, "w") as fd:
            json.dump(payload, fd)
    except Exception as error:
        log.warning("flight-recorder dump failed (%s)", error)
        return None
    _dumps_written += 1
    log.warning(
        "flight recorder dumped %d recent events to %s "
        "(trigger: %s at %s)", len(events), path,
        trigger.get("event"), trigger.get("site"))
    return path


def reset() -> None:
    """Testing hook: allow MAX_DUMPS fresh dumps."""
    global _dumps_written
    _dumps_written = 0

"""Pipeline observability: hierarchical span tracing + roofline accounting.

Two halves, both riding the same SolverStatistics emission path so bench
rounds can say WHERE the remaining gap is instead of just the wall:

  tracer.py    a thread-safe hierarchical span tracer instrumenting every
               pipeline stage (analyze -> LASER exec -> frontier/fallback
               -> solver prepare -> router -> pack/ship/kernel/settle ->
               cache tiers -> scheduler flushes), exported as a
               Chrome-trace-event / Perfetto JSON timeline
               (MYTHRIL_TPU_TRACE=<path>), pid/tid-mapped so --jobs
               workers merge into one timeline. Near-zero cost when
               disabled: span() returns one shared no-op object.
  roofline.py  per-stage attained-vs-attainable throughput against
               ceilings derived from the router's persisted
               micro-calibration profile (cells/s for the kernel, bytes/s
               for pack/ship, a calibrated CDCL rate for settle), plus a
               reconciled solver-wall decomposition whose components sum
               to the measured total. Emitted in the stats JSON under
               "roofline"; bench.py ranks the top gap stages per leg.
  metrics.py   the LIVE plane on top of both: a typed metrics registry
               (counter/gauge/histogram) unifying SolverStatistics
               scalars, resilience events, and roofline figures into one
               snapshot; a daemon-thread heartbeat appending JSONL
               snapshots (MYTHRIL_TPU_HEARTBEAT / --heartbeat) with
               schema_version + git rev + platform stamps; a Prometheus
               text-exposition writer (MYTHRIL_TPU_PROM).
  flightrec.py always-on flight recorder: a bounded ring of recent spans
               + resilience events fed by the tracer even with
               MYTHRIL_TPU_TRACE unarmed, auto-dumped as a post-mortem
               artifact on breaker trips, stage deadlines, or an
               incomplete run.
"""

from mythril_tpu.observe.tracer import (  # noqa: F401 (public API)
    TRACE_ENV,
    Tracer,
    get_tracer,
    span,
    traced,
)

"""Live metrics plane: typed registry, heartbeat stream, Prometheus text.

PR 7's spans and roofline are exit-time artifacts: a wedged round yields
telemetry only after the run (or never). This module is the LIVE view —
the piece the `mythril_tpu serve` daemon's `/metrics` endpoint will sit
on, testable today:

  registry     every emitted metric declared as a typed Instrument
               (counter / gauge / histogram) with its source and whether
               bench.py's roll-up must carry it. The registry does not
               re-instrument the pipeline — SolverStatistics stays the
               single write path — it ENUMERATES the live view so the
               no-orphan-instruments lint (tools/check_stats_keys.py)
               can prove every instrument reaches the stats JSON, the
               heartbeat snapshot, and (where benchmarked) the bench
               roll-up.
  snapshot()   one point-in-time reading of everything the registry
               names: SolverStatistics scalars (monotone counters —
               they only grow within a run), occupancy gauges, roofline
               attained/attainable per stage, the per-site resilience
               events, and the run stamp.
  heartbeat    a daemon thread appending snapshot JSONL lines every
               MYTHRIL_TPU_HEARTBEAT_INTERVAL seconds to the
               MYTHRIL_TPU_HEARTBEAT (or --heartbeat) path, so "what is
               this process doing RIGHT NOW" has an answer mid-run. The
               final beat (written from fire_lasers' finally) carries
               final=true and reconciles with the exit stats JSON by
               construction: both sample the same singleton.
  prometheus   text-exposition rendering of a snapshot; with
               MYTHRIL_TPU_PROM=<path> the heartbeat atomically rewrites
               the exposition file each beat — point a node-exporter
               textfile collector (or the future serve daemon) at it.

Every snapshot and stats JSON is stamped with `schema_version`, the git
revision, and the jax platform (stamp()), so committed BENCH_r*.json
rounds and salvaged post-mortems are self-describing.
"""

import json
import logging
import os
import re
import sys
import threading
import time
from typing import NamedTuple, Optional, Tuple

from mythril_tpu.support.env import env_float

log = logging.getLogger(__name__)

# bump when the snapshot/stats-JSON envelope changes shape (keys moved or
# re-typed — additive keys do not bump)
SCHEMA_VERSION = 1

HEARTBEAT_ENV = "MYTHRIL_TPU_HEARTBEAT"
INTERVAL_ENV = "MYTHRIL_TPU_HEARTBEAT_INTERVAL"
PROM_ENV = "MYTHRIL_TPU_PROM"
DEFAULT_INTERVAL_S = 10.0


class Instrument(NamedTuple):
    name: str          # metric name (SolverStatistics field for source=stats)
    kind: str          # counter | gauge | histogram
    unit: str
    source: str        # stats | roofline | resilience
    benchmarked: bool  # must have a bench.py ROUTING_KEYS row


# gauges derived from counters (SolverStatistics properties) and the
# non-scalar histograms as_dict() already emits; counters/timers are
# enumerated from SolverStatistics itself so a new counter is registered
# by construction — the lint closes the loop in the other direction
# (every instrument must reach every consumer)
_GAUGE_NAMES = (
    "device_occupancy", "coalesce_occupancy", "frontier_batch_occupancy",
    "serve_tenant_window_share")
_HISTOGRAM_NAMES = ("prepare_suffix_hist", "interp_opcode_wall")
_ROOFLINE_FIELDS = ("attained", "attainable", "sol_gap_s")


def _build_registry() -> Tuple[Instrument, ...]:
    from mythril_tpu.observe import roofline
    from mythril_tpu.smt.solver.statistics import SolverStatistics

    instruments = [
        Instrument(name, "counter", "1", "stats", True)
        for name in SolverStatistics._COUNTERS
    ]
    instruments += [
        Instrument(name, "counter", "seconds", "stats", True)
        for name in SolverStatistics._TIMERS
    ]
    instruments += [
        Instrument(name, "gauge", "ratio", "stats", False)
        for name in _GAUGE_NAMES
    ]
    instruments += [
        Instrument(name, "histogram", "1", "stats", False)
        for name in _HISTOGRAM_NAMES
    ]
    for stage in roofline.STAGES:
        for field in _ROOFLINE_FIELDS:
            unit = "seconds" if field == "sol_gap_s" else "per_second"
            instruments.append(Instrument(
                f"roofline.{stage}.{field}", "gauge", unit, "roofline",
                False))
    # the per-(site, event) breakdown behind the resilience_* scalars
    instruments.append(
        Instrument("resilience_events", "counter", "1", "resilience",
                   False))
    return tuple(instruments)


REGISTRY: Tuple[Instrument, ...] = _build_registry()


def snapshot_covers(instrument: Instrument, snap: dict) -> bool:
    """Does this heartbeat snapshot carry the instrument? One shared
    answer for the no-orphan-instruments lint and the tests."""
    if instrument.source == "stats":
        section = {"counter": "counters", "gauge": "gauges",
                   "histogram": "histograms"}[instrument.kind]
        return instrument.name in snap.get(section, {})
    if instrument.source == "roofline":
        # stage names may themselves be dotted ("frontier.fork"): the
        # field is the LAST component, the stage everything between the
        # "roofline." prefix and it
        stem, field = instrument.name.rsplit(".", 1)
        stage = stem.split(".", 1)[1]
        return field in snap.get("roofline", {}).get(stage, {})
    if instrument.source == "resilience":
        return isinstance(snap.get("resilience"), dict)
    return False


# -- run stamp (shared by heartbeat, stats JSON, flight recorder) -------------

_git_rev_cache: Optional[str] = None


def git_revision() -> str:
    """Current git revision, read straight from .git (no subprocess —
    stamps happen on telemetry paths that must never block). "unknown"
    outside a checkout."""
    global _git_rev_cache
    if _git_rev_cache is not None:
        return _git_rev_cache
    _git_rev_cache = "unknown"
    root = os.path.dirname(os.path.abspath(__file__))
    for _ in range(8):
        git_dir = os.path.join(root, ".git")
        if os.path.isdir(git_dir):
            _git_rev_cache = _read_git_rev(git_dir)
            break
        parent = os.path.dirname(root)
        if parent == root:
            break
        root = parent
    return _git_rev_cache


def _read_git_rev(git_dir: str) -> str:
    try:
        with open(os.path.join(git_dir, "HEAD")) as fd:
            head = fd.read().strip()
        if not head.startswith("ref:"):
            return head[:40] or "unknown"
        ref = head.split(None, 1)[1]
        ref_path = os.path.join(git_dir, *ref.split("/"))
        if os.path.isfile(ref_path):
            with open(ref_path) as fd:
                return fd.read().strip()[:40] or "unknown"
        packed = os.path.join(git_dir, "packed-refs")
        if os.path.isfile(packed):
            with open(packed) as fd:
                for line in fd:
                    parts = line.strip().split()
                    if len(parts) == 2 and parts[1] == ref:
                        return parts[0][:40]
    except OSError:
        pass
    return "unknown"


def jax_platform() -> Optional[str]:
    """The jax backend platform, WITHOUT forcing jax (or a backend) to
    initialize — a telemetry stamp must never be the thing that wakes a
    wedged tunnel. None when jax was never imported; "uninitialized"
    when jax is loaded but no backend has materialized yet."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        bridge = sys.modules.get("jax._src.xla_bridge")
        if bridge is not None and getattr(bridge, "_backends", None):
            return jax.default_backend()
    except Exception:
        pass
    return "uninitialized"


def stamp() -> dict:
    """The self-description every telemetry artifact carries: heartbeat
    snapshots, the MYTHRIL_TPU_STATS_JSON dump (so committed BENCH
    rounds say what produced them), and flight-recorder dumps."""
    return {
        "schema_version": SCHEMA_VERSION,
        "git_rev": git_revision(),
        "platform": jax_platform(),
    }


# -- snapshots ----------------------------------------------------------------


def snapshot(seq: int = 0, final: bool = False) -> dict:
    """One live reading of every registered instrument. Counters are
    monotone within a run (they sample the growing SolverStatistics
    singleton); `seq` and `ts` let a reader order and gap-check the
    stream; `final` marks the reconciling last beat."""
    from mythril_tpu.observe import roofline
    from mythril_tpu.resilience import registry as fault_registry
    from mythril_tpu.smt.solver.statistics import SolverStatistics

    stats = SolverStatistics()
    counters = {name: getattr(stats, name)
                for name in SolverStatistics._COUNTERS}
    counters.update({name: round(getattr(stats, name), 4)
                     for name in SolverStatistics._TIMERS})
    gauges = {name: round(getattr(stats, name), 4)
              for name in _GAUGE_NAMES}
    histograms = {
        "prepare_suffix_hist": dict(stats.prepare_suffix_hist),
        "interp_opcode_wall": {
            op: [count, round(seconds, 4)]
            for op, (count, seconds) in stats.interp_opcode_wall.items()},
    }
    roof = roofline.build(stats)
    roofline_view = {
        stage: {field: row.get(field) for field in _ROOFLINE_FIELDS}
        for stage, row in roof.get("stages", {}).items()
    }
    # stable zero-filled shape, like the stats JSON resilience section
    sites = {name: dict(stats.resilience_events.get(name, {}))
             for name in fault_registry.FAULT_SITES}
    for site, events in stats.resilience_events.items():
        sites.setdefault(site, dict(events))
    # the resolved knob configuration (value + source per knob): a
    # heartbeat stream is attributable to its schedule the same way the
    # exit stats JSON is (mythril_tpu/tune/space.py)
    from mythril_tpu.tune import space as tune_space

    snap = stamp()
    snap.update({
        "seq": seq,
        "ts": round(time.time(), 3),
        "pid": os.getpid(),
        "final": bool(final),
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "roofline": roofline_view,
        "resilience": sites,
        "knobs": tune_space.resolved_config(),
    })
    return snap


def merge_snapshots(snaps) -> dict:
    """Fold N process snapshots into one fleet view (the supervisor's
    /metrics rollup): counters and timers SUM, histograms and per-site
    resilience events merge, and the ratio gauges are RECOMPUTED from
    the merged counters — averaging per-process ratios would weight an
    idle shard equally with a loaded one. The roofline view is omitted:
    per-stage attainable rates are calibrated per process and do not
    add across machines. The stamp/seq/ts come from the newest
    snapshot, so the exposition's freshness gauge reflects the most
    recent reading in the merge."""
    from mythril_tpu.smt.solver.statistics import SolverStatistics

    snaps = [snap for snap in snaps if snap]
    if not snaps:
        return snapshot()
    merged = dict(max(snaps, key=lambda s: s.get("ts", 0)))
    counters: dict = {}
    for name in SolverStatistics._COUNTERS:
        counters[name] = sum(
            int(snap.get("counters", {}).get(name, 0)) for snap in snaps)
    for name in SolverStatistics._TIMERS:
        counters[name] = round(sum(
            float(snap.get("counters", {}).get(name, 0.0))
            for snap in snaps), 4)
    merged["counters"] = counters

    def _ratio(numerator: float, denominator: float) -> float:
        return round(numerator / denominator, 4) if denominator else 0.0

    merged["gauges"] = {
        "device_occupancy": _ratio(
            counters["device_dispatched_queries"],
            counters["device_slots"]),
        "coalesce_occupancy": _ratio(
            counters["coalesced_queries"], counters["window_flushes"]),
        "frontier_batch_occupancy": _ratio(
            counters["frontier_states_stepped"]
            + counters["frontier_batch_bails"]
            + counters["frontier_fork_cohort_rows"],
            counters["frontier_batch_slots"]),
        "serve_tenant_window_share": _ratio(
            counters["serve_batch_requests"],
            counters["serve_batch_tenants"]),
    }
    histograms: dict = {name: {} for name in _HISTOGRAM_NAMES}
    for snap in snaps:
        for name, buckets in (snap.get("histograms") or {}).items():
            section = histograms.setdefault(name, {})
            for bucket, value in buckets.items():
                if isinstance(value, (list, tuple)):
                    record = section.setdefault(bucket, [0, 0.0])
                    record[0] += int(value[0])
                    record[1] = round(record[1] + float(value[1]), 4)
                else:
                    section[bucket] = section.get(bucket, 0) + int(value)
    merged["histograms"] = histograms
    sites: dict = {}
    for snap in snaps:
        for site, events in (snap.get("resilience") or {}).items():
            per_site = sites.setdefault(site, {})
            for event, count in events.items():
                per_site[event] = per_site.get(event, 0) + int(count)
    merged["resilience"] = sites
    merged["roofline"] = {}
    merged["pid"] = os.getpid()
    merged["final"] = all(snap.get("final") for snap in snaps)
    return merged


# -- Prometheus text exposition -----------------------------------------------

_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return "mythril_tpu_" + _PROM_NAME_RE.sub("_", name)


def _prom_escape(value) -> str:
    return str(value).replace("\\", r"\\").replace('"', r'\"')


def prometheus_text(snap: Optional[dict] = None,
                    scrape_stamp: bool = False) -> str:
    """Render a snapshot in the Prometheus text exposition format — the
    payload the serve daemon's /metrics endpoint will return, written to
    a file today (MYTHRIL_TPU_PROM) for a textfile collector.

    scrape_stamp=True additionally emits the mythril_tpu_snapshot_ts
    freshness gauge; only the LIVE scrape paths (daemon /metrics, fleet
    rollup) set it — the file-based exposition stays byte-deterministic
    for identical counter state, and a file could not prove freshness
    anyway."""
    snap = snap or snapshot()
    lines = [
        "# HELP mythril_tpu_build_info run stamp (constant 1)",
        "# TYPE mythril_tpu_build_info gauge",
        'mythril_tpu_build_info{git_rev="%s",platform="%s",'
        'schema_version="%d"} 1' % (
            _prom_escape(snap.get("git_rev", "unknown")),
            _prom_escape(snap.get("platform") or "none"),
            snap.get("schema_version", SCHEMA_VERSION)),
    ]
    # scrape-freshness stamp: the wall-clock second this snapshot was
    # taken. /metrics renders a FRESH snapshot per scrape, so the gauge
    # tracking scrape time is the pinned liveness property (a stale
    # file-based exposition would show this value freeze)
    ts = snap.get("ts") if scrape_stamp else None
    if ts is not None:
        lines.append("# TYPE mythril_tpu_snapshot_ts gauge")
        lines.append(f"mythril_tpu_snapshot_ts {ts}")
    for name, value in sorted(snap.get("counters", {}).items()):
        prom = _prom_name(name)
        lines.append(f"# TYPE {prom} counter")
        lines.append(f"{prom} {value}")
    for name, value in sorted(snap.get("gauges", {}).items()):
        prom = _prom_name(name)
        lines.append(f"# TYPE {prom} gauge")
        lines.append(f"{prom} {value}")
    for name, buckets in sorted(snap.get("histograms", {}).items()):
        prom = _prom_name(name)
        lines.append(f"# TYPE {prom} gauge")
        for bucket, value in sorted(buckets.items()):
            # interp_opcode_wall buckets are [count, seconds] pairs;
            # suffix-hist buckets are plain counts
            count = value[0] if isinstance(value, (list, tuple)) else value
            lines.append(
                f'{prom}{{bucket="{_prom_escape(bucket)}"}} {count}')
    roof_rows = sorted(snap.get("roofline", {}).items())
    for field in _ROOFLINE_FIELDS:
        prom = _prom_name(f"roofline_{field}")
        lines.append(f"# TYPE {prom} gauge")
        for stage, row in roof_rows:
            value = row.get(field)
            if value is not None:
                lines.append(
                    f'{prom}{{stage="{_prom_escape(stage)}"}} {value}')
    prom = _prom_name("resilience_events")
    lines.append(f"# TYPE {prom} counter")
    for site, events in sorted(snap.get("resilience", {}).items()):
        for event, count in sorted(events.items()):
            lines.append(
                f'{prom}{{site="{_prom_escape(site)}",'
                f'event="{_prom_escape(event)}"}} {count}')
    return "\n".join(lines) + "\n"


def write_prometheus(path: str, snap: Optional[dict] = None) -> bool:
    """Atomically (re)write the exposition file — a scraper must never
    read a torn half-write."""
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fd:
            fd.write(prometheus_text(snap))
        os.replace(tmp, path)
        return True
    except OSError as error:
        log.warning("could not write prometheus exposition to %s (%s)",
                    path, error)
        return False


# -- heartbeat ----------------------------------------------------------------


class Heartbeat:
    """Daemon-thread JSONL metrics stream. One writer per process (the
    analyzer's fire_lasers); --jobs workers do not heartbeat — their
    counters reach the parent through the existing stats absorb and show
    up in the beats that follow the merge."""

    # floor for any configured cadence: a zero/negative interval (env
    # typo) must never turn the daemon into a busy loop appending
    # snapshots continuously
    MIN_INTERVAL_S = 0.05

    def __init__(self, path: str, interval_s: Optional[float] = None,
                 prom_path: Optional[str] = None):
        self.path = path
        resolved = (interval_s if interval_s and interval_s > 0
                    else env_float(INTERVAL_ENV, DEFAULT_INTERVAL_S))
        if resolved <= 0:
            resolved = DEFAULT_INTERVAL_S
        self.interval_s = max(resolved, self.MIN_INTERVAL_S)
        self.prom_path = prom_path or os.environ.get(PROM_ENV) or None
        self.beats = 0
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, name="mythril-tpu-heartbeat", daemon=True)

    def start(self) -> "Heartbeat":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.beat()

    def beat(self, final: bool = False) -> Optional[dict]:
        """Append one snapshot line (and refresh the Prometheus file).
        Serialized under a lock so the final beat from stop() cannot
        interleave with a timer beat. NEVER raises: a telemetry beat
        racing a counter mutation (snapshot() walks shared dicts other
        threads grow) must not kill the stream — and the final beat runs
        in fire_lasers' finally, where an escape would mask the run's
        real exception and cost the stats JSON behind it."""
        with self._lock:
            try:
                snap = snapshot(seq=self.beats, final=final)
                line = json.dumps(snap)
                with open(self.path, "a") as fd:
                    fd.write(line + "\n")
            except Exception as error:
                log.warning("heartbeat beat to %s failed (%s)",
                            self.path, error)
                return None
            self.beats += 1
            if self.prom_path:
                write_prometheus(self.prom_path, snap)
            return snap

    def stop(self, final: bool = True) -> None:
        """Stop the timer and write the reconciling final beat: it
        samples the same SolverStatistics singleton the exit stats JSON
        serializes, in the same finally, so the two artifacts agree."""
        self._stop.set()
        self._thread.join(timeout=self.interval_s + 5.0)
        if final:
            self.beat(final=True)


def start_heartbeat(cli_path: Optional[str] = None,
                    interval_s: Optional[float] = None
                    ) -> Optional[Heartbeat]:
    """Start the heartbeat if --heartbeat or MYTHRIL_TPU_HEARTBEAT names
    a path; None (no thread at all) otherwise — the disabled path costs
    one env read per run."""
    path = cli_path or os.environ.get(HEARTBEAT_ENV) or None
    if not path:
        return None
    heartbeat = Heartbeat(path, interval_s=interval_s)
    heartbeat.start()
    log.info("heartbeat metrics stream: %s every %.1fs",
             path, heartbeat.interval_s)
    return heartbeat

"""Speed-of-light roofline accounting (SOLAR-style attained vs attainable).

build(stats) assembles the "roofline" section of the stats JSON from
three sources:

  SolverStatistics   settle work/wall (cdcl_clauses / settle_wall) and the
                     solver-wall timers for the decomposition — these
                     aggregate across --jobs workers via absorb().
  device backend     pack/ship/kernel work and busy seconds (pack_bytes,
                     ship_bytes, cells_stepped vs pack/ship/solve walls).
                     Per-process, like the rest of the device stats: the
                     backend object never crosses the spawn boundary.
  router profile     attainable ceilings from the micro-calibration
                     (tpu/router.attainable_rates): cells/s for the
                     kernel, bytes/s for pack/ship, clauses/s for settle.
                     None when the router never calibrated this run (the
                     stage then reports attained with no ceiling).

Each stage row carries `sol_gap_s` — the seconds the stage would get back
if it ran at its attainable rate (busy_s - work/attainable) — which is the
one unit comparable ACROSS stages; bench.py ranks the top gap stages per
leg with it. Ceilings are COLD-path micro-measurements on one calibration
shape, so a warm, cache-amortized stage can legitimately attain more than
its ceiling (pack on repeated shapes, settle on loaded sessions): that
clamps to headroom 1.0 / sol_gap_s 0.0 and reads as "this stage is not
the gap" — the ranking stays honest even where the ceiling is
conservative. The wall decomposition is reconciled by construction: the
independently-measured components (prepare / settle / crosscheck / device)
plus the explicit `other_s` residual sum to the measured solver wall, and
`attributed_frac` says how much of the wall the named components explain.

Everything here is read-only over already-collected counters and must
never break a stats emission: build() degrades to an empty-ceiling report
on any internal error.
"""

import logging
from typing import Optional

log = logging.getLogger(__name__)

# the device-path stages with measured work, busy wall, and a calibrated
# ceiling. One tuple drives build(), the check_stats_keys lint, and
# bench.py's ROOFLINE_STAGES gap table — adding a stage is one entry
# here plus its work/rate wiring below. "ragged" is the flat-stream
# assembly + upload of the ragged paged dispatch (circuit.RaggedStream:
# work = paged_stream_bytes, busy = the backend's ragged_seconds),
# the pack/ship counterpart of that path — its ceiling comes from the
# router micro-calibration's two-cone stream measurement
# (ragged_bytes_s, persisted with the calibration profile).
STAGES = ("pack", "ship", "ragged", "kernel", "settle", "frontier.fork")

_UNITS = {
    "pack": "bytes/s",
    "ship": "bytes/s",
    "ragged": "bytes/s",
    "kernel": "cells/s",
    "settle": "clauses/s",
    # device-side branching: rows forked batch-wise at symbolic JUMPI
    # per second of fork-epilogue wall (pending-condition rebuild +
    # coalesced feasibility + cohort materialization). No calibrated
    # ceiling yet — the stage reports attained only, and top_gaps ranks
    # it strictly last (gap unknown is not gap zero)
    "frontier.fork": "rows/s",
}


def _stage_row(work, busy_s: float, attainable: Optional[float],
               units: str) -> dict:
    attained = (work / busy_s) if busy_s else 0.0
    row = {
        "units": units,
        "work": int(work),
        "busy_s": round(busy_s, 4),
        "attained": round(attained, 2),
        "attainable": round(attainable, 2) if attainable else None,
    }
    if attainable and busy_s:
        # seconds recoverable at speed of light — the cross-stage ranking
        # unit (a stage at 10% of ceiling for 0.1 s matters less than one
        # at 80% for 30 s)
        row["sol_gap_s"] = round(max(busy_s - work / attainable, 0.0), 4)
        row["headroom"] = round(min(attained / attainable, 1.0), 4)
    else:
        row["sol_gap_s"] = None
        row["headroom"] = None
    return row


def _device_stats() -> dict:
    from mythril_tpu.tpu import backend as device_backend

    if device_backend._backend is None:
        return {}
    return device_backend._backend.stats()


def _router_rates() -> dict:
    from mythril_tpu.tpu import router as router_mod

    if router_mod._router is None:
        return {}
    try:
        return router_mod._router.attainable_rates()
    except Exception:
        return {}


def build(stats) -> dict:
    """The stats-JSON "roofline" section for a SolverStatistics snapshot.
    Never raises — a telemetry report must not break the run it reports."""
    try:
        return _build(stats)
    except Exception:
        log.exception("roofline accounting failed; emitting empty report")
        return {
            "stages": {name: _stage_row(0, 0.0, None, _UNITS[name])
                       for name in STAGES},
            "wall": {"solver_total_s": 0.0},
        }


def _build(stats) -> dict:
    device = _device_stats()
    rates = _router_rates()

    stages = {
        "pack": _stage_row(
            device.get("pack_bytes", 0),
            device.get("pack_seconds", 0.0),
            rates.get("pack_bytes_s"),
            _UNITS["pack"]),
        "ship": _stage_row(
            device.get("ship_bytes", 0),
            device.get("ship_seconds", 0.0),
            rates.get("ship_bytes_s"),
            _UNITS["ship"]),
        "ragged": _stage_row(
            device.get("paged_stream_bytes", 0),
            device.get("ragged_seconds", 0.0),
            rates.get("ragged_bytes_s"),
            _UNITS["ragged"]),
        "kernel": _stage_row(
            device.get("cells_stepped", 0),
            device.get("solve_seconds", 0.0),
            rates.get("kernel_cells_s"),
            _UNITS["kernel"]),
        "settle": _stage_row(
            stats.cdcl_clauses,
            stats.settle_wall,
            rates.get("settle_clauses_s"),
            _UNITS["settle"]),
        "frontier.fork": _stage_row(
            stats.frontier_fork_rows,
            stats.frontier_fork_wall,
            None,
            _UNITS["frontier.fork"]),
    }

    total = stats.solver_time
    prepare = stats.prepare_wall
    settle = stats.settle_wall
    crosscheck = stats.crosscheck_wall
    device_s = stats.route_device_seconds
    attributed = prepare + settle + crosscheck + device_s
    wall = {
        # the decomposition reconciles by construction: named components
        # + other_s == solver_total_s (other_s = cache probes, memo
        # lookups, marshalling — measured as the residual, never hidden)
        "solver_total_s": round(total, 4),
        "prepare_s": round(prepare, 4),
        "settle_s": round(settle, 4),
        "crosscheck_s": round(crosscheck, 4),
        "device_s": round(device_s, 4),
        "other_s": round(max(total - attributed, 0.0), 4),
        "attributed_frac": round(min(attributed / total, 1.0), 4)
        if total else 1.0,
        # interpreter wall is the ENGINE-side counterpart (outside the
        # solver wall); reported here so one section carries the split
        "interp_s": round(stats.interp_wall, 4),
    }
    return {"stages": stages, "wall": wall}


def top_gaps(roofline: dict, n: int = 3) -> list:
    """Top-`n` stages by sol_gap_s (descending) from a built roofline
    section — the per-leg "where the remaining gap is" table bench.py
    attaches to every analyze leg. Stages without a calibrated ceiling
    rank last (gap unknown is not gap zero)."""
    stages = (roofline or {}).get("stages", {})
    ranked = sorted(
        ((name, row) for name, row in stages.items()),
        # unknown gap (no calibrated ceiling) ranks strictly LAST — gap
        # unknown is not gap zero, and must not tie with at-ceiling stages
        key=lambda item: (item[1].get("sol_gap_s") is None,
                          -(item[1].get("sol_gap_s") or 0.0)),
    )
    return [
        {"stage": name,
         "sol_gap_s": row.get("sol_gap_s"),
         "attained": row.get("attained"),
         "attainable": row.get("attainable"),
         "units": row.get("units")}
        for name, row in ranked[:n]
    ]

"""Hierarchical span tracer with Chrome-trace-event / Perfetto export.

Usage (the instrumented seams throughout the pipeline):

    from mythril_tpu.observe.tracer import span, traced

    with span("router.dispatch", cat="router", queries=len(problems)) as sp:
        ...
        sp.set(hits=hits)          # attach attributes discovered mid-span

    @traced("laser.exec", cat="laser")
    def exec(self, ...): ...

Design constraints, in priority order:

  disabled cost   tracing is OFF unless MYTHRIL_TPU_TRACE (or --trace) set
                  a path. With the flight recorder ALSO off
                  (MYTHRIL_TPU_FLIGHTREC=0), span() returns ONE shared
                  no-op object — a module-global load, a truthiness
                  check, and a context-manager protocol on an empty
                  object. With the flight recorder on (the default),
                  spans additionally land in a bounded ring
                  (observe/flightrec.py) — a deque append under the
                  same lock, still inside the 2%-of-stress-wall budget
                  the tier-1 overhead test enforces (<10 µs/site).
  thread safety   completed spans append to a lock-protected list; the
                  hierarchy needs no explicit parent tracking because
                  Perfetto nests complete ("X") events by containment per
                  (pid, tid) lane, and spans measured with one shared
                  perf_counter anchor are contained by construction.
  process merge   timestamps are wall-clock-anchored microseconds
                  (anchor = time.time() at enable + perf_counter deltas),
                  so events recorded in --jobs worker processes — drained
                  as plain dicts through the existing stats-snapshot
                  pickle channel and absorbed by the parent — land on the
                  same timeline, each under its own pid lane.

Export is the Chrome trace event format (the `traceEvents` array form):
one "X" (complete) event per span with ph/ts/dur/pid/tid/name/cat, plus
"M" process_name metadata per merged pid. Load the file in Perfetto
(ui.perfetto.dev) or chrome://tracing.
"""

import json
import logging
import os
import threading
import time
from functools import wraps
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

TRACE_ENV = "MYTHRIL_TPU_TRACE"


class _NullSpan:
    """Shared do-nothing span — the entire disabled-mode code path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **attrs):
        return self


NULL_SPAN = _NullSpan()


class _Span:
    """One live span; records itself into the tracer on exit."""

    __slots__ = ("_tracer", "name", "cat", "args", "_ts_us", "_t0")

    def __init__(self, tracer, name, cat, attrs):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = attrs

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._ts_us = self._tracer._anchor_wall_us + (
            self._t0 - self._tracer._anchor_perf) * 1e6
        return self

    def __exit__(self, exc_type, exc, tb):
        dur_us = (time.perf_counter() - self._t0) * 1e6
        self._tracer._record(self.name, self.cat, self._ts_us, dur_us,
                             self.args)
        return False

    def set(self, **attrs):
        self.args.update(attrs)
        return self


class Tracer:
    """Process-global span collector (singleton, like SolverStatistics)."""

    _instance: Optional["Tracer"] = None

    def __new__(cls):
        if cls._instance is None:
            inst = super().__new__(cls)
            inst.enabled = False
            inst.path = None
            inst._events = []
            inst._lock = threading.Lock()
            inst._pid = os.getpid()
            # flight-recorder ring: bounded capture of recent spans even
            # with full tracing unarmed (0 capacity = recorder off).
            # Ring spans are timestamped off a lazy anchor set here so
            # ring events are orderable without enable() ever running.
            try:
                from collections import deque

                from mythril_tpu.observe import flightrec

                cap = flightrec.ring_capacity()
            except Exception:
                cap = 0
            inst._ring = deque(maxlen=cap) if cap > 0 else None
            inst._anchor_perf = time.perf_counter()
            inst._anchor_wall_us = time.time() * 1e6
            # _active is THE hot-path flag span() reads: true when either
            # full tracing or the ring wants events
            inst._active = inst._ring is not None
            cls._instance = inst
        return cls._instance

    # -- lifecycle -----------------------------------------------------------

    def enable(self, path: Optional[str] = None) -> None:
        """Start collecting spans. `path` is where write() will export the
        timeline; workers pass None (they drain events back to the parent
        instead of writing a file)."""
        self.path = path
        self._pid = os.getpid()
        # one shared anchor: perf_counter gives monotonic sub-µs deltas,
        # the wall clock gives a base comparable ACROSS processes
        self._anchor_perf = time.perf_counter()
        self._anchor_wall_us = time.time() * 1e6
        self.enabled = True
        self._active = True

    def disable(self) -> None:
        self.enabled = False
        self._active = self._ring is not None

    def reset(self) -> None:
        """Testing hook: drop collected events (and the ring) and disable
        full tracing. The flight-recorder ring stays INSTALLED — always-on
        means a reset starts a fresh ring, not no ring."""
        with self._lock:
            self._events = []
            if self._ring is not None:
                self._ring.clear()
        self.enabled = False
        self._active = self._ring is not None
        self.path = None

    # -- flight-recorder ring (observe/flightrec.py) -------------------------

    def attach_ring(self, ring) -> None:
        """Install (or replace) the bounded span ring; None detaches it
        and restores the pure no-op disabled path."""
        with self._lock:
            self._ring = ring
        self._active = self.enabled or self._ring is not None

    def ring_events(self) -> List[dict]:
        """Snapshot of the ring in time order (oldest first)."""
        with self._lock:
            return list(self._ring) if self._ring is not None else []

    # -- recording -----------------------------------------------------------

    def _record(self, name, cat, ts_us, dur_us, attrs) -> None:
        event = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": round(ts_us, 3),
            "dur": round(dur_us, 3),
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        if attrs:
            event["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        with self._lock:
            if self._ring is not None:
                self._ring.append(event)
            if self.enabled:
                self._events.append(event)

    # -- cross-process merge (--jobs workers) --------------------------------

    def drain_events(self) -> List[dict]:
        """Take every collected event (worker side of the merge: the
        returned plain dicts pickle through the corpus-worker payload)."""
        with self._lock:
            events, self._events = self._events, []
        return events

    def absorb_events(self, events) -> None:
        """Fold a worker's drained events into this (parent) tracer —
        they already carry the worker's pid, so each worker gets its own
        process lane in the merged timeline."""
        if not events:
            return
        with self._lock:
            self._events.extend(events)

    # -- aggregation / export ------------------------------------------------

    def summary(self) -> Dict[str, list]:
        """{stage name: [span count, total seconds]} over every collected
        event — the span-summary section of the stats JSON."""
        out: Dict[str, list] = {}
        with self._lock:
            events = list(self._events)
        for event in events:
            record = out.setdefault(event["name"], [0, 0.0])
            record[0] += 1
            record[1] += event["dur"] / 1e6
        for record in out.values():
            record[1] = round(record[1], 4)
        return out

    def write(self, path: Optional[str] = None) -> Optional[str]:
        """Export the collected timeline as Chrome trace JSON. Returns the
        written path, or None when there was nowhere to write."""
        path = path or self.path
        if not path:
            return None
        with self._lock:
            events = list(self._events)
        # normalize to a zero-based timeline (comparable across merged
        # pids: every anchor is the shared wall clock)
        base = min((e["ts"] for e in events), default=0.0)
        out_events = []
        pids = []
        for event in events:
            event = dict(event)
            event["ts"] = round(event["ts"] - base, 3)
            out_events.append(event)
            if event["pid"] not in pids:
                pids.append(event["pid"])
        meta = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": ("mythril_tpu analyzer" if pid == self._pid
                               else f"mythril_tpu worker {pid}")}}
            for pid in pids
        ]
        payload = {"traceEvents": meta + out_events,
                   "displayTimeUnit": "ms"}
        try:
            with open(path, "w") as fd:
                json.dump(payload, fd)
        except OSError as error:
            log.warning("could not write trace to %s (%s)", path, error)
            return None
        log.info("wrote %d trace spans to %s (load in ui.perfetto.dev)",
                 len(out_events), path)
        return path


def _jsonable(value):
    if isinstance(value, (bool, int, float, str)) or value is None:
        return value
    return str(value)


def get_tracer() -> Tracer:
    return Tracer()


def span(name: str, cat: str = "stage", **attrs):
    """A span context manager, or the shared no-op when neither full
    tracing nor the flight-recorder ring wants events. THE hot-path
    entry point: keep the inactive branch allocation-free."""
    tracer = Tracer._instance
    if tracer is None or not tracer._active:
        return NULL_SPAN
    return _Span(tracer, name, cat, attrs)


def traced(name: str, cat: str = "stage"):
    """Decorator form for whole-function stages."""

    def decorate(func):
        @wraps(func)
        def wrapped(*args, **kwargs):
            tracer = Tracer._instance
            if tracer is None or not tracer._active:
                return func(*args, **kwargs)
            with _Span(tracer, name, cat, {}):
                return func(*args, **kwargs)

        return wrapped

    return decorate

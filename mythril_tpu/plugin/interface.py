"""Package-level plugin interfaces (reference mythril/plugin/interface.py).

Third-party pip packages extend the framework by exposing entry points in
the ``mythril_tpu.plugins`` group; each entry point resolves to a subclass
of one of these interfaces."""

from abc import ABC

from mythril_tpu.laser.plugin.interface import PluginBuilder as LaserPluginBuilder


class MythrilPlugin:
    """Base interface for package-level plugins.

    Plugins extend the framework in one of these ways:
    1. instrument LASER (implement MythrilLaserPlugin),
    2. add a search strategy,
    3. add a detection module (subclass analysis.module.DetectionModule),
    4. add CLI commands (implement MythrilCLIPlugin).
    """

    author = "Default Author"
    name = "Plugin Name"
    plugin_license = "All rights reserved."
    plugin_type = "Mythril Plugin"
    plugin_version = "0.0.1"
    plugin_description = "Example plugin description"
    plugin_default_enabled = False

    def __init__(self, **kwargs):
        pass

    def __repr__(self):
        return f"{type(self).__name__} - {self.plugin_version} - {self.author}"


class MythrilCLIPlugin(MythrilPlugin):
    """Plugins that add commands to the CLI."""


class MythrilLaserPlugin(MythrilPlugin, LaserPluginBuilder, ABC):
    """Plugins that instrument the LASER EVM."""

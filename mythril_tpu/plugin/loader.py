"""Package-level plugin loader (reference mythril/plugin/loader.py):
validates a plugin's type and dispatches it to the matching subsystem —
detection modules into the ModuleLoader, laser plugins into the
LaserPluginLoader."""

import logging
from typing import Dict

from mythril_tpu.analysis.module.base import DetectionModule
from mythril_tpu.analysis.module.loader import ModuleLoader
from mythril_tpu.laser.plugin.loader import LaserPluginLoader
from mythril_tpu.plugin.discovery import PluginDiscovery
from mythril_tpu.plugin.interface import MythrilLaserPlugin, MythrilPlugin

log = logging.getLogger(__name__)


class UnsupportedPluginType(Exception):
    """Raised when a plugin with an unsupported type is loaded."""


class MythrilPluginLoader:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.loaded_plugins = []
            cls._instance.plugin_args = {}
            cls._instance._load_default_enabled()
        return cls._instance

    def set_args(self, plugin_name: str, **kwargs) -> None:
        self.plugin_args[plugin_name] = kwargs

    def load(self, plugin: MythrilPlugin) -> None:
        if not isinstance(plugin, MythrilPlugin):
            raise ValueError("passed plugin is not a MythrilPlugin")
        log.info("loading plugin %s", plugin)
        if isinstance(plugin, DetectionModule):
            ModuleLoader().register_module(plugin)
        elif isinstance(plugin, MythrilLaserPlugin):
            LaserPluginLoader().load(plugin)
        else:
            raise UnsupportedPluginType(
                f"plugin type of {plugin!r} is not supported")
        self.loaded_plugins.append(plugin)

    def _load_default_enabled(self) -> None:
        for name in PluginDiscovery().get_plugins(default_enabled=True):
            try:
                plugin = PluginDiscovery().build_plugin(
                    name, self.plugin_args.get(name, {}))
                self.load(plugin)
            except Exception:
                log.exception("failed to load default-enabled plugin %s", name)

from mythril_tpu.plugin.discovery import PluginDiscovery
from mythril_tpu.plugin.interface import (
    MythrilCLIPlugin,
    MythrilLaserPlugin,
    MythrilPlugin,
)
from mythril_tpu.plugin.loader import MythrilPluginLoader, UnsupportedPluginType

__all__ = [
    "PluginDiscovery",
    "MythrilPlugin",
    "MythrilCLIPlugin",
    "MythrilLaserPlugin",
    "MythrilPluginLoader",
    "UnsupportedPluginType",
]

"""Entry-point plugin discovery (reference mythril/plugin/discovery.py:26).

Scans installed python packages for ``mythril_tpu.plugins`` entry points
via importlib.metadata — `pip install` a package exposing that group and
its plugins load without any repo change."""

from typing import Any, Dict, List, Optional

from mythril_tpu.plugin.interface import MythrilPlugin

ENTRY_POINT_GROUP = "mythril_tpu.plugins"


class PluginDiscovery:
    _instance = None
    _installed_plugins: Optional[Dict[str, Any]] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def init_installed_plugins(self) -> None:
        import logging
        from importlib.metadata import entry_points

        eps = entry_points()
        if hasattr(eps, "select"):  # python >= 3.10
            group = eps.select(group=ENTRY_POINT_GROUP)
        else:
            group = [ep for ep in eps if ep.group == ENTRY_POINT_GROUP]
        # one broken installed package must not take down the CLI
        self._installed_plugins = {}
        for ep in group:
            try:
                self._installed_plugins[ep.name] = ep.load()
            except Exception:
                logging.getLogger(__name__).exception(
                    "failed to load plugin entry point %r", ep.name)

    @property
    def installed_plugins(self) -> Dict[str, Any]:
        if self._installed_plugins is None:
            self.init_installed_plugins()
        return self._installed_plugins

    def is_installed(self, plugin_name: str) -> bool:
        return plugin_name in self.installed_plugins

    def build_plugin(self, plugin_name: str,
                     plugin_args: Optional[Dict] = None) -> MythrilPlugin:
        if not self.is_installed(plugin_name):
            raise ValueError(f"plugin {plugin_name!r} is not installed")
        plugin = self.installed_plugins[plugin_name]
        if plugin is None or not (
            isinstance(plugin, type) and issubclass(plugin, MythrilPlugin)
        ):
            raise ValueError(f"no valid plugin found for {plugin_name!r}")
        return plugin(**(plugin_args or {}))

    def get_plugins(self, default_enabled: Optional[bool] = None) -> List[str]:
        if default_enabled is None:
            return list(self.installed_plugins)
        return [
            name for name, cls in self.installed_plugins.items()
            if getattr(cls, "plugin_default_enabled", False) == default_enabled
        ]

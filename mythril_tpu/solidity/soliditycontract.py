"""SolidityContract — compile .sol files with solc and carry srcmaps
(reference mythril/solidity/soliditycontract.py:395; solc invocation as in
mythril/ethereum/util.py get_solc_json).

The solc binary itself is an external tool (SURVEY §2.9: out of scope to
rebuild); it is located via $SOLC or PATH and its standard-json output is
parsed here. Everything downstream (srcmap decoding, instruction-offset ->
source-line resolution for reports) is implemented locally.
"""

import json
import os
import shutil
import subprocess
from typing import Dict, List, Optional

from mythril_tpu.ethereum.evmcontract import EVMContract


class SolcError(Exception):
    pass


class NoContractFoundError(Exception):
    pass


def find_solc(solc_binary: Optional[str] = None) -> str:
    binary = solc_binary or os.environ.get("SOLC") or shutil.which("solc")
    if not binary or not (os.path.exists(binary) or shutil.which(binary)):
        raise ImportError(
            "solc binary not found (install solc or set $SOLC)"
        )
    return binary


def find_solc_version(version: str) -> str:
    """Resolve a specific compiler version (reference `--solv`): looks for
    `solc-vVERSION` on PATH and in $SOLC_DIR / ~/.mythril/solc. This
    environment has no network, so nothing is downloaded — a missing
    version is a clear error, not a fetch."""
    name = f"solc-v{version.lstrip('v')}"
    candidates = [shutil.which(name)]
    for root in (os.environ.get("SOLC_DIR"),
                 os.path.join(os.path.expanduser("~"), ".mythril", "solc")):
        if root:
            candidates.append(os.path.join(root, name))
    for candidate in candidates:
        if candidate and os.path.exists(candidate):
            return candidate
    raise ImportError(
        f"solc {version} not found (looked for {name} on PATH and in "
        "$SOLC_DIR; downloads are disabled in this environment)"
    )


def get_solc_json(file_path: str, solc_binary: Optional[str] = None,
                  solc_args: Optional[List[str]] = None) -> dict:
    """Run `solc --standard-json` on one file; returns the parsed output.

    solc rejects most CLI options in standard-json mode, so the common
    compile flags (--optimize, --optimize-runs N) are translated into the
    standard-json settings; path options pass through on the command line."""
    binary = find_solc(solc_binary)
    with open(file_path) as handle:
        source = handle.read()
    optimizer: dict = {"enabled": False}
    cli_args: List[str] = []
    args_iter = iter(solc_args or [])
    for arg in args_iter:
        if arg == "--optimize":
            optimizer["enabled"] = True
        elif arg == "--optimize-runs" or arg.startswith("--optimize-runs="):
            optimizer["enabled"] = True
            raw = (arg.split("=", 1)[1] if "=" in arg
                   else next(args_iter, "200"))
            try:
                optimizer["runs"] = int(raw)
            except ValueError:
                raise SolcError(
                    f"--optimize-runs expects a number, got {raw!r}"
                ) from None
        else:
            cli_args.append(arg)
    standard_input = {
        "language": "Solidity",
        "sources": {file_path: {"content": source}},
        "settings": {
            "outputSelection": {
                "*": {
                    "*": [
                        "evm.bytecode.object",
                        "evm.bytecode.sourceMap",
                        "evm.deployedBytecode.object",
                        "evm.deployedBytecode.sourceMap",
                        "abi",
                    ],
                    "": ["ast"],
                }
            },
            "optimizer": optimizer,
        },
    }
    proc = subprocess.run(
        [binary, "--standard-json", "--allow-paths", "."] + cli_args,
        input=json.dumps(standard_input),
        capture_output=True, text=True,
    )
    if proc.returncode:
        raise SolcError(f"solc failed: {proc.stderr[:500]}")
    output = json.loads(proc.stdout)
    errors = [e for e in output.get("errors", [])
              if e.get("severity") == "error"]
    if errors:
        raise SolcError(errors[0].get("formattedMessage",
                                      errors[0].get("message", "solc error")))
    return output


class SourceInfo:
    __slots__ = ("filename", "code", "lineno", "solc_mapping")

    def __init__(self, filename: str, code: str, lineno: Optional[int],
                 solc_mapping: str):
        self.filename = filename
        self.code = code
        self.lineno = lineno
        self.solc_mapping = solc_mapping


def decode_srcmap(srcmap: str) -> List[List[str]]:
    """solc srcmap run-length decoding: empty fields inherit the previous
    entry's value."""
    entries = []
    prev = ["0", "0", "0", "-", "0"]
    for item in srcmap.split(";"):
        fields = item.split(":")
        entry = list(prev)
        for i, field in enumerate(fields):
            if field:
                entry[i] = field
        entries.append(entry)
        prev = entry
    return entries


def _strip_placeholders(bytecode: str) -> str:
    """Unlinked library placeholders (__$...$__) become zero addresses."""
    out = []
    i = 0
    while i < len(bytecode):
        if bytecode.startswith("__", i):
            end = bytecode.find("__", i + 2)
            span = (end + 2 - i) if end != -1 else 40
            out.append("0" * span)
            i += span
        else:
            out.append(bytecode[i])
            i += 1
    return "".join(out)


class SolidityContract(EVMContract):
    def __init__(self, input_file: str, name: str, solc_output: dict,
                 source_text: Optional[str] = None):
        contracts = solc_output["contracts"][input_file]
        data = contracts[name]
        evm = data["evm"]
        super().__init__(
            code=_strip_placeholders(evm["deployedBytecode"]["object"]),
            creation_code=_strip_placeholders(evm["bytecode"]["object"]),
            name=name,
        )
        self.input_file = input_file
        self.solc_indices = self._build_source_index(solc_output)
        self.srcmap = decode_srcmap(
            evm["deployedBytecode"].get("sourceMap", ""))
        self.creation_srcmap = decode_srcmap(
            evm["bytecode"].get("sourceMap", ""))
        self.abi = data.get("abi", [])
        self.solc_ast = solc_output.get("sources", {}).get(
            input_file, {}).get("ast")  # feeds laser/tx_prioritiser.py
        if source_text is None:
            with open(input_file) as handle:
                source_text = handle.read()
        self.source_text = source_text

    @staticmethod
    def _build_source_index(solc_output: dict) -> Dict[int, str]:
        indices = {}
        for path, meta in solc_output.get("sources", {}).items():
            indices[meta.get("id", 0)] = path
        return indices

    def _mapping_at(self, address: int, constructor: bool):
        disassembly = (self.creation_disassembly if constructor
                       else self.disassembly)
        srcmap = self.creation_srcmap if constructor else self.srcmap
        index = disassembly.index_of_address(address)
        if index is None or index >= len(srcmap):
            return None
        return srcmap[index]

    def get_source_info(self, address: int,
                        constructor: bool = False) -> Optional[SourceInfo]:
        entry = self._mapping_at(address, constructor)
        if entry is None:
            return None
        offset, length, file_index = (int(entry[0]), int(entry[1]),
                                      int(entry[2]))
        if file_index < 0:  # autogenerated code (no source)
            return None
        filename = self.solc_indices.get(file_index, self.input_file)
        snippet = self.source_text[offset: offset + length]
        lineno = self.source_text[:offset].count("\n") + 1
        return SourceInfo(
            filename=filename,
            code=snippet,
            lineno=lineno,
            solc_mapping=f"{offset}:{length}:{file_index}",
        )


def get_contracts_from_file(
    input_file: str,
    solc_binary: Optional[str] = None,
    solc_args: Optional[List[str]] = None,
) -> List[SolidityContract]:
    """All deployable contracts in a file, file-order, skipping interfaces
    (empty bytecode)."""
    output = get_solc_json(input_file, solc_binary, solc_args)
    contracts = []
    for name, data in output.get("contracts", {}).get(input_file, {}).items():
        if not data.get("evm", {}).get("deployedBytecode", {}).get("object"):
            continue
        contracts.append(SolidityContract(input_file, name, output))
    if not contracts:
        raise NoContractFoundError(
            f"no deployable contract found in {input_file}"
        )
    return contracts


def get_contracts_from_foundry(build_info: dict) -> List[SolidityContract]:
    """All deployable contracts in one `forge build --build-info` artifact
    (reference soliditycontract.py:141 get_contracts_from_foundry +
    mythril_disassembler.py:160 load_from_foundry). The build-info JSON
    carries solc standard-json "input" (with source text) and "output"
    (bytecode + srcmaps), so no file reads or solc invocation is needed."""
    if build_info.get("input", {}).get("language", "Solidity") != "Solidity":
        raise NotImplementedError("only Solidity foundry projects supported")
    output = build_info["output"]
    sources_in = build_info.get("input", {}).get("sources", {})
    contracts = []
    for input_file, per_file in output.get("contracts", {}).items():
        source_text = sources_in.get(input_file, {}).get("content", "")
        for name, data in per_file.items():
            if not data.get("evm", {}).get(
                    "deployedBytecode", {}).get("object"):
                continue
            contracts.append(SolidityContract(
                input_file, name, output, source_text=source_text))
    return contracts

"""Per-function feature extraction from the solc AST
(reference mythril/solidity/features.py:234) — the feature vector feeding
the transaction-sequence prioritizer (laser/tx_prioritiser.py).

Walks the standard-json AST of each function and records state-changing or
guard constructs: selfdestruct/call-family use, payability, owner-style
modifiers, assert/require guards (require'd variables propagate from
modifiers into the functions that use them), and the address variables that
receive transfer()/send() value."""

from typing import Dict, List, Set


FEATURES = (
    "contains_selfdestruct",
    "contains_call",
    "contains_delegatecall",
    "contains_callcode",
    "contains_staticcall",
    "contains_assert",
    "all_require_vars",
    "transfer_vars",
    "payable",
    "is_constructor",
    "has_modifiers",
    "has_owner_modifier",
    "transfers_value",
)

_CALL_KIND = {
    "call": "contains_call",
    "delegatecall": "contains_delegatecall",
    "callcode": "contains_callcode",
    "staticcall": "contains_staticcall",
}

_TRANSFER_METHODS = ("transfer", "send")
_OWNER_HINTS = ("owner", "admin", "auth")


def _walk(node, visit) -> None:
    if isinstance(node, dict):
        visit(node)
        for value in node.values():
            _walk(value, visit)
    elif isinstance(node, list):
        for item in node:
            _walk(item, visit)


def _identifiers_in(node) -> Set[str]:
    names: Set[str] = set()
    _walk(node, lambda n: (
        names.add(n["name"]) if n.get("nodeType") == "Identifier" else None
    ))
    return names


class SolidityFeatureExtractor:
    def __init__(self, ast: dict):
        self.ast = ast or {}

    def extract_features(self) -> Dict[str, Dict]:
        """function name -> feature dict. Modifier guard variables resolve
        within the function's own contract (same-named modifiers in other
        contracts of the file don't leak in)."""
        out: Dict[str, Dict] = {}
        modifier_cache: Dict[int, Dict[str, Set[str]]] = {}
        for fn, contract in self._function_nodes():
            scope = contract or self.ast
            if id(scope) not in modifier_cache:
                modifier_cache[id(scope)] = self._modifier_require_vars(scope)
            out[fn.get("name") or "constructor"] = self._features_of(
                fn, modifier_cache[id(scope)])
        return out

    def _function_nodes(self) -> List[tuple]:
        """(function node, enclosing ContractDefinition or None) pairs."""
        nodes = []

        def collect(node, contract):
            if isinstance(node, dict):
                if node.get("nodeType") == "ContractDefinition":
                    contract = node
                if node.get("nodeType") == "FunctionDefinition":
                    nodes.append((node, contract))
                for value in node.values():
                    collect(value, contract)
            elif isinstance(node, list):
                for item in node:
                    collect(item, contract)

        collect(self.ast, None)
        return nodes

    @staticmethod
    def _modifier_require_vars(scope: dict) -> Dict[str, Set[str]]:
        """modifier name -> variables required inside it, within one
        contract's scope (reference features.py:28-35: modifier guards
        count toward the functions that carry the modifier)."""
        out: Dict[str, Set[str]] = {}

        def visit(node):
            if node.get("nodeType") != "ModifierDefinition":
                return
            required: Set[str] = set()

            def inner(call):
                if call.get("nodeType") == "FunctionCall" and \
                        call.get("expression", {}).get("name") in (
                            "require", "assert"):
                    for arg in call.get("arguments", []):
                        required.update(_identifiers_in(arg))

            _walk(node.get("body") or {}, inner)
            out[node.get("name", "")] = required

        _walk(scope, visit)
        return out

    def _features_of(self, fn: dict,
                     modifier_vars: Dict[str, Set[str]]) -> Dict:
        features: Dict = {name: False for name in FEATURES}
        features["all_require_vars"] = set()
        features["transfer_vars"] = set()
        features["is_constructor"] = fn.get("kind") == "constructor"
        features["payable"] = fn.get("stateMutability") == "payable"
        modifiers = fn.get("modifiers") or []
        features["has_modifiers"] = bool(modifiers)
        features["has_owner_modifier"] = any(
            hint in (m.get("modifierName", {}).get("name", "").lower())
            for m in modifiers for hint in _OWNER_HINTS
        )
        for modifier in modifiers:
            name = modifier.get("modifierName", {}).get("name", "")
            features["all_require_vars"] |= modifier_vars.get(name, set())

        def visit(node):
            if node.get("nodeType") != "FunctionCall":
                return
            callee = node.get("expression", {})
            name = callee.get("name")
            member = callee.get("memberName")
            if name in ("selfdestruct", "suicide"):
                features["contains_selfdestruct"] = True
            if member in _CALL_KIND:
                features[_CALL_KIND[member]] = True
            if member in _TRANSFER_METHODS:
                features["transfers_value"] = True
                # the address variable receiving the value, e.g. `to` in
                # `to.transfer(amount)` (reference extract_address_variable)
                target = callee.get("expression", {})
                if target.get("nodeType") == "Identifier" and \
                        target.get("name"):
                    features["transfer_vars"].add(target["name"])
            if name == "assert":
                features["contains_assert"] = True
            if name in ("require", "assert"):
                for arg in node.get("arguments", []):
                    features["all_require_vars"] |= _identifiers_in(arg)

        _walk(fn.get("body") or {}, visit)
        return features

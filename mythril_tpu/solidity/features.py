"""Per-function feature extraction from the solc AST
(reference mythril/solidity/features.py:234) — the feature vector feeding
the transaction-sequence prioritizer (laser/tx_prioritiser.py).

Walks the standard-json AST of each function and records the presence of
state-changing or guard constructs.
"""

from typing import Dict, List


FEATURES = (
    "contains_selfdestruct",
    "contains_call",
    "contains_delegatecall",
    "contains_callcode",
    "contains_staticcall",
    "all_require_vars",
    "payable",
    "is_constructor",
    "has_modifiers",
    "has_owner_modifier",
    "transfers_value",
)

_CALL_KIND = {
    "call": "contains_call",
    "delegatecall": "contains_delegatecall",
    "callcode": "contains_callcode",
    "staticcall": "contains_staticcall",
}

_OWNER_HINTS = ("owner", "admin", "auth")


def _walk(node, visit) -> None:
    if isinstance(node, dict):
        visit(node)
        for value in node.values():
            _walk(value, visit)
    elif isinstance(node, list):
        for item in node:
            _walk(item, visit)


class SolidityFeatureExtractor:
    def __init__(self, ast: dict):
        self.ast = ast or {}

    def extract_features(self) -> Dict[str, Dict]:
        """function name -> feature dict."""
        out: Dict[str, Dict] = {}
        for fn in self._function_nodes():
            out[fn.get("name") or "constructor"] = self._features_of(fn)
        return out

    def _function_nodes(self) -> List[dict]:
        nodes = []

        def visit(node):
            if node.get("nodeType") == "FunctionDefinition":
                nodes.append(node)

        _walk(self.ast, visit)
        return nodes

    def _features_of(self, fn: dict) -> Dict:
        features = {name: False for name in FEATURES}
        features["all_require_vars"] = set()
        features["is_constructor"] = fn.get("kind") == "constructor"
        features["payable"] = fn.get("stateMutability") == "payable"
        modifiers = fn.get("modifiers") or []
        features["has_modifiers"] = bool(modifiers)
        features["has_owner_modifier"] = any(
            hint in (m.get("modifierName", {}).get("name", "").lower())
            for m in modifiers for hint in _OWNER_HINTS
        )

        def visit(node):
            node_type = node.get("nodeType")
            if node_type == "FunctionCall":
                callee = node.get("expression", {})
                name = callee.get("name")
                member = callee.get("memberName")
                if name == "selfdestruct" or name == "suicide":
                    features["contains_selfdestruct"] = True
                if member in _CALL_KIND:
                    features[_CALL_KIND[member]] = True
                if member in ("transfer", "send"):
                    features["transfers_value"] = True
                if name in ("require", "assert"):
                    for arg in node.get("arguments", []):
                        _walk(arg, lambda n: (
                            features["all_require_vars"].add(n["name"])
                            if n.get("nodeType") == "Identifier" else None
                        ))

        _walk(fn.get("body") or {}, visit)
        return features

"""Solidity frontend: solc standard-json compilation, source mapping,
AST feature extraction (reference mythril/solidity/)."""

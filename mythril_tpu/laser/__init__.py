"""LASER: the symbolic EVM engine (worklist interpreter over SMT state)."""

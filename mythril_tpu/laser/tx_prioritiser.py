"""Transaction-sequence prioritizer
(reference laser/ethereum/tx_prioritiser/rf_prioritiser.py:60).

Chooses which function selectors to explore first when incremental tx
ordering is disabled (`args.incremental_txs = False`, wired in
analysis/symbolic.py). Two modes:

* model mode — a pickled sklearn classifier (same contract as the
  reference's RandomForest: features in, per-function probabilities out)
  loaded from `model_path`;
* heuristic mode (default, no model file shipped) — deterministic scoring
  of the solc-AST features from solidity/features.py: state-mutating and
  value-moving functions first.
"""

import logging
import pickle
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

_FEATURE_WEIGHTS = {
    "contains_selfdestruct": 100,
    "contains_delegatecall": 60,
    "contains_callcode": 50,
    "contains_call": 40,
    "transfers_value": 30,
    "contains_staticcall": 5,
    "payable": 20,
    "has_owner_modifier": -10,  # likely guarded: explore later
}


class RfTxPrioritiser:
    def __init__(self, contract, model_path: Optional[str] = None):
        self.contract = contract
        self.model = None
        if model_path:
            try:
                with open(model_path, "rb") as handle:
                    self.model = pickle.load(handle)
            except (OSError, pickle.PickleError) as error:
                log.warning("could not load prioritizer model: %s", error)
        self.features: Dict[str, Dict] = {}
        ast = getattr(contract, "solc_ast", None)
        if ast is not None:
            from mythril_tpu.solidity.features import (
                SolidityFeatureExtractor,
            )

            self.features = SolidityFeatureExtractor(ast).extract_features()

    def _heuristic_score(self, name: str) -> int:
        features = self.features.get(name)
        if not features:
            return 0
        score = 0
        for key, weight in _FEATURE_WEIGHTS.items():
            if features.get(key):
                score += weight
        score += len(features.get("all_require_vars") or ()) * 2
        return score

    def predict_sequences(self, depth: int = 3) -> List[List[bytes]]:
        """Pinned selector list per transaction: tx i explores only the
        i-th best-ranked function (the predicted attack sequence), so the
        ranking actually prunes the search; txs beyond the ranking get the
        -1 wildcard (any selector / fallback)."""
        entries = self.contract.disassembly.function_entries
        selectors = list(entries)
        if self.model is not None and self.features:
            ranked = self._model_ranking(selectors)
        else:
            ranked = sorted(
                selectors,
                key=lambda sel: self._heuristic_score(
                    self._selector_name(sel)),
                reverse=True,
            )
        sequences: List[List[bytes]] = []
        for i in range(depth):
            if i < len(ranked):
                sequences.append([bytes.fromhex(ranked[i])])
            else:
                sequences.append([-1])
        return sequences

    def _selector_name(self, selector_hex: str) -> str:
        try:
            from mythril_tpu.support.signatures import SignatureDB

            matches = SignatureDB().get("0x" + selector_hex)
            if matches:
                return matches[0].split("(")[0]
        except Exception:
            pass
        return f"_function_0x{selector_hex}"

    def _model_ranking(self, selectors: List[str]) -> List[str]:
        """sklearn predict_proba over the feature matrix, highest first."""
        try:
            names = [self._selector_name(sel) for sel in selectors]
            matrix = [
                [int(bool(self.features.get(n, {}).get(k)))
                 for k in sorted(_FEATURE_WEIGHTS)]
                for n in names
            ]
            probabilities = self.model.predict_proba(matrix)
            scored = sorted(
                zip(selectors, (max(p) for p in probabilities)),
                key=lambda pair: pair[1], reverse=True,
            )
            return [sel for sel, _ in scored]
        except Exception as error:
            log.warning("model ranking failed (%s); falling back", error)
            return selectors

"""Batched straight-line step kernel.

One compiled Run executes over a whole DenseFrontier in a single step.
The micro-op interpreter `_exec` is written once against axis-agnostic
word ops (frontier/words.py) plus a tiny backend shim for the two
operations whose indexing genuinely differs per backend (dynamic memory
gather/scatter):

  numpy   eager, batch axis explicit — every stack slot is (N, 32), the
          memory window (N, W). No compile step: the right default on
          host-CPU platforms where an XLA compile per (run, shape) would
          eat the win.
  jax     the kernel is written single-state — stack slots (32,), memory
          (W,) — and `jax.jit(jax.vmap(...))` lifts it over the batch
          axis. Batches are padded to power-of-two slots so the compile
          cache is bounded per run; padding rides the `live` mask and is
          discarded on decode.

Because sibling states share their pc, the whole batch executes the SAME
opcode sequence — the program is a trace-time python loop, and the only
per-state control flow is the `ok` mask: a state whose dynamic behavior
leaves the fast path (memory access outside the dense window, gas
exhaustion) has its row frozen out and replays, untouched, on the
per-state interpreter. Stack shape is static per program point, so the
working stack is a python list of per-slot arrays — the padded dense
array exists only at the encode/decode boundary.

Backend choice: MYTHRIL_TPU_FRONTIER_BACKEND=numpy|jax|auto (default
auto = jax only when jax is already loaded AND its default platform is a
real accelerator — the TVM lesson: compile the common case where compile
time amortizes, interpret everywhere else).
"""

import os
from typing import Optional

import numpy as np

from mythril_tpu.laser.frontier import words
from mythril_tpu.laser.frontier.dense import DenseFrontier
from mythril_tpu.laser.frontier.fastset import Run

_JIT_CACHE = {}
_JIT_CACHE_MAX = 512


def resolve_backend() -> str:
    choice = os.environ.get("MYTHRIL_TPU_FRONTIER_BACKEND", "auto").lower()
    if choice in ("numpy", "jax"):
        return choice
    import sys

    if "jax" in sys.modules:
        try:
            if sys.modules["jax"].default_backend() != "cpu":
                return "jax"
        except Exception:
            pass
    return "numpy"


# -- binary op table ---------------------------------------------------------


def _lt(xp, a, b):
    return words.mask_to_word(xp, words.ult_mask(xp, a, b))


def _gt(xp, a, b):
    return words.mask_to_word(xp, words.ult_mask(xp, b, a))


def _slt(xp, a, b):
    return words.mask_to_word(xp, words.slt_mask(xp, a, b))


def _sgt(xp, a, b):
    return words.mask_to_word(xp, words.slt_mask(xp, b, a))


def _eq(xp, a, b):
    return words.mask_to_word(xp, words.eq_mask(xp, a, b))


_BIN_FNS = {
    "add": words.add, "sub": words.sub, "mul": words.mul,
    "div": words.div, "mod": words.mod,
    "sdiv": words.sdiv, "smod": words.smod,
    "and": words.bit_and, "or": words.bit_or, "xor": words.bit_xor,
    "lt": _lt, "gt": _gt, "slt": _slt, "sgt": _sgt, "eq": _eq,
}


# -- backends ----------------------------------------------------------------


class _NumpyBackend:
    def __init__(self, batch: int):
        self.xp = np
        self.batch = batch
        self._offsets32 = np.arange(32)

    def const_word(self, limbs):
        return np.broadcast_to(
            np.array(limbs, dtype=np.int32), (self.batch, words.LIMBS))

    def gather_word(self, mem, off):
        idx = off[:, None] + self._offsets32
        return np.take_along_axis(mem, idx, axis=1)

    def scatter(self, mem, written, off, value, ok, size):
        idx = off[:, None] + np.arange(size)
        value = np.broadcast_to(value, idx.shape)
        current = np.take_along_axis(mem, idx, axis=1)
        np.put_along_axis(
            mem, idx, np.where(ok[:, None], value, current), axis=1)
        current_w = np.take_along_axis(written, idx, axis=1)
        np.put_along_axis(written, idx, current_w | ok[:, None], axis=1)
        return mem, written


class _JaxBackend:
    """Single-state semantics; jax.vmap supplies the batch axis."""

    def __init__(self, jax_mod):
        self.jax = jax_mod
        self.xp = jax_mod.numpy

    def const_word(self, limbs):
        return self.xp.array(limbs, dtype=self.xp.int32)

    def gather_word(self, mem, off):
        return self.jax.lax.dynamic_slice(mem, (off,), (32,))

    def scatter(self, mem, written, off, value, ok, size):
        lax = self.jax.lax
        value = self.xp.broadcast_to(value, (size,))
        current = lax.dynamic_slice(mem, (off,), (size,))
        mem = lax.dynamic_update_slice(
            mem, self.xp.where(ok, value, current), (off,))
        current_w = lax.dynamic_slice(written, (off,), (size,))
        written = lax.dynamic_update_slice(written, current_w | ok, (off,))
        return mem, written


# -- the micro-op interpreter ------------------------------------------------


def _mem_extend(xp, off, size, msize, min_gas, max_gas, gas_limit, ok):
    """Bit-exact mirror of MachineState.mem_extend for concrete offsets:
    word-aligned growth + the yellow-paper quadratic fee + check_gas."""
    from mythril_tpu.laser.state.machine_state import memory_expansion_fee

    end = off + size
    needed = ((end + 31) // 32) * 32
    new_words = needed // 32
    old_words = msize // 32
    extend = (msize <= end) & (new_words > old_words)
    # quadratic terms only evaluated on the extending lane (a dead lane's
    # msize may sit anywhere below the int32 encode cap — its square must
    # never be computed)
    ow = xp.where(extend, old_words, 0)
    nw = xp.where(extend, new_words, 0)
    fee = memory_expansion_fee(nw) - memory_expansion_fee(ow)
    min_gas = min_gas + fee
    max_gas = max_gas + fee
    ok = ok & (min_gas <= gas_limit)
    msize = xp.where(extend, needed, msize)
    return msize, min_gas, max_gas, ok


def _exec(bk, run: Run, slots, mem, written, msize, min_gas, max_gas,
          gas_limit, ok):
    # (offset, value-word) per MSTORE/MSTORE8 in run order: decode
    # replays these through Memory.write_word_at/write_byte so the SMT
    # store chain is built in EXECUTION order with the exact values —
    # byte-identical to the per-state interpreter's chain (a later
    # symbolic-index read over the chain sees the same term structure)
    mem_log = []
    # [dest-word, condition-word] when the run terminates in a batched
    # JUMPI fork (fastset Run.fork), [offset-word, length-word] when it
    # terminates in a RETURN halt (Run.halt), else empty
    fork_out = []
    xp = bk.xp
    for op in run.ops:
        kind = op.kind
        if kind == "push":
            slots.append(bk.const_word(op.arg))
        elif kind == "dup":
            slots.append(slots[-op.arg])
        elif kind == "swap":
            n = op.arg
            slots[-1], slots[-n - 1] = slots[-n - 1], slots[-1]
        elif kind == "pop":
            slots.pop()
        elif kind == "bin":
            a = slots.pop()
            b = slots.pop()
            slots.append(_BIN_FNS[op.arg](xp, a, b))
        elif kind == "not":
            slots.append(words.bit_not(xp, slots.pop()))
        elif kind == "iszero":
            slots.append(
                words.mask_to_word(xp, words.is_zero_mask(xp, slots.pop())))
        elif kind == "byte":
            index = slots.pop()
            value = slots.pop()
            slots.append(words.byte_op(xp, index, value))
        elif kind in ("shl", "shr", "sar"):
            shift = slots.pop()
            value = slots.pop()
            slots.append(getattr(words, kind)(xp, shift, value))
        elif kind == "signextend":
            position = slots.pop()
            value = slots.pop()
            slots.append(words.signextend(xp, position, value))
        elif kind == "mload":
            off, oob = words.mem_offset(
                xp, slots.pop(), 32, run.window)
            ok = ok & ~oob
            msize, min_gas, max_gas, ok = _mem_extend(
                xp, off, 32, msize, min_gas, max_gas, gas_limit, ok)
            slots.append(bk.gather_word(mem, off))
        elif kind == "mstore":
            off, oob = words.mem_offset(
                xp, slots.pop(), 32, run.window)
            value = slots.pop()
            ok = ok & ~oob
            msize, min_gas, max_gas, ok = _mem_extend(
                xp, off, 32, msize, min_gas, max_gas, gas_limit, ok)
            mem, written = bk.scatter(mem, written, off, value, ok, 32)
            mem_log.append((off, value))
        elif kind == "mstore8":
            off, oob = words.mem_offset(
                xp, slots.pop(), 1, run.window)
            value = slots.pop()
            ok = ok & ~oob
            msize, min_gas, max_gas, ok = _mem_extend(
                xp, off, 1, msize, min_gas, max_gas, gas_limit, ok)
            mem, written = bk.scatter(
                mem, written, off, value[..., 31:], ok, 1)
            mem_log.append((off, value))
        elif kind == "jumpi":
            # terminal fork op: pop destination then condition (the
            # interpreter's pop order) and surface both words to the
            # host — the stepper's fork epilogue needs the per-row
            # concrete destination, and the condition word when the
            # condition slot was kernel-computed. Neither operand is
            # a "consumed" window slot: a passthrough-symbolic slot's
            # limbs are encode-time zeros and the decode side uses the
            # ORIGINAL BitVec object instead (fastset provenance).
            fork_out.append(slots.pop())
            fork_out.append(slots.pop())
        elif kind == "return":
            # terminal halt op: pop offset then length (the
            # interpreter's pop order) and surface both words — the
            # stepper's halt epilogue needs per-row concrete operands
            # for kernel-computed sources (opaque operands bail the
            # row before decode per the symbolic lane's tag sim)
            fork_out.append(slots.pop())
            fork_out.append(slots.pop())
        elif kind == "stop":
            pass  # terminal halt op: no operands, host-side epilogue
        elif kind == "calldataload":
            # symbolic-lane op: pop the offset and push a placeholder
            # word. The pushed value is a TERM HANDLE by construction —
            # every row of a calldataload-bearing run decodes through
            # the lane's structural replay, which builds the canonical
            # calldata.get_word_at term host-side; these limbs are
            # never read back.
            slots.pop()
            slots.append(bk.const_word(words.word_from_int(0)))
        elif kind == "msize":
            slots.append(words.small_to_word(xp, msize))
        elif kind == "pc":
            slots.append(bk.const_word(words.word_from_int(op.arg)))
        elif kind == "nop":
            pass
        else:  # pragma: no cover - compile and execute must stay in sync
            raise AssertionError(f"unknown micro-op {kind}")
        # opcode gas accrues after the handler, as in instructions.execute
        min_gas = min_gas + op.gas_min
        max_gas = max_gas + op.gas_max
        ok = ok & (min_gas <= gas_limit)
    return (slots, mem, written, msize, min_gas, max_gas, ok, mem_log,
            fork_out)


# -- entry points ------------------------------------------------------------


def _step_numpy(run: Run, dense: DenseFrontier):
    batch = dense.batch
    bk = _NumpyBackend(batch)
    slots = [dense.stack[:, j] for j in range(run.touch)]
    slots, mem, written, msize, min_gas, max_gas, ok, mem_log, fork_out = \
        _exec(bk, run, slots, dense.mem, dense.mem_written, dense.msize,
              dense.min_gas, dense.max_gas, dense.gas_limit,
              dense.live.copy())
    if slots:
        stack_out = np.stack(
            [np.broadcast_to(s, (batch, words.LIMBS)) for s in slots],
            axis=1)
    else:
        stack_out = np.zeros((batch, 0, words.LIMBS), dtype=np.int32)
    mem_log = [
        (np.broadcast_to(off, (batch,)),
         np.broadcast_to(value, (batch, words.LIMBS)))
        for off, value in mem_log
    ]
    fork_out = [np.broadcast_to(w, (batch, words.LIMBS)) for w in fork_out]
    return (stack_out, mem, written, msize, min_gas, max_gas, ok, mem_log,
            fork_out)


def _build_jax_step(run: Run):
    import jax

    bk = _JaxBackend(jax)
    jnp = jax.numpy

    def single(stack, mem, written, msize, min_gas, max_gas, gas_limit,
               live):
        slots = [stack[j] for j in range(run.touch)]
        slots, mem, written, msize, min_gas, max_gas, ok, mem_log, \
            fork_out = _exec(
                bk, run, slots, mem, written, msize, min_gas, max_gas,
                gas_limit, live)
        if slots:
            stack_out = jnp.stack(
                [jnp.broadcast_to(s, (words.LIMBS,)) for s in slots])
        else:
            stack_out = jnp.zeros((0, words.LIMBS), dtype=jnp.int32)
        flat_log = []
        for off, value in mem_log:
            flat_log.append(jnp.broadcast_to(off, ()))
            flat_log.append(jnp.broadcast_to(value, (words.LIMBS,)))
        for word in fork_out:
            flat_log.append(jnp.broadcast_to(word, (words.LIMBS,)))
        return (stack_out, mem, written, msize, min_gas, max_gas, ok,
                *flat_log)

    return jax.jit(jax.vmap(single))


def _step_jax(run: Run, dense: DenseFrontier):
    key = (run.key, dense.batch)
    step = _JIT_CACHE.get(key)
    if step is None:
        if len(_JIT_CACHE) >= _JIT_CACHE_MAX:
            _JIT_CACHE.clear()
        step = _build_jax_step(run)
        _JIT_CACHE[key] = step
    out = step(dense.stack, dense.mem, dense.mem_written, dense.msize,
               dense.min_gas, dense.max_gas, dense.gas_limit, dense.live)
    out = [np.asarray(part) for part in out]
    flat = out[7:]
    fork_words = 2 if (run.fork is not None
                       or (run.halt is not None
                           and run.halt.kind == "return")) else 0
    flat_log = flat[: len(flat) - fork_words]
    mem_log = [(flat_log[i], flat_log[i + 1])
               for i in range(0, len(flat_log), 2)]
    fork_out = list(flat[len(flat) - fork_words:]) if fork_words else []
    return (*out[:7], mem_log, fork_out)


def pad_slots(n: int) -> int:
    """Power-of-two jit shape bucket (bounds compile variants per run)."""
    slots = 1
    while slots < n:
        slots *= 2
    return slots


def step_batch(run: Run, dense: DenseFrontier,
               backend: Optional[str] = None):
    """Execute `run` over the dense batch. Returns (stack_out, mem,
    mem_written, msize, min_gas, max_gas, ok, mem_log, fork_out) as
    numpy arrays; mem_log holds one (offset, value-word) pair per
    MSTORE/MSTORE8 of the run, in execution order, and fork_out holds
    the popped [destination-word, condition-word] when the run ends in
    a batched JUMPI fork (empty otherwise). Rows with ok=False (bailed
    or padding) must be discarded by the caller."""
    if (backend or resolve_backend()) == "jax":
        return _step_jax(run, dense)
    return _step_numpy(run, dense)


def clear_caches() -> None:
    _JIT_CACHE.clear()

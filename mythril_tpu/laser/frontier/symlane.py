"""Symbolic-value lanes in the dense frontier representation.

The dense machine state (dense.py) is concrete byte limbs; before this
module, any run whose compute ops CONSUMED a symbolic or annotated stack
slot could not batch at all — on real contracts, whose dispatchers
consume symbolic calldata within an op or two of every block head, that
made the batchable surface near zero by construction. The lane fixes
this per ROW, not per run: each stack slot carries a tag (concrete
limbs vs. opaque term-handle), the per-row handle table is the original
BitVec objects themselves (held host-side, exactly like the PR-6
passthrough slots), and the compiled micro-op program doubles as a
STRUCTURAL OP LOG that decode replays into the ORIGINAL BitVec terms in
execution order — constructing, for every op that consumed an opaque
operand, the exact term the per-state interpreter's handler builds
(same helper calls, same operand objects, same eager constant folding),
while concrete lanes keep riding the kernel.

Admission is a per-row tag simulation (`admit`): abstract-interpret the
run over one bit per slot (opaque?) and decide
  "kernel"  no compute op consumes an opaque value — the existing
            kernel decode path is exact (passthrough slots included);
  "sym"     opaque values flow through computations — the kernel's
            limbs for those lanes are placeholders and decode takes
            the structural replay below;
  reject    an opaque value reaches a position the kernel (or the
            batch dialect) needs dynamically concrete: a memory
            offset, an MLOAD after a symbolic-valued store (the dense
            window bytes there are garbage), a guarded store about to
            write a word the hook predicate cannot judge, a JUMPI
            destination, a RETURN operand, a CALLDATALOAD offset.
            Rejected rows replay on the per-state interpreter, which
            handles every one of these today.

The kernel's `ok` mask, gas, and msize stay trustworthy for "sym" rows
by construction: taint only enters through opaque window slots and
CALLDATALOAD results, and every kernel computation that feeds ok/gas
(memory offsets and extension fees) is required concrete-tagged above.

The replay recomputes concrete intermediates with exact python-int EVM
semantics (the same semantics words.py implements limb-wise — held to
the interpreter by the differential property tests) so mixed terms like
`calldata_word + 4` embed the same constants eager folding would have
produced, and maintains a local overlay of the dense memory window so
MLOADs inside the run read what the kernel read.
"""

from typing import List, Optional, Tuple

from mythril_tpu.laser.frontier.dense import encodable_word
from mythril_tpu.laser.frontier.fastset import Run

M256 = 1 << 256
MASK256 = M256 - 1


def _opaque(entry) -> bool:
    """Does this shadow entry ride as a term handle? Mirrors
    dense.encodable_word: annotations are the taint channel, so an
    annotated constant is opaque too (its terms must carry the
    annotation exactly as the interpreter's would)."""
    if isinstance(entry, int):
        return False
    return encodable_word(entry) is None


# -- per-row admission (the tag simulation) ----------------------------------


def admit(state, run: Run) -> Tuple[Optional[str], Optional[str]]:
    """("kernel"|"sym", None) or (None, reason) for one state at `run`.
    Assumes the engine-level prechecks (dense.state_prechecks) already
    passed. reason in {"symbolic", "hook"} names the fallback-exit
    breakdown bucket for rejected rows."""
    stack = state.mstate.stack
    base = len(stack) - run.touch
    tags = [_opaque(stack[base + j]) for j in range(run.touch)]
    if not any(tags) and not run.has_calldataload:
        return "kernel", None
    guarded = {log_index for log_index, _predicates in run.mem_guards}
    needs_replay = run.has_calldataload
    sym_store = False
    mem_index = 0
    st = tags
    for op in run.ops:
        kind = op.kind
        if kind in ("push", "pc", "msize"):
            st.append(False)
        elif kind == "dup":
            st.append(st[-op.arg])
        elif kind == "swap":
            st[-1], st[-op.arg - 1] = st[-op.arg - 1], st[-1]
        elif kind == "pop":
            st.pop()
        elif kind in ("bin", "byte", "shl", "shr", "sar", "signextend"):
            a = st.pop()
            b = st.pop()
            result = a or b
            needs_replay = needs_replay or result
            st.append(result)
        elif kind in ("not", "iszero"):
            result = st.pop()
            needs_replay = needs_replay or result
            st.append(result)
        elif kind == "mload":
            if st.pop():
                return None, "symbolic"  # offset must be concrete
            if sym_store:
                # a symbolic word already entered the window: the
                # kernel's bytes under this load may be placeholders
                return None, "symbolic"
            st.append(False)
        elif kind in ("mstore", "mstore8"):
            if st.pop():
                return None, "symbolic"  # offset must be concrete
            if st.pop():
                if mem_index in guarded:
                    # the conditionally-transparent hook's predicate
                    # cannot judge a symbolic word: bail so the hook
                    # fires per-state, exactly as it always did
                    return None, "hook"
                sym_store = True
                needs_replay = True
            mem_index += 1
        elif kind == "calldataload":
            if st.pop():
                # only dynamically-concrete offsets promote; a fully
                # symbolic read stays on the per-state interpreter
                return None, "symbolic"
            st.append(True)
        elif kind == "jumpi":
            if st.pop():
                return None, "symbolic"  # symbolic jump destination
            st.pop()  # an opaque condition rides through (PendingFork)
        elif kind == "return":
            if st.pop() or st.pop():
                # the interpreter concretizes via the solver; that is
                # per-state work by definition
                return None, "symbolic"
        elif kind in ("stop", "nop"):
            pass
        else:  # pragma: no cover - compile and admit must stay in sync
            return None, "symbolic"
    return ("sym" if needs_replay else "kernel"), None


# -- exact python-int EVM semantics (concrete lanes of the replay) -----------


def _signed(value: int) -> int:
    return value - M256 if value >= (1 << 255) else value


def _sdiv(a: int, b: int) -> int:
    if b == 0:
        return 0
    sa, sb = _signed(a), _signed(b)
    quotient = abs(sa) // abs(sb)
    return (-quotient if (sa < 0) != (sb < 0) else quotient) % M256


def _smod(a: int, b: int) -> int:
    if b == 0:
        return 0
    sa, sb = _signed(a), _signed(b)
    remainder = abs(sa) % abs(sb)
    return (-remainder if sa < 0 else remainder) % M256


_INT_BIN = {
    "add": lambda a, b: (a + b) & MASK256,
    "sub": lambda a, b: (a - b) % M256,
    "mul": lambda a, b: (a * b) & MASK256,
    "div": lambda a, b: 0 if b == 0 else a // b,
    "mod": lambda a, b: 0 if b == 0 else a % b,
    "sdiv": _sdiv,
    "smod": _smod,
    "and": lambda a, b: a & b,
    "or": lambda a, b: a | b,
    "xor": lambda a, b: a ^ b,
    "lt": lambda a, b: int(a < b),
    "gt": lambda a, b: int(a > b),
    "slt": lambda a, b: int(_signed(a) < _signed(b)),
    "sgt": lambda a, b: int(_signed(a) > _signed(b)),
    "eq": lambda a, b: int(a == b),
}


def _int_byte(index: int, value: int) -> int:
    if index >= 32:
        return 0
    return (value >> (8 * (31 - index))) & 0xFF


def _int_shl(shift: int, value: int) -> int:
    return 0 if shift >= 256 else (value << shift) & MASK256


def _int_shr(shift: int, value: int) -> int:
    return 0 if shift >= 256 else value >> shift


def _int_sar(shift: int, value: int) -> int:
    signed = _signed(value)
    if shift >= 256:
        return MASK256 if signed < 0 else 0
    return (signed >> shift) % M256


def _int_signextend(position: int, value: int) -> int:
    if position >= 31:
        return value
    bits = 8 * (position + 1)
    low = value & ((1 << bits) - 1)
    if low >= 1 << (bits - 1):
        low |= M256 - (1 << bits)
    return low


# -- the structural replay ---------------------------------------------------


class Replay:
    """One row's structural-replay result: the final stack entries
    (python int for concrete lanes — interned as the same constants
    eager folding produces — or the constructed/original BitVec for
    opaque lanes), the per-store written values in mem-log order, and
    the popped terminal operands as the interpreter objects."""

    __slots__ = ("out", "mem_values", "terminal")

    def __init__(self, out: List, mem_values: List, terminal: Tuple):
        self.out = out
        self.mem_values = mem_values
        self.terminal = terminal


def to_term(entry):
    """Shadow entry -> the BitVec the interpreter's stack would hold:
    an int lane interns as BitVecVal (eager folding's constant), an
    opaque lane IS the original/constructed object."""
    if isinstance(entry, int):
        from mythril_tpu.laser.instructions import bv

        return bv(entry)
    return entry


def to_int(entry) -> int:
    return entry if isinstance(entry, int) else entry.raw.value


def _sym_bin(arg: str, a, b):
    """Mirror of the interpreter's binary handlers for opaque operands
    (instructions.py): the exact helper calls, in the exact operand
    orientation (`a` was the top of the stack)."""
    from mythril_tpu.laser.instructions import bool_to_bv
    from mythril_tpu.smt import SDiv, SRem, UDiv, UGT, ULT, URem

    a, b = to_term(a), to_term(b)
    if arg == "add":
        return a + b
    if arg == "sub":
        return a - b
    if arg == "mul":
        return a * b
    if arg == "div":
        return UDiv(a, b)
    if arg == "sdiv":
        return SDiv(a, b)
    if arg == "mod":
        return URem(a, b)
    if arg == "smod":
        return SRem(a, b)
    if arg == "and":
        return a & b
    if arg == "or":
        return a | b
    if arg == "xor":
        return a ^ b
    if arg == "lt":
        return bool_to_bv(ULT(a, b))
    if arg == "gt":
        return bool_to_bv(UGT(a, b))
    if arg == "slt":
        return bool_to_bv(a.slt(b))
    if arg == "sgt":
        return bool_to_bv(a.sgt(b))
    if arg == "eq":
        return bool_to_bv(a == b)
    raise AssertionError(f"unknown bin op {arg}")


def _sym_signextend(position, value):
    """Mirror of signextend_ for an opaque operand pair — including the
    concrete_or_none branch, which an ANNOTATED concrete position takes
    exactly as the interpreter would."""
    from mythril_tpu.laser.instructions import bv, concrete_or_none
    from mythril_tpu.smt import If, SignExt, Extract

    position, value = to_term(position), to_term(value)
    pos_concrete = concrete_or_none(position)
    if pos_concrete is not None:
        if pos_concrete >= 31:
            return value
        bits = 8 * (pos_concrete + 1)
        return SignExt(256 - bits, Extract(bits - 1, 0, value))
    result = value
    for k in range(31):
        bits = 8 * (k + 1)
        extended = SignExt(256 - bits, Extract(bits - 1, 0, value))
        result = If(position == bv(k), extended, result)
    return result


def replay(state, run: Run, window: Optional[List] = None) -> Replay:
    """Replay `run`'s structural op log for one admitted "sym" row over
    the row's ORIGINAL stack window objects — `window` is the dense
    frame's per-row handle table (DenseFrontier.handles, snapshotted at
    encode; read from the live stack when absent) — building every
    opaque lane's term exactly as the interpreter's handlers would.
    Called on the untouched pre-decode state (kernel `ok` already True
    for the row, so gas/msize/oob cannot bail here by construction)."""
    from mythril_tpu.laser.instructions import bool_to_bv, bv
    from mythril_tpu.smt import AShR, If, LShR, ULT

    mstate = state.mstate
    stack = mstate.stack
    if window is None:
        window = stack[len(stack) - run.touch:] if run.touch else []
    shadow: List = list(window)
    overlay = None
    if run.has_mem:
        window = mstate.memory.dense_window(run.window)
        overlay = (bytearray(window) if window is not None
                   else bytearray(run.window))
    msize = mstate.memory.size
    mem_values: List = []
    terminal: Tuple = ()

    def extend(offset: int, size: int) -> None:
        nonlocal msize
        end = offset + size
        needed = ((end + 31) // 32) * 32
        if msize <= end and needed // 32 > msize // 32:
            msize = needed

    for op in run.ops:
        kind = op.kind
        if kind == "push":
            shadow.append(int.from_bytes(bytes(op.arg), "big"))
        elif kind == "dup":
            shadow.append(shadow[-op.arg])
        elif kind == "swap":
            n = op.arg
            shadow[-1], shadow[-n - 1] = shadow[-n - 1], shadow[-1]
        elif kind == "pop":
            shadow.pop()
        elif kind == "bin":
            a = shadow.pop()
            b = shadow.pop()
            if _opaque(a) or _opaque(b):
                shadow.append(_sym_bin(op.arg, a, b))
            else:
                shadow.append(_INT_BIN[op.arg](to_int(a), to_int(b)))
        elif kind == "not":
            a = shadow.pop()
            shadow.append(~to_term(a) if _opaque(a)
                          else to_int(a) ^ MASK256)
        elif kind == "iszero":
            a = shadow.pop()
            shadow.append(bool_to_bv(to_term(a) == bv(0)) if _opaque(a)
                          else int(to_int(a) == 0))
        elif kind == "byte":
            index = shadow.pop()
            value = shadow.pop()
            if _opaque(index) or _opaque(value):
                index_t, value_t = to_term(index), to_term(value)
                shadow.append(If(
                    ULT(index_t, bv(32)),
                    LShR(value_t, (bv(31) - index_t) * bv(8)) & bv(0xFF),
                    bv(0)))
            else:
                shadow.append(_int_byte(to_int(index), to_int(value)))
        elif kind in ("shl", "shr", "sar"):
            shift = shadow.pop()
            value = shadow.pop()
            if _opaque(shift) or _opaque(value):
                shift_t, value_t = to_term(shift), to_term(value)
                shadow.append(
                    value_t << shift_t if kind == "shl"
                    else LShR(value_t, shift_t) if kind == "shr"
                    else AShR(value_t, shift_t))
            else:
                fn = {"shl": _int_shl, "shr": _int_shr,
                      "sar": _int_sar}[kind]
                shadow.append(fn(to_int(shift), to_int(value)))
        elif kind == "signextend":
            position = shadow.pop()
            value = shadow.pop()
            if _opaque(position) or _opaque(value):
                shadow.append(_sym_signextend(position, value))
            else:
                shadow.append(
                    _int_signextend(to_int(position), to_int(value)))
        elif kind == "mload":
            offset = to_int(shadow.pop())
            extend(offset, 32)
            shadow.append(
                int.from_bytes(bytes(overlay[offset:offset + 32]), "big"))
        elif kind == "mstore":
            offset = to_int(shadow.pop())
            value = shadow.pop()
            extend(offset, 32)
            mem_values.append(value)
            if not _opaque(value):
                overlay[offset:offset + 32] = \
                    to_int(value).to_bytes(32, "big")
            # an opaque store leaves the overlay alone: admission
            # rejected any MLOAD ordered after it
        elif kind == "mstore8":
            offset = to_int(shadow.pop())
            value = shadow.pop()
            extend(offset, 1)
            mem_values.append(value)
            if not _opaque(value):
                overlay[offset] = to_int(value) & 0xFF
        elif kind == "calldataload":
            offset = shadow.pop()
            # the exact handler line: the popped object goes into
            # get_word_at, so the canonical calldata term (and any
            # annotations on the offset) come out bit-identical
            shadow.append(
                state.environment.calldata.get_word_at(to_term(offset)))
        elif kind == "msize":
            shadow.append(msize)
        elif kind == "pc":
            shadow.append(op.arg)
        elif kind == "nop":
            pass
        elif kind == "jumpi":
            dest = shadow.pop()
            cond = shadow.pop()
            terminal = (to_term(dest), to_term(cond))
        elif kind == "return":
            offset = shadow.pop()
            length = shadow.pop()
            terminal = (to_term(offset), to_term(length))
        elif kind == "stop":
            pass
        else:  # pragma: no cover - compile and replay must stay in sync
            raise AssertionError(f"unknown micro-op {kind}")
    return Replay(shadow, mem_values, terminal)


def decode_sym_state(global_state, run: Run, rep: Replay, mem_log,
                     msize, min_gas, max_gas, i: int) -> None:
    """Commit one "sym" row: the replayed stack entries replace the
    window (int lanes intern as constants — dense.decode_state's exact
    discipline — opaque lanes keep their objects), memory replays the
    kernel's store log in execution order with the REPLAYED value
    objects (so a symbolic stored word enters the SMT chain exactly as
    write_word_at would have taken it from the interpreter), and
    msize/gas/pc commit from the kernel row, which is exact for "sym"
    rows by the admission rules."""
    from mythril_tpu.smt import Extract

    mstate = global_state.mstate
    stack = mstate.stack
    if run.touch:
        del stack[len(stack) - run.touch:]
    for entry in rep.out:
        stack.append(to_term(entry))
    if run.has_mem:
        memory = mstate.memory
        log_index = 0
        for op in run.ops:
            if op.kind == "mstore":
                off, _value = mem_log[log_index]
                value = rep.mem_values[log_index]
                log_index += 1
                memory.write_word_at(
                    int(off[i]),
                    value if _opaque(value) else to_int(value))
            elif op.kind == "mstore8":
                off, _value = mem_log[log_index]
                value = rep.mem_values[log_index]
                log_index += 1
                if _opaque(value):
                    memory.write_byte(int(off[i]),
                                      Extract(7, 0, to_term(value)))
                else:
                    memory.write_byte(int(off[i]), to_int(value) & 0xFF)
        new_msize = int(msize[i])
        if new_msize > memory.size:
            memory._msize = new_msize
    mstate.min_gas_used = int(min_gas[i])
    mstate.max_gas_used = int(max_gas[i])
    mstate.pc = run.end_pc

"""Batched 256-bit machine words as big-endian uint8 limb arrays.

The dense frontier representation keeps every EVM word as 32 limbs of one
byte each (big-endian, limb 0 = most significant), stored in int32 arrays
whose LAST axis is the limb axis — leading axes are free, so the same op
code runs single-state (shape ``(32,)``, the form `jax.vmap` maps over)
and batched (shape ``(N, 32)``, the numpy eager path). Byte limbs were
chosen over wider packings deliberately:

  - they match EVM memory bytes exactly, so MLOAD/MSTORE are pure
    gathers/scatters with no repacking at the memory seam;
  - partial products in MUL fit comfortably in int32 (32 * 255^2 < 2^21),
    so no backend needs 64-bit intermediates — jax under the default
    x64-disabled config has no int64;
  - carry/borrow propagation is a statically-unrolled 32-step pass.

Every function takes the array namespace `xp` (numpy or jax.numpy)
explicitly; nothing here imports jax. All ops are exact bit-level
implementations of the corresponding EVM semantics — the differential
property tests in tests/test_frontier.py hold them to the per-state
interpreter bit for bit.
"""

LIMBS = 32
WORD_BITS = 256


# -- host-side packing (python int <-> limb vectors) -------------------------


def word_from_int(value: int):
    """256-bit python int -> list of 32 big-endian byte limbs."""
    return list(value.to_bytes(32, "big"))


def int_from_limbs(limbs) -> int:
    """Limb vector (any int array-like of length 32) -> python int."""
    return int.from_bytes(bytes(int(v) & 0xFF for v in limbs), "big")


# -- canonicalization --------------------------------------------------------


def _carry_canon(xp, cols):
    """Propagate carries LSB->MSB over raw column sums (each column may
    hold any value < 2^31 / 32); the final carry out of limb 0 is dropped
    (mod 2^256)."""
    cols = list(cols)
    for i in range(LIMBS - 1, 0, -1):
        carry = cols[i] >> 8
        cols[i] = cols[i] & 0xFF
        cols[i - 1] = cols[i - 1] + carry
    cols[0] = cols[0] & 0xFF
    return xp.stack(cols, axis=-1)


# -- arithmetic --------------------------------------------------------------


def add(xp, a, b):
    return _carry_canon(xp, [a[..., i] + b[..., i] for i in range(LIMBS)])


def sub(xp, a, b):
    # one borrow-propagation implementation: the division step needs
    # the final borrow exposed (_sub_borrow), SUB just drops it
    return _sub_borrow(xp, a, b)[0]


def mul(xp, a, b):
    """Truncated 256-bit product. Column k (byte weight 31-k) collects the
    partial products with i + j = 31 + k."""
    zero = a[..., 0] * 0
    cols = [zero] * LIMBS
    for i in range(LIMBS):
        ai = a[..., i]
        for j in range(LIMBS - 1 - i, LIMBS):
            k = i + j - (LIMBS - 1)
            cols[k] = cols[k] + ai * b[..., j]
    return _carry_canon(xp, cols)


# -- division (bit-serial restoring long division) ---------------------------


def _sub_borrow(xp, a, b):
    """a - b with the final borrow exposed: (difference mod 2^256,
    borrow-out mask). The borrow-out IS the unsigned a < b verdict, so
    the division step gets its compare and its conditional subtract from
    ONE limb pass."""
    cols = [a[..., i] - b[..., i] for i in range(LIMBS)]
    for i in range(LIMBS - 1, 0, -1):
        borrow = (cols[i] < 0).astype(a.dtype)
        cols[i] = cols[i] + (borrow << 8)
        cols[i - 1] = cols[i - 1] - borrow
    underflow = cols[0] < 0
    cols[0] = cols[0] & 0xFF
    return xp.stack(cols, axis=-1), underflow


def _shift_in_bit(xp, rem, bit):
    """rem * 2 + bit across big-endian byte limbs (one vectorized pass:
    per-limb double is even and <= 254, so adding the carry bit — or the
    incoming dividend bit at the LSB — cannot overflow a byte)."""
    doubled = rem * 2
    kept = doubled & 0xFF
    carry = doubled >> 8
    shifted = kept + xp.concatenate(
        [carry[..., 1:], xp.zeros_like(carry[..., :1])], axis=-1)
    lsb = shifted[..., 31:] + bit[..., None]
    return xp.concatenate([shifted[..., :31], lsb], axis=-1)


def _divmod_host(xp, a, b):
    """Numpy (eager, concrete) divmod: the limbs ARE concrete bytes, so
    per-row python bignum divmod is exact and ~100x cheaper than the
    bit-serial array loop (which exists for traced backends, where
    values are abstract)."""
    import numpy as np

    flat_a = np.asarray(a, dtype=np.int64).reshape(-1, LIMBS)
    flat_b = np.asarray(b, dtype=np.int64).reshape(-1, LIMBS)
    quotient = np.zeros_like(flat_a, dtype=np.int32)
    remainder = np.zeros_like(flat_a, dtype=np.int32)
    for i in range(flat_a.shape[0]):
        divisor = int_from_limbs(flat_b[i])
        if divisor == 0:
            continue
        q, r = divmod(int_from_limbs(flat_a[i]), divisor)
        quotient[i] = np.frombuffer(q.to_bytes(32, "big"), dtype=np.uint8)
        remainder[i] = np.frombuffer(r.to_bytes(32, "big"), dtype=np.uint8)
    shape = np.shape(a)
    return quotient.reshape(shape), remainder.reshape(shape)


def _divmod_bitserial(xp, a, b):
    """Traced-backend divmod: 256 bit-serial restoring-division steps as
    a jax fori_loop (constant-size graph — an unrolled python loop would
    trace ~25k ops per DIV and dominate compile time)."""
    from jax import lax

    abits = to_bits(xp, a)
    qbits0 = xp.zeros_like(abits)
    rem0 = xp.zeros_like(a)

    def step(i, carry):
        rem, qbits = carry
        bit = lax.dynamic_index_in_dim(abits, i, axis=-1, keepdims=False)
        rem = _shift_in_bit(xp, rem, bit)
        diff, under = _sub_borrow(xp, rem, b)
        rem = xp.where(under[..., None], rem, diff)
        qbit = xp.where(under, 0, 1).astype(abits.dtype)
        qbits = lax.dynamic_update_index_in_dim(
            qbits, qbit[..., None], i, axis=-1)
        return rem, qbits

    rem, qbits = lax.fori_loop(0, WORD_BITS, step, (rem0, qbits0))
    return from_bits(xp, qbits), rem


def divmod_unsigned(xp, a, b):
    """EVM unsigned (a // b, a % b); division by zero yields (0, 0), as
    DIV/MOD specify. Bit-exact on either backend — the differential
    property tests hold both paths to the per-state interpreter."""
    if xp.__name__ == "numpy":
        quotient, remainder = _divmod_host(xp, a, b)
    else:
        quotient, remainder = _divmod_bitserial(xp, a, b)
    by_zero = is_zero_mask(xp, b)[..., None]
    quotient = xp.where(by_zero, 0, quotient)
    remainder = xp.where(by_zero, 0, remainder)
    return quotient, remainder


def _negate(xp, a):
    """Two's-complement negation (0 - a mod 2^256)."""
    return sub(xp, xp.zeros_like(a), a)


def _sign_mask(xp, a):
    return a[..., 0] >= 128


def _abs_word(xp, a):
    return xp.where(_sign_mask(xp, a)[..., None], _negate(xp, a), a)


def div(xp, a, b):
    return divmod_unsigned(xp, a, b)[0]


def mod(xp, a, b):
    return divmod_unsigned(xp, a, b)[1]


def sdiv(xp, a, b):
    """EVM SDIV: truncated signed division on two's-complement words.
    abs-divide then negate when the signs differ; the -2^255 / -1
    overflow case falls out correctly (abs(-2^255) = 2^255 unsigned,
    and negating 2^255 is the identity)."""
    quotient, _ = divmod_unsigned(xp, _abs_word(xp, a), _abs_word(xp, b))
    negate = _sign_mask(xp, a) ^ _sign_mask(xp, b)
    return xp.where(negate[..., None], _negate(xp, quotient), quotient)


def smod(xp, a, b):
    """EVM SMOD: remainder takes the DIVIDEND's sign (truncated
    division), |b| = 0 yields 0."""
    _, remainder = divmod_unsigned(xp, _abs_word(xp, a), _abs_word(xp, b))
    return xp.where(_sign_mask(xp, a)[..., None],
                    _negate(xp, remainder), remainder)


# -- comparisons (return bool masks over the leading axes) -------------------


def eq_mask(xp, a, b):
    return xp.all(a == b, axis=-1)


def is_zero_mask(xp, a):
    return xp.all(a == 0, axis=-1)


def ult_mask(xp, a, b):
    """Unsigned a < b: lexicographic from the most significant limb."""
    result = xp.zeros(a.shape[:-1], dtype=bool)
    decided = xp.zeros(a.shape[:-1], dtype=bool)
    for i in range(LIMBS):
        ai, bi = a[..., i], b[..., i]
        result = xp.where(~decided & (ai < bi), True, result)
        decided = decided | (ai != bi)
    return result


def _flip_sign(xp, a):
    """XOR the sign bit so signed compare = unsigned compare of images."""
    return xp.concatenate([a[..., :1] ^ 0x80, a[..., 1:]], axis=-1)


def slt_mask(xp, a, b):
    return ult_mask(xp, _flip_sign(xp, a), _flip_sign(xp, b))


def mask_to_word(xp, mask):
    """bool mask -> EVM boolean word (0 or 1)."""
    shape = mask.shape + (LIMBS - 1,)
    return xp.concatenate(
        [xp.zeros(shape, dtype=xp.int32),
         mask.astype(xp.int32)[..., None]], axis=-1)


# -- bitwise -----------------------------------------------------------------


def bit_and(xp, a, b):
    return a & b


def bit_or(xp, a, b):
    return a | b


def bit_xor(xp, a, b):
    return a ^ b


def bit_not(xp, a):
    return 255 - a


def byte_op(xp, index_word, value):
    """EVM BYTE: byte `i` of `value` (0 = most significant), 0 for i >= 32.
    With big-endian byte limbs this is a single limb gather."""
    high = xp.any(index_word[..., :31] != 0, axis=-1)
    small = index_word[..., 31]
    oob = high | (small >= LIMBS)
    idx = xp.where(oob, 0, small)
    picked = xp.take_along_axis(value, idx[..., None], axis=-1)[..., 0]
    picked = xp.where(oob, 0, picked)
    shape = picked.shape + (LIMBS - 1,)
    return xp.concatenate(
        [xp.zeros(shape, dtype=xp.int32), picked[..., None]], axis=-1)


# -- shifts ------------------------------------------------------------------


def to_bits(xp, a):
    """(..., 32) byte limbs -> (..., 256) bits, MSB first."""
    shifts = xp.arange(7, -1, -1)
    bits = (a[..., :, None] >> shifts) & 1
    return bits.reshape(a.shape[:-1] + (WORD_BITS,))


def from_bits(xp, bits):
    grouped = bits.reshape(bits.shape[:-1] + (LIMBS, 8))
    weights = 1 << xp.arange(7, -1, -1)
    return xp.sum(grouped * weights, axis=-1).astype(xp.int32)


def shift_amount(xp, w):
    """Shift-word -> (amount clamped into [0, 255], oob mask for >=256)."""
    high = xp.any(w[..., :30] != 0, axis=-1)
    small = w[..., 30] * 256 + w[..., 31]
    oob = high | (small >= WORD_BITS)
    return xp.where(oob, 0, small), oob


def shl(xp, shift_word, value):
    amount, oob = shift_amount(xp, shift_word)
    bits = to_bits(xp, value)
    idx = xp.arange(WORD_BITS) + amount[..., None]
    src = xp.take_along_axis(bits, xp.clip(idx, 0, WORD_BITS - 1), axis=-1)
    out = xp.where((idx < WORD_BITS) & ~oob[..., None], src, 0)
    return from_bits(xp, out)


def shr(xp, shift_word, value):
    amount, oob = shift_amount(xp, shift_word)
    bits = to_bits(xp, value)
    idx = xp.arange(WORD_BITS) - amount[..., None]
    src = xp.take_along_axis(bits, xp.clip(idx, 0, WORD_BITS - 1), axis=-1)
    out = xp.where((idx >= 0) & ~oob[..., None], src, 0)
    return from_bits(xp, out)


def sar(xp, shift_word, value):
    amount, oob = shift_amount(xp, shift_word)
    bits = to_bits(xp, value)
    sign = bits[..., :1]
    idx = xp.arange(WORD_BITS) - amount[..., None]
    src = xp.take_along_axis(bits, xp.clip(idx, 0, WORD_BITS - 1), axis=-1)
    out = xp.where((idx >= 0) & ~oob[..., None], src, sign)
    return from_bits(xp, out)


# -- SIGNEXTEND --------------------------------------------------------------


def signextend(xp, pos_word, value):
    """EVM SIGNEXTEND: sign byte sits at byte index 31 - pos (big-endian
    limbs); every more significant limb becomes the sign fill. pos >= 31
    is the identity."""
    high = xp.any(pos_word[..., :30] != 0, axis=-1)
    small = pos_word[..., 30] * 256 + pos_word[..., 31]
    identity = high | (small >= 31)
    sign_idx = xp.clip(31 - small, 0, 31)
    sign_byte = xp.take_along_axis(value, sign_idx[..., None], axis=-1)[..., 0]
    fill = ((sign_byte >> 7) & 1) * 255
    keep = xp.arange(LIMBS) >= sign_idx[..., None]
    return xp.where(keep | identity[..., None], value, fill[..., None])


# -- memory offsets / small-int conversions ----------------------------------


def mem_offset(xp, w, size, window):
    """Offset word -> (small offset, oob mask). oob marks states whose
    access [off, off+size) does not fit the dense window — they exit the
    batch and replay on the per-state interpreter (which handles huge
    concrete offsets via gas exhaustion)."""
    high = xp.any(w[..., :29] != 0, axis=-1)
    small = w[..., 29] * 65536 + w[..., 30] * 256 + w[..., 31]
    oob = high | (small + size > window)
    return xp.where(oob, 0, small), oob


def small_to_word(xp, value):
    """Non-negative int32 scalar array (< 2^31) -> word."""
    cols = [value * 0] * (LIMBS - 4)
    cols.append((value >> 24) & 0xFF)
    cols.append((value >> 16) & 0xFF)
    cols.append((value >> 8) & 0xFF)
    cols.append(value & 0xFF)
    return xp.stack(cols, axis=-1)

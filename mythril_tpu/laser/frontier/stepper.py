"""FrontierStepper — the engine side of the vmapped frontier.

Sits in LaserEVM.exec between the strategy and execute_state. For each
state the strategy yields, it tries to execute the straight-line run at
the state's pc as ONE batched device step over every eligible sibling
(same code object, same pc) it can pull from the worklist. Everything the
per-state path would have done for those opcodes is either replicated
exactly (stack/memory/pc/gas, in the kernel), fired host-side once per
state (execute_state laser hooks and the first opcode's pre hooks —
eligibility requires every such hook to opt in), or provably a no-op for
straight-line fast-set runs (manage_cfg, fork pruning, depth accounting).

Hook contract (opt-in via function attributes):
  frontier_once_ok    an execute_state laser hook whose effect is
                      equivalent when fired once per run instead of once
                      per instruction (its condition only reads run-
                      invariant state, e.g. the transaction stack) —
                      fired host-side per state at run start.
  frontier_batch      optional companion: called once per successful
                      batched run with (completed_states, run) to replay
                      per-instruction accounting batch-wise (coverage
                      marks the whole run's pcs).
  frontier_transparent  a pre/post/instr hook that is purely
                      observational per-instruction telemetry and may be
                      skipped for batched runs (the instruction
                      profiler; the interp_opcode_wall_top histogram
                      covers the fallback path it still profiles).

Any unmarked execute_state hook disables the stepper for the whole
engine; any unmarked pre/post/instr hook on an opcode cuts runs before
that opcode — detection modules and pruners always see their states
individually.
"""

import logging
from typing import List, Optional

from mythril_tpu.laser.frontier import dense, fastset, kernel
from mythril_tpu.laser.plugin.signals import PluginSkipState
from mythril_tpu.observe.tracer import NULL_SPAN, span as trace_span

log = logging.getLogger(__name__)

# cap on sibling states per batched step (bounds encode latency and the
# jit shape buckets; BFS worklists happily exceed this on dispatch fans)
MAX_BATCH = 64

_MISS = object()


def _span_skipped(state, pc: int) -> bool:
    """True while `state` sits inside the run span it last exited (bailed
    mid-batch or failed encoding): the per-state interpreter owns it from
    the run start through end_pc (re-batching at every interior pc would
    cost O(run length) kernel launches per bail). The flag clears itself
    the first time the state is seen OUTSIDE the span, so a later
    loop-back into the same run — where the bail cause may no longer
    hold — batches again."""
    span = getattr(state, "_frontier_skip_span", None)
    if span is None:
        return False
    if span[0] <= pc < span[1]:
        return True
    state._frontier_skip_span = None
    return False


class FrontierStepper:
    def __init__(self, svm):
        self.svm = svm
        self.backend = kernel.resolve_backend()
        self._runs = {}          # (bytecode_hash, pc) -> Run | None
        self._blocked = {}       # opcode name -> interior-blocked bool
        self._engine_ok: Optional[bool] = None
        log.debug("frontier stepper ready (backend=%s)", self.backend)

    # -- engine / hook gates -------------------------------------------------

    def _check_engine(self) -> bool:
        """All execute_state laser hooks must be frontier-aware; checked
        once (hooks are registered before sym_exec starts)."""
        if self._engine_ok is None:
            self._engine_ok = all(
                getattr(hook, "frontier_once_ok", False)
                for hook in self.svm._hooks["execute_state"]
            )
            if not self._engine_ok:
                log.debug("frontier disabled: unmarked execute_state hook")
        return self._engine_ok

    def _hook_entries(self, tables, name):
        for table in tables:
            entries = table.get(name)
            if entries:
                for hook in entries:
                    yield hook

    def _interior_blocked(self, name: str) -> bool:
        cached = self._blocked.get(name)
        if cached is None:
            svm = self.svm
            cached = any(
                not getattr(hook, "frontier_transparent", False)
                for hook in self._hook_entries(
                    (svm.pre_hooks, svm.post_hooks,
                     svm.instr_pre_hook, svm.instr_post_hook), name)
            )
            self._blocked[name] = cached
        return cached

    def _first_post_blocked(self, name: str) -> bool:
        svm = self.svm
        return any(
            not getattr(hook, "frontier_transparent", False)
            for hook in self._hook_entries(
                (svm.post_hooks, svm.instr_post_hook), name)
        )

    # -- run cache -----------------------------------------------------------

    def _run_for(self, code, pc: int) -> Optional[fastset.Run]:
        key = (code.bytecode_hash, pc)
        cached = self._runs.get(key, _MISS)
        if cached is not _MISS:
            return cached
        run = None
        # cheap peek before paying full extraction: most pcs are visited
        # once per leg (the cache rarely amortizes), and most fail
        # because fewer than MIN_RUN_OPS fast opcodes follow — a set
        # probe over the next few instruction names settles that at a
        # fraction of the compile cost
        if self._peek_fast(code, pc):
            from mythril_tpu import preanalysis

            summary = preanalysis.get_code_summary(code)
            if summary is not None:
                run = fastset.extract_run(
                    summary, pc, self._interior_blocked,
                    self._first_post_blocked)
        self._runs[key] = run
        return run

    @staticmethod
    def _peek_fast(code, pc: int) -> bool:
        index = code.index_of_address(pc)
        if index is None:
            return False
        instrs = code.instruction_list
        if index + fastset.MIN_RUN_OPS > len(instrs):
            return False
        return all(
            fastset.is_fast_op(instrs[index + k].opcode)
            for k in range(fastset.MIN_RUN_OPS)
        )

    # -- sibling scheduling --------------------------------------------------

    def _loop_vetter(self):
        """The bounded-loops wrapper's per-yield accounting, if present in
        the strategy chain — sibling states bypass strategy.__next__, so
        the stepper must apply the same vetting or loops run unbounded."""
        strategy = self.svm.strategy
        while strategy is not None:
            vet = getattr(strategy, "vet_state", None)
            if vet is not None:
                return vet
            strategy = getattr(strategy, "super_strategy", None)
        return None

    def _collect_siblings(self, lead, run) -> List:
        svm = self.svm
        # bytecode-hash equality, not object identity: sibling states of
        # one contract share the Disassembly, but separately-loaded equal
        # code executes identically and batches just as well
        code_hash = lead.environment.code.bytecode_hash
        pc = lead.mstate.pc
        vet = self._loop_vetter()
        batch = [lead]
        kept = []
        taken = 0
        for state in svm.work_list:
            if (taken < MAX_BATCH - 1
                    and state.mstate.pc == pc
                    and state.environment.code.bytecode_hash == code_hash
                    and state.mstate.depth < svm.max_depth
                    and not _span_skipped(state, pc)
                    and dense.state_encodable(state, run)):
                if vet is not None and not vet(state):
                    # loop bound exceeded: dropped exactly as the
                    # strategy's own filter would have dropped it
                    taken += 1
                    continue
                batch.append(state)
                taken += 1
            else:
                kept.append(state)
        if taken:
            svm.work_list[:] = kept
        return batch

    def _retract_loop_visit(self, state, run) -> None:
        """A bailed state will be re-yielded at the SAME pc and vetted by
        the bounded-loops wrapper again — but its JUMPDEST trace entry
        for this visit was already appended (by the strategy yield for
        the lead, by _collect_siblings' vetting for siblings). Pop it so
        one real visit counts once, or loop bounds would trip at half the
        true iteration count on repeatedly-bailing runs."""
        if run.first_instr.opcode != "JUMPDEST" \
                or self._loop_vetter() is None:
            return
        from mythril_tpu.laser.strategy.extensions.bounded_loops import (
            JumpdestCountAnnotation,
        )

        for annotation in state.annotations:
            if isinstance(annotation, JumpdestCountAnnotation):
                if annotation.trace and annotation.trace[-1] == \
                        run.start_pc:
                    annotation.trace.pop()
                return

    # -- the batched step ----------------------------------------------------

    def try_step(self, lead) -> Optional[List]:
        """Batched-step the run at `lead`'s pc. Returns the successor
        list (completed states at the run-end pc + bailed states,
        untouched, flagged to replay per-state), or None when the normal
        per-state path must handle `lead`."""
        if not self._check_engine():
            return None
        from mythril_tpu import resilience

        if resilience.fuse_blown("frontier.step"):
            # disable-for-session degradation: repeated batch-path faults
            # blew the fuse; the per-state interpreter owns every state
            return None
        pc = lead.mstate.pc
        if _span_skipped(lead, pc):
            return None
        # a pc past the code end (implicit STOP) has no instruction index
        # and falls out of _run_for's peek — the per-state path owns it
        run = self._run_for(lead.environment.code, pc)
        if run is None:
            return None
        if not dense.state_encodable(lead, run):
            lead._frontier_skip_span = (run.start_pc, run.end_pc)
            return None
        with trace_span("laser.frontier_step", cat="laser", pc=pc) as sp:
            return self._step_batch(lead, run, sp)

    def _step_batch(self, lead, run, sp=NULL_SPAN) -> Optional[List]:
        """The batched step itself (traced as laser.frontier_step)."""
        svm = self.svm
        batch = self._collect_siblings(lead, run)

        # host-side per-state prologue: execute_state hooks (all
        # frontier_once_ok), the run-start statespace snapshot, and the
        # first opcode's non-transparent pre hooks
        first_name = run.first_instr.opcode
        first_pre = [
            hook for hook in self._hook_entries(
                (svm.pre_hooks, svm.instr_pre_hook), first_name)
            if not getattr(hook, "frontier_transparent", False)
        ]
        survivors = []
        snapshots = {}
        for state in batch:
            try:
                for hook in svm._hooks["execute_state"]:
                    hook(state)
            except PluginSkipState:
                continue
            if svm.requires_statespace and state.node is not None:
                # capture the run-start snapshot NOW (it must show the
                # pre-run stack) but commit it only if the state
                # completes the batch — a bailed state re-records when
                # it replays per-state, and committing both would
                # duplicate the snapshot
                from mythril_tpu.laser.svm import _StateSnapshot

                snapshots[id(state)] = (
                    state.node, _StateSnapshot(state, run.first_instr))
            try:
                for hook in first_pre:
                    hook(state)
            except PluginSkipState:
                continue
            survivors.append(state)
        if not survivors:
            return []

        # registered disable-action fault site (frontier.step): a fault in
        # encode/kernel sends every collected survivor down the existing
        # bail path — untouched original states, flagged to replay the
        # whole run per-state — so a batch-step fault can cost wall, never
        # a state or a finding; repeated faults blow the session fuse
        from mythril_tpu import resilience

        try:
            resilience.maybe_inject("frontier.step")
            pad = (kernel.pad_slots(len(survivors))
                   if self.backend == "jax" else len(survivors))
            frame = dense.encode_frontier(survivors, run, pad_to=pad)
            stack_out, mem, written, msize, min_gas, max_gas, ok, mem_log = \
                kernel.step_batch(run, frame, self.backend)
        except Exception:
            log.warning("frontier batch step failed; per-state replay for "
                        "%d state(s)", len(survivors), exc_info=True)
            resilience.note_stage_failure("frontier.step")
            for state in survivors:
                state._frontier_skip_span = (run.start_pc, run.end_pc)
                self._retract_loop_visit(state, run)
            return survivors

        results = []
        completed = []
        for i, state in enumerate(survivors):
            if ok[i]:
                dense.decode_state(state, run, stack_out, mem, written,
                                   msize, min_gas, max_gas, i,
                                   mem_log=mem_log)
                snapshot = snapshots.get(id(state))
                if snapshot is not None:
                    snapshot[0].states.append(snapshot[1])
                completed.append(state)
            else:
                # replay the WHOLE run on the per-state interpreter from
                # the untouched original state; the span flag keeps every
                # pc of this run off the batch path for it
                state._frontier_skip_span = (run.start_pc, run.end_pc)
                self._retract_loop_visit(state, run)
            results.append(state)

        from mythril_tpu.smt.solver.statistics import SolverStatistics

        SolverStatistics().add_frontier_step(
            states=len(completed), slots=pad,
            fallback_exits=len(survivors) - len(completed))
        sp.set(states=len(completed), slots=pad,
               fallbacks=len(survivors) - len(completed),
               ops=len(run.ops))
        if completed:
            for hook in svm._hooks["execute_state"]:
                replay = getattr(hook, "frontier_batch", None)
                if replay is not None:
                    replay(completed, run)
        return results

"""FrontierStepper — the engine side of the vmapped frontier.

Sits in LaserEVM.exec between the strategy and execute_state. For each
state the strategy yields, it tries to execute the straight-line run at
the state's pc as ONE batched device step over every eligible sibling
(same code object, same pc) it can pull from the worklist. Everything the
per-state path would have done for those opcodes is either replicated
exactly (stack/memory/pc/gas, in the kernel), fired host-side once per
state (execute_state laser hooks and the first opcode's pre hooks —
eligibility requires every such hook to opt in), or provably a no-op for
straight-line fast-set runs (manage_cfg, fork pruning, depth accounting).

Hook contract (opt-in via function attributes):
  frontier_once_ok    an execute_state laser hook whose effect is
                      equivalent when fired once per run instead of once
                      per instruction (its condition only reads run-
                      invariant state, e.g. the transaction stack) —
                      fired host-side per state at run start.
  frontier_batch      optional companion: called once per successful
                      batched run with (completed_states, run) to replay
                      per-instruction accounting batch-wise (coverage
                      marks the whole run's pcs).
  frontier_transparent  a pre/post/instr hook that is purely
                      observational per-instruction telemetry and may be
                      skipped for batched runs (the instruction
                      profiler; the interp_opcode_wall_top histogram
                      covers the fallback path it still profiles).

Any unmarked execute_state hook disables the stepper for the whole
engine; any unmarked pre/post/instr hook on an opcode cuts runs before
that opcode — detection modules and pruners always see their states
individually.
"""

import logging
import random
import time
from typing import List, Optional

from mythril_tpu.laser.frontier import dense, fastset, kernel, symlane
from mythril_tpu.laser.plugin.signals import PluginSkipState
from mythril_tpu.observe.tracer import NULL_SPAN, span as trace_span

log = logging.getLogger(__name__)

# cap on sibling states per batched step (bounds encode latency and the
# jit shape buckets; BFS worklists happily exceed this on dispatch fans)
MAX_BATCH = 64

_MISS = object()
# run-cache sentinel: no batchable run at this pc, but the NEXT
# instruction after one fast op is a JUMPI — a fork-capable site the
# current configuration leaves to the per-state interpreter (feature
# off / hook-gated / fork-less prefix below MIN_RUN_OPS). try_step
# counts the handoff as a fallback exit so the branch_fusion on/off
# legs expose exactly the exits device-side branching removes.
_FORK_SITE = object()
# likewise for symbolic-lane-capable sites (one fast op, then a
# RETURN/STOP halt or a CALLDATALOAD): no run compiled here, and the
# handoff is counted — dialect or symbolic-operand reason — so the
# symlane on/off legs expose exactly the exits the lane removes.
# (pc, reason) pairs; the pc disambiguates from _FORK_SITE handling.
_LANE_SITE_HALT = object()
_LANE_SITE_SYMBOLIC = object()
_LANE_SITES = (_LANE_SITE_HALT, _LANE_SITE_SYMBOLIC)


class StepResults(list):
    """try_step's successor list, carrying the opcode the exec loop must
    hand manage_cfg: None for straight-line runs (no CFG opcodes inside),
    "JUMPI" when the batch forked — the successors then get the same
    conditional-edge nodes the per-state JUMPI handler's states get.
    Plain list at every other call site."""

    op_code: Optional[str] = None


def _span_skipped(state, pc: int) -> bool:
    """True while `state` sits inside the run span it last exited (bailed
    mid-batch or failed encoding): the per-state interpreter owns it from
    the run start through end_pc (re-batching at every interior pc would
    cost O(run length) kernel launches per bail). The flag clears itself
    the first time the state is seen OUTSIDE the span, so a later
    loop-back into the same run — where the bail cause may no longer
    hold — batches again."""
    span = getattr(state, "_frontier_skip_span", None)
    if span is None:
        return False
    if span[0] <= pc < span[1]:
        return True
    state._frontier_skip_span = None
    return False


class FrontierStepper:
    def __init__(self, svm):
        from mythril_tpu.laser import frontier

        self.svm = svm
        self.backend = kernel.resolve_backend()
        self._runs = {}          # (bytecode_hash, pc) -> Run | None
        self._blocked = {}       # opcode name -> interior-blocked bool
        self._guards = {}        # opcode name -> predicates tuple | None
        self._engine_ok: Optional[bool] = None
        # device-side branching: fork symbolic JUMPI batch-wise
        # (MYTHRIL_TPU_FRONTIER_FORK / --no-frontier-fork, on top of the
        # vmap-frontier switch); the depth cap bounds how deep batched
        # forking applies (0 = uncapped — the per-state path has no cap,
        # this is an operator brake on fork fan-out)
        self.fork_enabled = frontier.fork_enabled()
        self.fork_depth_cap = frontier.fork_depth_cap()
        self._fork_ok: Optional[bool] = None
        # symbolic-value lanes (MYTHRIL_TPU_FRONTIER_SYMLANE): opaque
        # term-handle slots ride compute ops via the structural replay,
        # CALLDATALOAD promotes in-batch, RETURN/STOP become terminal
        # micro-ops the halt epilogue settles host-side
        self.symlane = frontier.symlane_enabled()
        # cross-fork re-batching (MYTHRIL_TPU_FRONTIER_MULTIPC): fork
        # cohorts chain through their next dense run without the
        # one-iteration worklist stall; the width caps how many cohort
        # groups one top-level step may chain
        self.multipc_width = frontier.multipc_width()
        self._chain_depth = 0
        self._chain_budget = 0
        log.debug("frontier stepper ready (backend=%s, fork=%s, "
                  "symlane=%s, multipc=%d)", self.backend,
                  self.fork_enabled, self.symlane, self.multipc_width)

    # -- engine / hook gates -------------------------------------------------

    def _check_engine(self) -> bool:
        """All execute_state laser hooks must be frontier-aware; checked
        once (hooks are registered before sym_exec starts)."""
        if self._engine_ok is None:
            self._engine_ok = all(
                getattr(hook, "frontier_once_ok", False)
                for hook in self.svm._hooks["execute_state"]
            )
            if not self._engine_ok:
                log.debug("frontier disabled: unmarked execute_state hook")
        return self._engine_ok

    def _hook_entries(self, tables, name):
        for table in tables:
            entries = table.get(name)
            if entries:
                for hook in entries:
                    yield hook

    def _interior_blocked(self, name: str) -> bool:
        cached = self._blocked.get(name)
        if cached is None:
            svm = self.svm
            cached = any(
                not getattr(hook, "frontier_transparent", False)
                for hook in self._hook_entries(
                    (svm.pre_hooks, svm.post_hooks,
                     svm.instr_pre_hook, svm.instr_post_hook), name)
            )
            self._blocked[name] = cached
        return cached

    def _interior_guards(self, name: str) -> Optional[tuple]:
        """Value predicates when EVERY non-transparent hook on `name` is
        conditionally transparent (frontier_transparent_unless): the op
        may enter a run guarded — a row whose written value trips a
        predicate bails and replays per-state, where the hook fires.
        None when any hook is unconditionally opaque."""
        cached = self._guards.get(name, _MISS)
        if cached is not _MISS:
            return cached
        svm = self.svm
        predicates = []
        for hook in self._hook_entries(
                (svm.pre_hooks, svm.post_hooks,
                 svm.instr_pre_hook, svm.instr_post_hook), name):
            if getattr(hook, "frontier_transparent", False):
                continue
            predicate = getattr(hook, "frontier_transparent_unless", None)
            if predicate is None:
                predicates = None
                break
            predicates.append(predicate)
        result = tuple(predicates) if predicates is not None else None
        self._guards[name] = result
        return result

    def _fork_allowed(self) -> bool:
        """Batched JUMPI forking is available: the feature switch is on
        and JUMPI carries no non-transparent POST hooks. Pre hooks are
        fine — the fork epilogue fires them host-side on the exact
        pre-JUMPI state, as execute_state would — but the per-state path
        fires post hooks on BOTH sides before the exec loop's
        feasibility prune, and the whole point of the fused path is to
        mask infeasible sides before they materialize."""
        if self._fork_ok is None:
            svm = self.svm
            self._fork_ok = self.fork_enabled and not any(
                not getattr(hook, "frontier_transparent", False)
                for hook in self._hook_entries(
                    (svm.post_hooks, svm.instr_post_hook), "JUMPI")
            )
        return self._fork_ok

    def _first_post_blocked(self, name: str) -> bool:
        svm = self.svm
        return any(
            not getattr(hook, "frontier_transparent", False)
            for hook in self._hook_entries(
                (svm.post_hooks, svm.instr_post_hook), name)
        )

    # -- run cache -----------------------------------------------------------

    def _run_for(self, code, pc: int) -> Optional[fastset.Run]:
        key = (code.bytecode_hash, pc)
        cached = self._runs.get(key, _MISS)
        if cached is not _MISS:
            return cached
        run = None
        # cheap peek before paying full extraction: most pcs are visited
        # once per leg (the cache rarely amortizes), and most fail
        # because fewer than MIN_RUN_OPS fast opcodes follow — a set
        # probe over the next few instruction names settles that at a
        # fraction of the compile cost
        if self._peek_fast(code, pc):
            from mythril_tpu import preanalysis

            summary = preanalysis.get_code_summary(code)
            if summary is not None:
                run = fastset.extract_run(
                    summary, pc, self._interior_blocked,
                    self._first_post_blocked,
                    guards_for=self._interior_guards,
                    allow_fork=self._fork_allowed(),
                    allow_halt=self.symlane,
                    allow_symbolic=self.symlane)
        if run is None:
            site = self._minimal_site(code, pc)
            if site is not None:
                run = site
        self._runs[key] = run
        return run

    @staticmethod
    def _minimal_site(code, pc: int):
        """One fast op, then a lane-capable terminator: the minimal
        batched run's shape. When no run compiled here the interpreter
        takes the op — exactly the exit the fork / symbolic-lane
        features exist to remove, so the handoff is counted (by
        reason) for the on/off comparators."""
        index = code.index_of_address(pc)
        if index is None or index + 1 >= len(code.instruction_list):
            return None
        instrs = code.instruction_list
        if not fastset.is_fast_op(instrs[index].opcode):
            return None
        follower = instrs[index + 1].opcode
        if follower == "JUMPI":
            return _FORK_SITE
        if follower in ("RETURN", "STOP"):
            return _LANE_SITE_HALT
        if follower == "CALLDATALOAD":
            return _LANE_SITE_SYMBOLIC
        return None

    def _peek_fast(self, code, pc: int) -> bool:
        index = code.index_of_address(pc)
        if index is None:
            return False
        instrs = code.instruction_list
        fork_ok = self._fork_allowed()
        lane_ok = self.symlane
        seen_calldataload = False
        for k in range(fastset.MIN_RUN_OPS):
            if index + k >= len(instrs):
                return False
            name = instrs[index + k].opcode
            if fork_ok and name == "JUMPI":
                # a fork terminal satisfies the peek with any fast
                # prefix at all (the batched fork is the win even on
                # short runs)
                return k >= 1
            if lane_ok and name in ("RETURN", "STOP"):
                # a halt terminal satisfies the peek even BARE (a
                # cohort landing on a STOP settles through the halt
                # epilogue with no kernel work)
                return True
            if lane_ok and name == "CALLDATALOAD":
                # promoted op: a calldataload-bearing run is worth a
                # batch at 2 ops (extraction enforces the floor), so
                # any fast prefix before it — or any fast op after a
                # LEADING calldataload — satisfies the peek
                if k >= 1:
                    return True
                seen_calldataload = True
                continue
            if not fastset.is_fast_op(name):
                # [CALLDATALOAD, fast-op, blocked] still compiles a
                # 2-op promoted run — only a sub-2-op shape fails
                return seen_calldataload and k >= 2
        return True

    # -- sibling scheduling --------------------------------------------------

    def _loop_vetter(self):
        """The bounded-loops wrapper's per-yield accounting, if present in
        the strategy chain — sibling states bypass strategy.__next__, so
        the stepper must apply the same vetting or loops run unbounded."""
        strategy = self.svm.strategy
        while strategy is not None:
            vet = getattr(strategy, "vet_state", None)
            if vet is not None:
                return vet
            strategy = getattr(strategy, "super_strategy", None)
        return None

    def _admit(self, state, run):
        """Per-row batch admission: ("kernel", None) for the exact
        kernel decode, ("sym", None) for the symbolic lane's structural
        replay, or (None, fallback-reason bucket). The prechecks run
        ONCE here (state_encodable would re-run them — and rebuild the
        dense memory window — per sibling)."""
        reason = dense.state_prechecks(state, run)
        if reason is not None:
            return None, reason
        if self.symlane:
            return symlane.admit(state, run)
        if dense.consumed_windows_concrete(state, run):
            return "kernel", None
        return None, "symbolic"

    def _collect_siblings(self, lead, run, plans) -> List:
        svm = self.svm
        # bytecode-hash equality, not object identity: sibling states of
        # one contract share the Disassembly, but separately-loaded equal
        # code executes identically and batches just as well
        code_hash = lead.environment.code.bytecode_hash
        pc = lead.mstate.pc
        vet = self._loop_vetter()
        batch = [lead]
        kept = []
        taken = 0
        for state in svm.work_list:
            verdict = None
            if (taken < MAX_BATCH - 1
                    and state.mstate.pc == pc
                    and state.environment.code.bytecode_hash == code_hash
                    and state.mstate.depth < svm.max_depth
                    and self._span_allows(state, pc, run)
                    and self._fork_admissible(state, run)):
                verdict, _reason = self._admit(state, run)
            if verdict is not None:
                if vet is not None and not vet(state):
                    # loop bound exceeded: dropped exactly as the
                    # strategy's own filter would have dropped it
                    taken += 1
                    continue
                plans[id(state)] = verdict
                batch.append(state)
                taken += 1
            else:
                kept.append(state)
        if taken:
            svm.work_list[:] = kept
        return batch

    def _retract_loop_visit(self, state, run) -> None:
        """A bailed state will be re-yielded at the SAME pc and vetted by
        the bounded-loops wrapper again — but its JUMPDEST trace entry
        for this visit was already appended (by the strategy yield for
        the lead, by _collect_siblings' vetting for siblings). Pop it so
        one real visit counts once, or loop bounds would trip at half the
        true iteration count on repeatedly-bailing runs."""
        if run.first_instr.opcode != "JUMPDEST" \
                or self._loop_vetter() is None:
            return
        from mythril_tpu.laser.strategy.extensions.bounded_loops import (
            JumpdestCountAnnotation,
        )

        for annotation in state.annotations:
            if isinstance(annotation, JumpdestCountAnnotation):
                if annotation.trace and annotation.trace[-1] == \
                        run.start_pc:
                    annotation.trace.pop()
                return

    # -- the batched step ----------------------------------------------------

    def try_step(self, lead) -> Optional[List]:
        """Batched-step the run at `lead`'s pc. Returns the successor
        list (completed states at the run-end pc + bailed states,
        untouched, flagged to replay per-state), or None when the normal
        per-state path must handle `lead`."""
        if not self._check_engine():
            return None
        from mythril_tpu import resilience

        if resilience.fuse_blown("frontier.step"):
            # disable-for-session degradation: repeated batch-path faults
            # blew the fuse; the per-state interpreter owns every state
            return None
        pc = lead.mstate.pc
        # a pc past the code end (implicit STOP) has no instruction index
        # and falls out of _run_for's peek — the per-state path owns it
        run = self._run_for(lead.environment.code, pc)
        if run is None:
            _span_skipped(lead, pc)  # self-clears once outside the span
            return None
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        if run is _FORK_SITE:
            # fork-capable site the configuration leaves per-state: the
            # interpreter takes this branch (one visit, one exit)
            SolverStatistics().add_fork_site_exit(reason="dialect")
            return None
        if run is _LANE_SITE_HALT:
            # halt-capable site left per-state (symbolic lane off, or
            # no compilable prefix): the interpreter ends the frame
            SolverStatistics().add_fork_site_exit(reason="dialect")
            return None
        if run is _LANE_SITE_SYMBOLIC:
            # CALLDATALOAD site left per-state: the symbolic-operand
            # exit the lane exists to remove
            SolverStatistics().add_fork_site_exit(reason="symbolic")
            return None
        if not self._span_allows(lead, pc, run):
            return None
        verdict = None
        if self._fork_admissible(lead, run):
            verdict, refusal = self._admit(lead, run)
        else:
            refusal = "dialect"  # depth-capped fork: operator brake
        if verdict is None:
            if (run.fork is not None or run.halt is not None) \
                    and len(run.ops) == 2:
                # the MINIMAL fork/halt run refused a row: no shorter
                # retry site exists before the terminator — a real exit
                SolverStatistics().add_fork_site_exit(reason=refusal)
            lead._frontier_skip_span = (run.start_pc, run.end_pc)
            return None
        if self._chain_depth == 0:
            # top-level entry: arm the cross-fork re-batching budget
            # (consumed by _rebatch_cohorts, bounding how many cohort
            # groups may chain under this one strategy yield)
            self._chain_budget = self.multipc_width
        with trace_span("laser.frontier_step", cat="laser", pc=pc) as sp:
            self._chain_depth += 1
            try:
                return self._step_batch(lead, run, sp, verdict)
            finally:
                self._chain_depth -= 1

    @staticmethod
    def _span_allows(state, pc: int, run) -> bool:
        """Skip-span check that does NOT let a longer run's span eat a
        terminal: a state that failed encoding at a block-head run (its
        consumed slots held symbolic calldata) gets a span covering the
        whole block tail, but the SHORT fork/halt run at the terminator
        — dispatch ladders are exactly [PUSH dest, JUMPI] after a
        per-state EQ — may still batch. A terminal run whose OWN start
        pc set the span (a genuine batch bail) still defers to the
        per-state interpreter, so a persistently-bailing row costs one
        batch attempt per pc, never a loop."""
        if not _span_skipped(state, pc):
            return True
        if run.fork is None and run.halt is None:
            return False
        span = state._frontier_skip_span
        return span is not None and span[0] != pc

    def _fork_admissible(self, state, run) -> bool:
        """Fork-depth cap (MYTHRIL_TPU_FRONTIER_FORK_DEPTH, 0 =
        uncapped): rows past the cap take the per-state JUMPI instead of
        the batched fork — an operator brake on fork fan-out, never a
        semantic change (the interpreter forks them identically)."""
        if run.fork is None or not self.fork_depth_cap:
            return True
        return state.mstate.depth < self.fork_depth_cap

    def _step_batch(self, lead, run, sp=NULL_SPAN,
                    lead_verdict: str = "kernel") -> Optional[List]:
        """The batched step itself (traced as laser.frontier_step)."""
        svm = self.svm
        plans = {id(lead): lead_verdict}
        batch = self._collect_siblings(lead, run, plans)

        # host-side per-state prologue: execute_state hooks (all
        # frontier_once_ok), the run-start statespace snapshot, and the
        # first opcode's non-transparent pre hooks
        first_name = run.first_instr.opcode
        first_pre = [
            hook for hook in self._hook_entries(
                (svm.pre_hooks, svm.instr_pre_hook), first_name)
            if not getattr(hook, "frontier_transparent", False)
        ]
        survivors = []
        snapshots = {}
        for state in batch:
            try:
                for hook in svm._hooks["execute_state"]:
                    hook(state)
            except PluginSkipState:
                continue
            if svm.requires_statespace and state.node is not None:
                # capture the run-start snapshot NOW (it must show the
                # pre-run stack) but commit it only if the state
                # completes the batch — a bailed state re-records when
                # it replays per-state, and committing both would
                # duplicate the snapshot
                from mythril_tpu.laser.svm import _StateSnapshot

                snapshots[id(state)] = (
                    state.node, _StateSnapshot(state, run.first_instr))
            try:
                for hook in first_pre:
                    hook(state)
            except PluginSkipState:
                continue
            survivors.append(state)
        if not survivors:
            return []

        # registered disable-action fault site (frontier.step): a fault in
        # encode/kernel sends every collected survivor down the existing
        # bail path — untouched original states, flagged to replay the
        # whole run per-state — so a batch-step fault can cost wall, never
        # a state or a finding; repeated faults blow the session fuse
        from mythril_tpu import resilience

        try:
            resilience.maybe_inject("frontier.step")
            pad = (kernel.pad_slots(len(survivors))
                   if self.backend == "jax" else len(survivors))
            # the lane's tag/handle capture costs a window snapshot per
            # row: build it only when some collected row actually takes
            # the structural-replay decode
            lane_rows = any(verdict == "sym" for verdict in plans.values())
            frame = dense.encode_frontier(survivors, run, pad_to=pad,
                                          lane=lane_rows)
            (stack_out, mem, written, msize, min_gas, max_gas, ok,
             mem_log, fork_out) = kernel.step_batch(run, frame,
                                                    self.backend)
        except Exception:
            log.warning("frontier batch step failed; per-state replay for "
                        "%d state(s)", len(survivors), exc_info=True)
            resilience.note_stage_failure("frontier.step")
            for state in survivors:
                state._frontier_skip_span = (run.start_pc, run.end_pc)
                self._retract_loop_visit(state, run)
            return StepResults(survivors)

        results = StepResults()
        completed = []
        pending_forks = []  # dense.PendingFork per forked row, in order
        halt_rows = []      # (state, popped halt operands) per halt row
        bails_dynamic = bails_hook = bails_symbolic = 0
        sym_rows = 0
        for i, state in enumerate(survivors):
            plan = plans.get(id(state), "kernel")
            row_ok = bool(ok[i])
            bail_reason = "dynamic"
            if row_ok and run.mem_guards and dense.guard_tripped(
                    run, mem_log, i):
                # a conditionally-transparent hook is NOT inert for this
                # row's written value (hevm marker): replay per-state so
                # the hook fires exactly as it always did
                row_ok = False
                bail_reason = "hook"
            rep = None
            if row_ok and plan == "sym":
                # symbolic lane: replay the structural op log over the
                # row's ORIGINAL window objects — the opaque lanes'
                # terms, bit-identical to the interpreter's handlers.
                # A replay fault degrades the row to per-state replay,
                # never to a wrong term.
                try:
                    rep = symlane.replay(state, run,
                                         window=frame.handles[i])
                except Exception:
                    log.warning("symbolic-lane replay failed; per-state "
                                "replay", exc_info=True)
                    row_ok = False
            fork_operands = None
            halt_operands = ()
            if row_ok and run.fork is not None:
                from mythril_tpu.laser.instructions import concrete_or_none

                # read the popped (dest, cond) objects BEFORE decode
                # rebuilds the stack window; a symbolic destination
                # bails the row pre-decode so the untouched original
                # replays per-state and raises the exact
                # InvalidJumpDestination the interpreter raises
                fork_operands = (rep.terminal if rep is not None else
                                 dense.fork_operands(state, run,
                                                     fork_out, i))
                if concrete_or_none(fork_operands[0]) is None:
                    row_ok = False
                    bail_reason = "symbolic"
            if row_ok and run.halt is not None \
                    and run.halt.kind == "return":
                halt_operands = (rep.terminal if rep is not None else
                                 dense.halt_operands(state, run,
                                                     fork_out, i))
            if row_ok:
                if rep is not None:
                    symlane.decode_sym_state(state, run, rep, mem_log,
                                             msize, min_gas, max_gas, i)
                    sym_rows += 1
                else:
                    dense.decode_state(state, run, stack_out, mem,
                                       written, msize, min_gas, max_gas,
                                       i, mem_log=mem_log)
                snapshot = snapshots.get(id(state))
                if snapshot is not None:
                    snapshot[0].states.append(snapshot[1])
                completed.append(state)
                if run.fork is not None:
                    pf = self._fork_row(state, run, fork_operands)
                    if pf is not None:
                        pending_forks.append(pf)
                    # pf None: PluginSkipState from a JUMPI pre hook —
                    # the row completes with no successors, exactly as
                    # execute_state returns [] on a skipped state
                elif run.halt is not None:
                    halt_rows.append((state, halt_operands))
                else:
                    results.append(state)
            else:
                # replay the WHOLE run on the per-state interpreter from
                # the untouched original state; the span flag keeps every
                # pc of this run off the batch path for it
                state._frontier_skip_span = (run.start_pc, run.end_pc)
                self._retract_loop_visit(state, run)
                if bail_reason == "hook":
                    bails_hook += 1
                elif bail_reason == "symbolic":
                    bails_symbolic += 1
                else:
                    bails_dynamic += 1
                results.append(state)

        from mythril_tpu.smt.solver.statistics import SolverStatistics

        stats = SolverStatistics()
        # completed rows of a run that CUT at an unforked JUMPI or an
        # unpromoted RETURN/STOP exit the batch dialect to the
        # interpreter (dialect reason); rows cutting at a CALLDATALOAD
        # the lane was off for are symbolic-operand exits — on top of
        # being stepped rows, so the branch_fusion / symlane on/off
        # legs expose exactly the exits each feature removes
        cut_exits = symbolic_cuts = 0
        if run.fork is None and run.halt is None:
            if run.cut_at_jumpi or run.cut_at_halt:
                cut_exits = len(completed)
            elif run.cut_at_calldataload:
                symbolic_cuts = len(completed)
        stats.add_frontier_step(
            states=len(completed), slots=pad,
            fallback_exits=bails_dynamic, cut_exits=cut_exits,
            hook_exits=bails_hook, symbolic_exits=bails_symbolic,
            symbolic_cuts=symbolic_cuts, sym_rows=sym_rows)
        sp.set(states=len(completed), slots=pad,
               fallbacks=(bails_dynamic + bails_hook + bails_symbolic
                          + cut_exits + symbolic_cuts),
               ops=len(run.ops), sym_rows=sym_rows)
        if completed:
            for hook in svm._hooks["execute_state"]:
                replay = getattr(hook, "frontier_batch", None)
                if replay is not None:
                    replay(completed, run)
        if run.halt is not None:
            successors = self._halt_epilogue(run, halt_rows)
            if not completed:
                # every row bailed: pure replay, the straight-line bail
                # shape (no RETURN/STOP executed)
                return results
            # bailed rows replay per-state and re-enter the worklist
            # directly — the exec loop's new_states must carry only the
            # frame successors (manage_cfg gives them RETURN nodes; a
            # bailed, untouched original must not get one)
            if results:
                svm.work_list.extend(results)
            results = StepResults(successors)
            results.op_code = ("RETURN" if run.halt.kind == "return"
                               else "STOP")
            return results
        if run.fork is not None:
            successors = self._fork_epilogue(run, pending_forks)
            if not completed and not successors:
                # every row bailed: pure replay, exactly the
                # straight-line bail shape (no JUMPI executed)
                return results
            # bailed rows replay per-state and re-enter the worklist
            # directly — the exec loop's new_states must carry only the
            # fork successors (manage_cfg gives them JUMPI nodes; a
            # bailed, untouched original must not get one)
            if results:
                svm.work_list.extend(results)
            results = StepResults(successors)
            results.op_code = "JUMPI"
            if successors and self.multipc_width and self._chain_budget:
                # cross-fork re-batching: both cohorts stay dense
                # through their next run instead of re-entering the
                # worklist for one serialized iteration
                results = StepResults(self._rebatch_cohorts(successors))
        return results

    # -- the batched fork (device-side branching) ---------------------------

    def _fork_pre_hooks(self) -> List:
        hooks = getattr(self, "_fork_pre", None)
        if hooks is None:
            svm = self.svm
            hooks = [
                hook for hook in self._hook_entries(
                    (svm.pre_hooks, svm.instr_pre_hook), "JUMPI")
                if not getattr(hook, "frontier_transparent", False)
            ]
            self._fork_pre = hooks
        return hooks

    def _terminal_prologue(self, state, pc: int, operands, hooks,
                           run) -> bool:
        """Mirror of execute_state at a run terminator, shared by the
        fork and halt rows: reconstruct the exact pre-terminal machine
        state (`operands` pushed back in the given order, pc at the
        instruction), record the statespace snapshot, fire the
        non-transparent pre hooks host-side, pop the operands back.
        Returns False when a hook skipped the state (no successors, as
        execute_state returns [])."""
        svm = self.svm
        mstate = state.mstate
        mstate.pc = pc
        for entry in operands:
            mstate.stack.append(entry)
        if svm.requires_statespace and state.node is not None:
            from mythril_tpu.laser.svm import _StateSnapshot

            code = state.environment.code
            index = code.index_of_address(pc)
            instr = (code.instruction_list[index]
                     if index is not None else run.first_instr)
            state.node.states.append(_StateSnapshot(state, instr))
        skipped = False
        try:
            for hook in hooks:
                hook(state)
        except PluginSkipState:
            skipped = True
        for _ in operands:
            mstate.stack.pop()
        return not skipped

    def _fork_row(self, state, run, operands):
        """Per-row JUMPI prologue: the terminal reconstruction above
        (condition below destination, as the handler's pops see them),
        then pop into a pending-fork entry. None when a hook skipped
        the state."""
        dest_obj, cond_obj = operands
        fired = self._terminal_prologue(state, run.fork.pc,
                                        (cond_obj, dest_obj),
                                        self._fork_pre_hooks(), run)
        state.mstate.pc = run.end_pc
        if not fired:
            return None
        return dense.build_pending_fork(state, dest_obj, cond_obj)

    # -- the batched halt (terminal RETURN/STOP micro-ops) -------------------

    def _halt_epilogue(self, run, halt_rows) -> List:
        """Mirror of execute_state at the halting instruction for every
        completed row: reconstruct the exact pre-halt machine state
        (operands back on the stack, pc at the RETURN/STOP), record the
        statespace snapshot, fire the non-transparent pre hooks
        host-side, then drive the interpreter's own transaction-end
        machinery — return-data built from the POST-decode memory via
        Memory.get_byte, so symbolic bytes the run stored come out as
        the exact terms the interpreter's RETURN would read — with
        execute_state's signal handling (TransactionEndSignal ->
        _end_transaction, VmException -> frame revert) and its post-hook
        kept-loop, verbatim. Deliberately NOT timed into the
        interp_opcode_wall histogram: these rows no longer take the
        per-state path, which is the point."""
        if not halt_rows:
            return []
        op_name = "RETURN" if run.halt.kind == "return" else "STOP"
        pre_hooks, post_hooks = self._halt_hook_lists(op_name)
        # a BARE halt run (no prefix ops) already fired the terminal's
        # pre hooks and committed its snapshot in the batch prologue —
        # the halting instruction IS the run's first instruction, and
        # the prologue saw the exact pre-halt stack; re-firing here
        # would double every hook and snapshot
        bare = len(run.ops) == 1
        successors = []
        for state, operands in halt_rows:
            if bare:
                state.mstate.pc = run.halt.pc
            else:
                push = ()
                if op_name == "RETURN":
                    offset_obj, length_obj = operands
                    push = (length_obj, offset_obj)  # offset on top
                if not self._terminal_prologue(state, run.halt.pc,
                                               push, pre_hooks, run):
                    continue  # no successors, as execute_state returns []
            for successor in self._run_halting_op(state, op_name,
                                                  operands):
                try:
                    for hook in post_hooks:
                        hook(successor)
                except PluginSkipState:
                    continue
                successors.append(successor)
        return successors

    def _halt_hook_lists(self, op_name: str):
        """Cached non-transparent (pre, post) hook lists for a halting
        opcode — the _fork_pre_hooks discipline; registration precedes
        sym_exec, so the lists never change within a run."""
        cached = getattr(self, "_halt_hooks", None)
        if cached is None:
            cached = self._halt_hooks = {}
        lists = cached.get(op_name)
        if lists is None:
            svm = self.svm
            lists = (
                [hook for hook in self._hook_entries(
                    (svm.pre_hooks, svm.instr_pre_hook), op_name)
                 if not getattr(hook, "frontier_transparent", False)],
                [hook for hook in self._hook_entries(
                    (svm.post_hooks, svm.instr_post_hook), op_name)
                 if not getattr(hook, "frontier_transparent", False)],
            )
            cached[op_name] = lists
        return lists

    def _run_halting_op(self, state, op_name: str, operands) -> List:
        """RETURN/STOP semantics for one reconstructed row, with
        execute_state's exception arms: the interpreter's own
        transaction machinery does all the work, so frame reverts,
        caller resumption, world-state harvesting and potential-issue
        checks are the per-state path's code, not a copy. Halting ops
        charge no opcode gas (the signal propagates before
        instructions.execute's accrual — the terminal micro-op's spec
        gas is 0 on both bounds), and RETURN's memory-expansion fee is
        charged here by the same mem_extend call the handler makes."""
        svm = self.svm
        from mythril_tpu.laser.evm_exceptions import VmException
        from mythril_tpu.laser.instructions import concrete_or_none
        from mythril_tpu.laser.state.return_data import ReturnData
        from mythril_tpu.laser.transaction.models import (
            TransactionEndSignal,
        )

        try:
            try:
                transaction = state.current_transaction
                if op_name == "STOP":
                    transaction.end(state, return_data=None, revert=False)
                else:
                    offset_obj, length_obj = operands
                    # both dynamically concrete by admission (an opaque
                    # operand bailed the row to the per-state path,
                    # where the handler concretizes via the solver)
                    length_c = min(concrete_or_none(length_obj), 0x10000)
                    offset_c = concrete_or_none(offset_obj)
                    if length_c:
                        state.mstate.mem_extend(offset_c, length_c)
                    data = [
                        state.mstate.memory.get_byte(offset_c + k)
                        for k in range(length_c)
                    ]
                    transaction.end(
                        state, return_data=ReturnData(data, length_c))
                return []  # unreachable: transaction.end always raises
            except VmException as error:
                # exceptional halt: the frame reverts, exactly as the
                # exec loop's VmException arm handles it
                transaction, return_snapshot = \
                    state.transaction_stack[-1]
                svm._fire_transaction_end_hooks(
                    state, transaction, return_snapshot, True)
                return svm.handle_vm_exception(
                    state, op_name, str(error))[0]
        except TransactionEndSignal as signal:
            return svm._end_transaction(state, signal, op_name)

    # -- cross-fork re-batching (multi-pc) -----------------------------------

    def _rebatch_cohorts(self, successors) -> List:
        """Both forked cohorts stay dense through their NEXT run
        instead of re-entering the worklist for one serialized
        iteration: the fork step's successor set is a multi-pc batch
        keyed on (code-hash, pc-set) — each distinct pc's cohort (the
        groups the dense frame's per-row pc table already names)
        chains through its own compiled run right here, bounded by the
        MYTHRIL_TPU_FRONTIER_MULTIPC budget armed at the top-level
        step. manage_cfg runs FIRST with the fork's op code, so every
        successor gets the exact JUMPI conditional-edge node exec
        would have assigned — the chained results then return to exec
        with op_code None and are never node-managed twice. Cohort
        leads pass the same bounded-loops vetting a strategy yield
        applies; siblings are vetted by _collect_siblings as usual."""
        svm = self.svm
        svm.manage_cfg("JUMPI", successors)
        vet = self._loop_vetter()
        groups = {}
        for state in successors:
            key = (state.environment.code.bytecode_hash,
                   state.mstate.pc)
            groups.setdefault(key, []).append(state)
        out = []
        for group in groups.values():
            if self._chain_budget <= 0:
                out.extend(group)
                continue
            probe = self._run_for(group[0].environment.code,
                                  group[0].mstate.pc)
            if probe is None or probe is _FORK_SITE \
                    or probe in _LANE_SITES:
                # nothing batchable here; site-exit accounting happens
                # when the strategy yields these states normally
                out.extend(group)
                continue
            pending = list(group)
            lead = None
            while pending:
                candidate = pending.pop(0)
                if candidate.mstate.depth >= svm.max_depth:
                    # past the depth bound: hand it back unchained so
                    # the strategy discards it on yield, exactly as the
                    # per-state path would — chaining it would execute
                    # a run the depth filter forbids
                    out.append(candidate)
                    continue
                if vet is None or vet(candidate):
                    lead = candidate
                    break
                # loop bound exceeded: dropped exactly as the
                # strategy's own filter would have dropped it
            if lead is None:
                continue
            self._chain_budget -= 1
            mark = len(svm.work_list)
            svm.work_list.extend(pending)
            stepped = self.try_step(lead)
            if stepped is None:
                # the lead could not batch after all: undo — retract
                # the vet's trace entry (the strategy will vet again on
                # yield) and hand the whole cohort back to the caller
                restored = svm.work_list[mark:]
                del svm.work_list[mark:]
                self._retract_chain_vet(lead)
                out.append(lead)
                out.extend(restored)
            else:
                # a chained step's own terminal results still carry an
                # op code (an inner fork past the budget, a halt run's
                # frame successors): run the node management exec would
                # have run — dropping it here loses the conditional-
                # edge nodes AND the function-entry naming that rides
                # them (found as findings attributed to "fallback" on
                # the dispatch ladder)
                inner_op = getattr(stepped, "op_code", None)
                if inner_op is not None:
                    svm.manage_cfg(inner_op, stepped)
                # non-collected siblings stay in the worklist (unvetted
                # — the strategy vets them on yield, as for any
                # successor set exec extends)
                out.extend(stepped)
        return out

    def _retract_chain_vet(self, state) -> None:
        """A chained cohort lead that failed to batch will be re-vetted
        when the strategy yields it — pop the trace entry this chain's
        vet appended so one real visit counts once (the sibling-side
        twin of _retract_loop_visit)."""
        instruction = state.instruction
        if instruction is None or instruction.opcode != "JUMPDEST" \
                or self._loop_vetter() is None:
            return
        from mythril_tpu.laser.strategy.extensions.bounded_loops import (
            JumpdestCountAnnotation,
        )

        for annotation in state.annotations:
            if isinstance(annotation, JumpdestCountAnnotation):
                if annotation.trace \
                        and annotation.trace[-1] == state.mstate.pc:
                    annotation.trace.pop()
                return

    def _prune_decision(self) -> str:
        """The exec loop's fork-pruning policy, verbatim (one random
        draw per fork batch instead of per row — pruning is sound either
        way, so the draw granularity cannot move a finding)."""
        from mythril_tpu.support.args import args

        svm = self.svm
        pruning_factor = args.pruning_factor
        if pruning_factor is None:
            pruning_factor = 1.0 if svm.execution_timeout > 300 else 0.0
        if (pruning_factor > 0.0 and svm.strategy.run_check()
                and random.random() < pruning_factor):
            return "solve"
        if not svm.strategy.run_check():
            return "park"
        return "keep"

    def _side_skippable(self, pf, run, fall: bool) -> bool:
        """preanalysis.prune_check_skippable for one PENDING side without
        materializing it: everything the check reads (frame stack,
        annotations, code, pc) is shared with the row's state except the
        pc, which is swapped in for the probe."""
        if self.svm.preanalysis is None:
            return False
        from mythril_tpu import preanalysis as pre_mod

        state = pf.state
        old_pc = state.mstate.pc
        state.mstate.pc = run.end_pc if fall else pf.dest
        try:
            return pre_mod.prune_check_skippable(state)
        finally:
            state.mstate.pc = old_pc

    def _fork_epilogue(self, run, pending_forks) -> List:
        """Split the decoded rows into taken/fall-through cohorts and
        settle the sibling feasibility checks as ONE coalesced bundle
        whose blasted cones ride a single ragged stream with the fork
        literals as extra assumption roots (service/scheduler
        solve_fork_batch → tpu/router fork lane). The host CDCL remains
        the sole UNSAT oracle — an infeasible side is masked dead here,
        before it ever materializes as a Python GlobalState."""
        if not pending_forks:
            return []
        svm = self.svm
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        stats = SolverStatistics()
        start = time.monotonic()
        with trace_span("frontier.fork", cat="laser",
                        rows=len(pending_forks)) as sp:
            symbolic = [pf for pf in pending_forks if pf.symbolic]
            decision = self._prune_decision() if symbolic else "keep"
            keep = {}  # id(pf) -> [keep_fall, keep_jump]
            if decision == "solve" and symbolic:
                bundle, pairs, sides = [], [], []
                for pf in symbolic:
                    fall_c, jump_c = pf.side_constraints()
                    check_fall = not self._side_skippable(pf, run,
                                                          fall=True)
                    check_jump = not self._side_skippable(pf, run,
                                                          fall=False)
                    avoided = (not check_fall) + (not check_jump)
                    if avoided:
                        # skipped sides are KEPT unchecked, exactly as
                        # the exec loop's preanalysis filter keeps them
                        stats.add_queries_avoided(avoided)
                    index_fall = index_jump = None
                    if check_fall:
                        index_fall = len(bundle)
                        bundle.append(fall_c)
                        sides.append((pf, 0))
                    if check_jump:
                        index_jump = len(bundle)
                        bundle.append(jump_c)
                        sides.append((pf, 1))
                    if index_fall is not None and index_jump is not None:
                        pairs.append((index_fall, index_jump))
                if bundle:
                    from mythril_tpu.service.scheduler import get_scheduler

                    outcomes = get_scheduler().solve_fork_batch(
                        bundle, pairs, crosscheck=False)
                    pruned = 0
                    for (pf, side), (status, _model) in zip(sides,
                                                            outcomes):
                        if status == "unsat":
                            keep.setdefault(id(pf), [True, True])[side] \
                                = False
                            pruned += 1
                    if pruned:
                        stats.add_fork_pruned(pruned)
            successors = []
            parkable = []  # (pending fork, its materialized sides)
            cohort_extra = 0  # materialized rows beyond one per slot
            for pf in pending_forks:
                flags = keep.get(id(pf), (True, True))
                sides_out = pf.materialize(keep_fall=flags[0],
                                           keep_jump=flags[1])
                successors.extend(sides_out)
                cohort_extra += max(0, len(sides_out) - 1)
                if pf.symbolic:
                    parkable.append(sides_out)
            if decision == "park" and parkable:
                parked = {id(s) for s in self._park_successors(
                    [side for sides in parkable for side in sides])}
                for sides in parkable:
                    if len(sides) == 2 and all(id(s) in parked
                                               for s in sides):
                        # sibling-pair token, set ONLY on sides that
                        # actually parked: the delayed-solving drain
                        # recovers the pairing and routes the bundle
                        # through the fork lane (and clears the token),
                        # so a token can never outlive its one drain —
                        # stale tokens would re-pair long-diverged
                        # states and corrupt the fork counters
                        token = object()
                        for side in sides:
                            side._fork_pair_token = token
                successors = [s for s in successors
                              if id(s) not in parked]
            if symbolic:
                stats.add_frontier_fork(len(symbolic),
                                        time.monotonic() - start,
                                        cohort_rows=cohort_extra)
            sp.set(forked=len(symbolic), successors=len(successors))
        return successors

    def _park_successors(self, successors) -> List:
        """Delayed-solving strategy mirror of the exec loop's pending
        branch: forked sides failing the quick model-cache probe park in
        the base strategy's pending_worklist (batch-solved when the
        ready worklist drains). Returns the PARKED states."""
        base = self.svm.strategy
        while hasattr(base, "super_strategy"):
            base = base.super_strategy
        pending = getattr(base, "pending_worklist", None)
        if pending is None:
            return []
        from mythril_tpu.support.model import model_cache

        parked = []
        for state in successors:
            if model_cache.check_quick_sat(
                    state.world_state.constraints.get_all_constraints()
            ) is None:
                pending.append(state)
                parked.append(state)
        return parked

"""Fast-set dispatch table and straight-line run extraction.

A *run* is the longest prefix of a PR-3 CFG basic block starting at some
pc whose every opcode the batched kernel can execute: stack shuffles
(PUSH/DUP/SWAP/POP), add/sub/mul, bitwise ops, comparisons, shifts,
SIGNEXTEND/BYTE, MLOAD/MSTORE/MSTORE8 on (dynamically) concrete offsets,
and the PC/MSIZE/JUMPDEST bookkeeping ops. Runs stop before block
terminators (the fork points), before any opcode outside the fast set,
before any opcode with non-transparent engine hooks (detection modules,
pruners — those must see every state individually), and before a PUSH
with a symbolic (deploy-time-patched) operand.

Promoted INTO the fast set in earlier rounds (per the
interp_opcode_wall_top histogram): DIV/MOD/SDIV/SMOD as bit-serial
restoring division in words.py, and the block-terminating symbolic
JUMPI as a batched FORK — a run may end in a terminal `jumpi` micro-op
that pops the destination and condition and hands both words to the
host, where the stepper's fork epilogue splits every live row into
taken/fall-through cohorts with per-row pending path-condition literals
(dense.PendingFork).

Promoted this round, on top of the symbolic-value lane
(laser/frontier/symlane.py, `allow_symbolic`): CALLDATALOAD — with a
dynamically-concrete offset it promotes to the canonical calldata term
handle in-batch (the micro-op pops the offset in the kernel and the
lane's structural replay builds `calldata.get_word_at(offset)` at
decode, the exact term the interpreter's handler appends) — and
RETURN/STOP as terminal `halt` micro-ops (`allow_halt`): the run ends
at the halting instruction and the stepper's halt epilogue rebuilds the
exact pre-halt state per row, fires the opcode's pre hooks host-side,
and drives the interpreter's own transaction-end machinery with
return-data built from the post-decode memory. Deliberately still
OUTSIDE the fast set, with the per-state interpreter as the oracle:
ADDMOD/MULMOD/EXP, SHA3/keccak (function-manager constraints), every
storage read (SLOAD/SSTORE carry detector and pruner hooks in every
shipped configuration), and the CALL/CREATE family.

Conditionally transparent hooks: an engine hook carrying a
`frontier_transparent_unless` value predicate (user_assertions' MSTORE
hook: inert unless the written word matches the hevm marker prefix) no
longer cuts runs — the op enters the batch with a compile-time guard
(Run.mem_guards) and any row whose dynamically-written value trips the
predicate bails to the per-state interpreter, where the hook fires
exactly as before.

Compilation statically derives the run's stack shape: `touch` (how many
entries of the caller's stack the run can read — all must be concrete and
annotation-free to enter a batch), `out_len` (slice length it leaves),
and `max_height` (peak growth, for the 1024-entry overflow pre-check).
All of this is per (code, pc), cached by the stepper — states only pay a
dictionary hit per step.
"""

from typing import Callable, List, Optional

from mythril_tpu.laser.frontier import words
from mythril_tpu.support.opcodes import BY_NAME

# shortest run worth a batch: below this the encode/decode term traffic
# cancels the saved interpreter steps
MIN_RUN_OPS = 3
# dense memory window (bytes) carried per state when a run touches
# memory; accesses past it exit the batch at run time
MEM_WINDOW = 2048

_BIN_OPS = {
    "ADD": "add", "SUB": "sub", "MUL": "mul",
    "DIV": "div", "MOD": "mod", "SDIV": "sdiv", "SMOD": "smod",
    "AND": "and", "OR": "or", "XOR": "xor",
    "LT": "lt", "GT": "gt", "SLT": "slt", "SGT": "sgt", "EQ": "eq",
}
_SHIFT_OPS = {"SHL": "shl", "SHR": "shr", "SAR": "sar"}
_SIMPLE_OPS = frozenset(
    ["POP", "NOT", "ISZERO", "BYTE", "SIGNEXTEND",
     "MLOAD", "MSTORE", "MSTORE8", "MSIZE", "PC", "JUMPDEST"])


def is_fast_op(name: str) -> bool:
    return (
        name in _BIN_OPS or name in _SHIFT_OPS or name in _SIMPLE_OPS
        or name.startswith("PUSH") or name.startswith("DUP")
        or name.startswith("SWAP")
    )


class MicroOp:
    """One compiled kernel instruction: kind + static argument + the
    opcode's static gas bounds (accrued after the op, mirroring
    instructions.execute)."""

    __slots__ = ("kind", "arg", "gas_min", "gas_max", "name")

    def __init__(self, kind, arg, gas_min, gas_max, name):
        self.kind = kind
        self.arg = arg
        self.gas_min = gas_min
        self.gas_max = gas_max
        self.name = name


class ForkInfo:
    """Static description of a run's terminal batched-JUMPI fork.

    `dest_source` / `cond_source` mirror Run.out_sources' encoding: the
    original window index the popped operand passes through from (decode
    reuses the ORIGINAL BitVec object — identical identity and
    annotations to the interpreter's pops), or -1 for a kernel-computed
    value (decode interns the kernel word, exactly the constant the
    interpreter's eager folding would have left on the stack)."""

    __slots__ = ("pc", "dest_source", "cond_source")

    def __init__(self, pc: int, dest_source: int, cond_source: int):
        self.pc = pc                  # the JUMPI instruction's address
        self.dest_source = dest_source
        self.cond_source = cond_source


class HaltInfo:
    """Static description of a run's terminal RETURN/STOP micro-op.

    `kind` is "return" or "stop"; for RETURN, `offset_source` /
    `length_source` use ForkInfo's encoding (original window index the
    popped operand passes through from, or -1 for a kernel-computed
    word surfaced in term_out). The operands must be dynamically
    concrete per row — a row popping an opaque offset/length bails to
    the per-state interpreter, whose handler concretizes via the
    solver exactly as before."""

    __slots__ = ("pc", "kind", "offset_source", "length_source")

    def __init__(self, pc: int, kind: str,
                 offset_source: int = -1, length_source: int = -1):
        self.pc = pc                  # the halting instruction's address
        self.kind = kind              # "return" | "stop"
        self.offset_source = offset_source
        self.length_source = length_source


class Run:
    """A compiled straight-line run shared by every sibling state at its
    start pc within one code object."""

    __slots__ = ("ops", "start_pc", "end_pc", "touch", "out_len",
                 "capacity", "max_height", "has_mem", "has_mload",
                 "window", "first_instr", "key", "op_names", "op_pcs",
                 "consumed_windows", "out_sources", "fork", "mem_guards",
                 "cut_at_jumpi", "halt", "has_calldataload",
                 "cut_at_halt", "cut_at_calldataload")

    def __init__(self, ops: List[MicroOp], start_pc: int, end_pc: int,
                 touch: int, out_len: int, max_height: int,
                 has_mem: bool, has_mload: bool, first_instr, key,
                 op_pcs=(), consumed_windows=None, out_sources=None,
                 fork: Optional[ForkInfo] = None, mem_guards=(),
                 cut_at_jumpi: bool = False,
                 halt: Optional[HaltInfo] = None,
                 has_calldataload: bool = False,
                 cut_at_halt: bool = False,
                 cut_at_calldataload: bool = False):
        self.ops = ops
        self.start_pc = start_pc
        self.end_pc = end_pc
        self.touch = touch          # entries read below the initial top
        self.out_len = out_len      # entries the run leaves in their place
        self.capacity = touch + max(max_height, 0)
        self.max_height = max_height  # peak net growth above the start
        self.has_mem = has_mem
        self.has_mload = has_mload
        self.window = MEM_WINDOW if has_mem else 1
        self.first_instr = first_instr
        self.key = key              # kernel jit-cache identity
        self.op_names = tuple(op.name for op in ops)
        self.op_pcs = tuple(op_pcs)  # instruction addresses of the run
        # static slot provenance (stack shuffles are data-independent, so
        # the flow of every original window slot through the run is known
        # at compile time):
        #   consumed_windows  window indices some compute op reads — ONLY
        #                     these must be concrete and taint-free to
        #                     enter a batch; purely-shuffled slots ride
        #                     through as opaque host-side values
        #   out_sources       per output slot: the original window index
        #                     it passes through from (decode reuses the
        #                     ORIGINAL BitVec object — identical object
        #                     identity and annotations to an interpreter
        #                     shuffle), or -1 for kernel-computed values
        self.consumed_windows = (
            frozenset(range(touch)) if consumed_windows is None
            else frozenset(consumed_windows))
        self.out_sources = (
            tuple([-1] * out_len) if out_sources is None
            else tuple(out_sources))
        # terminal batched-JUMPI fork (None for straight-line runs)
        self.fork = fork
        # ((mem-log index, value predicates), ...) for memory stores
        # whose engine hooks are conditionally transparent: decode bails
        # any row whose written value trips a predicate, so the hook
        # fires on the per-state replay exactly as it always did
        self.mem_guards = tuple(mem_guards)
        # the run stops right before a JUMPI it did NOT fork (feature
        # off / no fork prefix): completed rows exit the batch dialect
        # to the interpreter's fork handler and count as fallback exits
        self.cut_at_jumpi = cut_at_jumpi
        # terminal RETURN/STOP (None for non-halting runs); mutually
        # exclusive with `fork`
        self.halt = halt
        # the run contains a promoted CALLDATALOAD: every row's decode
        # takes the symbolic lane's structural replay (the pushed word
        # is a term handle by construction)
        self.has_calldataload = has_calldataload
        # the run stops right before a RETURN/STOP (halt promotion off)
        # or a CALLDATALOAD (symbolic lane off): completed rows exit
        # the batch dialect and count as fallback exits — dialect and
        # symbolic-operand reasons respectively, the symlane on/off
        # comparator
        self.cut_at_halt = cut_at_halt
        self.cut_at_calldataload = cut_at_calldataload

    def __len__(self):
        return len(self.ops)

    def __repr__(self):
        return (f"<Run pc {self.start_pc}..{self.end_pc} "
                f"{len(self.ops)} ops touch={self.touch} "
                f"out={self.out_len}>")


def _compile_one(ins) -> Optional[MicroOp]:
    """Instr -> MicroOp, or None when the instruction cannot enter a
    batch (symbolic PUSH operand, op outside the fast set)."""
    name = ins.opcode
    spec = BY_NAME.get(name)
    if spec is None:
        return None
    gas = (spec.gas_min, spec.gas_max)
    if name.startswith("PUSH"):
        value = ins.argument_int if ins.argument is not None else 0
        if value is None:
            return None  # deploy-time-patched symbolic operand
        return MicroOp("push", tuple(words.word_from_int(value)), *gas,
                       name)
    if name.startswith("DUP"):
        return MicroOp("dup", int(name[3:]), *gas, name)
    if name.startswith("SWAP"):
        return MicroOp("swap", int(name[4:]), *gas, name)
    if name in _BIN_OPS:
        return MicroOp("bin", _BIN_OPS[name], *gas, name)
    if name in _SHIFT_OPS:
        return MicroOp(_SHIFT_OPS[name], None, *gas, name)
    if name == "POP":
        return MicroOp("pop", None, *gas, name)
    if name == "NOT":
        return MicroOp("not", None, *gas, name)
    if name == "ISZERO":
        return MicroOp("iszero", None, *gas, name)
    if name == "BYTE":
        return MicroOp("byte", None, *gas, name)
    if name == "SIGNEXTEND":
        return MicroOp("signextend", None, *gas, name)
    if name in ("MLOAD", "MSTORE", "MSTORE8"):
        return MicroOp(name.lower(), None, *gas, name)
    if name == "MSIZE":
        return MicroOp("msize", None, *gas, name)
    if name == "PC":
        return MicroOp("pc", ins.address, *gas, name)
    if name == "JUMPDEST":
        return MicroOp("nop", None, *gas, name)
    return None


# micro-op kinds that CONSUME their popped operands in a computation (the
# popped values feed limb arithmetic / memory indexing in the kernel, so
# the originating window slots must be concrete). POP discards, DUP/SWAP
# shuffle — their operands ride through opaquely.
_CONSUMING_POPS = {
    "bin": 2, "byte": 2, "shl": 2, "shr": 2, "sar": 2, "signextend": 2,
    "not": 1, "iszero": 1, "mload": 1, "mstore": 2, "mstore8": 2,
}


class _Provenance:
    """Compile-time abstract stack tracking where every slot comes from:
    ("o", d) = the original entry d below the run-start top (1-based),
    None = kernel-computed. Shuffles are data-independent, so this flow
    is exact, not approximate."""

    def __init__(self):
        self.virtual = []      # entries above the untouched stack region
        self.below = 0         # deepest original entry materialized
        self.consumed = set()  # original depths feeding computations
        self.max_height = 0    # peak of len(virtual) - below

    def _ensure(self, needed: int) -> None:
        while len(self.virtual) < needed:
            self.below += 1
            self.virtual.insert(0, ("o", self.below))

    def _pop(self):
        self._ensure(1)
        return self.virtual.pop()

    def apply(self, op: MicroOp) -> None:
        kind = op.kind
        consuming = _CONSUMING_POPS.get(kind, 0)
        if consuming:
            for _ in range(consuming):
                item = self._pop()
                if item is not None:
                    self.consumed.add(item[1])
            if BY_NAME[op.name].pushes:
                self.virtual.append(None)
        elif kind == "pop":
            self._pop()
        elif kind == "calldataload":
            # the popped offset rides opaquely (its concreteness is
            # judged per ROW by the symbolic lane's tag sim, not at
            # compile time); the pushed word is a term handle the
            # kernel never computes
            self._pop()
            self.virtual.append(None)
        elif kind == "dup":
            self._ensure(op.arg)
            self.virtual.append(self.virtual[-op.arg])
        elif kind == "swap":
            self._ensure(op.arg + 1)
            self.virtual[-1], self.virtual[-op.arg - 1] = \
                self.virtual[-op.arg - 1], self.virtual[-1]
        elif kind in ("push", "pc", "msize"):
            self.virtual.append(None)
        # "nop" (JUMPDEST): no stack effect
        self.max_height = max(self.max_height,
                              len(self.virtual) - self.below)


def _instr_width(ins) -> int:
    argument = ins.argument
    if argument is None:
        return 1
    return 1 + len(argument)


def extract_run(summary, pc: int,
                interior_blocked: Callable[[str], bool],
                first_post_blocked: Callable[[str], bool],
                guards_for: Optional[Callable] = None,
                allow_fork: bool = False,
                allow_halt: bool = False,
                allow_symbolic: bool = False) -> Optional[Run]:
    """Compile the straight-line run starting at `pc` inside its PR-3
    basic block, or None when no batchable run (>= MIN_RUN_OPS) starts
    there. `interior_blocked(name)` must be True for opcodes carrying any
    non-transparent pre/post/instr hook; the FIRST opcode may carry pre
    hooks (the stepper fires them host-side per state) but its post hooks
    must be transparent (`first_post_blocked`). `guards_for(name)` may
    return value predicates when EVERY non-transparent hook on a memory
    store is conditionally transparent (frontier_transparent_unless) —
    the op then enters the run guarded instead of cutting it. With
    `allow_fork`, a run may terminate in the block's JUMPI as a batched
    fork (its own pre/post hooks fire host-side in the fork epilogue,
    exactly as the interpreter fires them); with `allow_halt`, in the
    block's RETURN/STOP as a terminal halt micro-op (same host-side
    hook discipline, in the stepper's halt epilogue). `allow_symbolic`
    (the symbolic-value lane) additionally promotes CALLDATALOAD into
    runs — its hooks gate it exactly like any other interior op."""
    block = summary.cfg.block_at(pc)
    if block is None:
        return None
    start_idx = None
    for i, ins in enumerate(block.instrs):
        if ins.address == pc:
            start_idx = i
            break
    if start_idx is None:
        return None

    ops: List[MicroOp] = []
    op_pcs: List[int] = []
    prov = _Provenance()
    has_mem = has_mload = False
    has_calldataload = False
    mem_log_count = 0
    mem_guards = []
    fork: Optional[ForkInfo] = None
    halt: Optional[HaltInfo] = None
    cut_name = None
    end_pc = pc
    for i in range(start_idx, len(block.instrs)):
        ins = block.instrs[i]
        name = ins.opcode
        cut_name = name
        if (allow_fork and name == "JUMPI" and ops):
            # terminal batched fork: pop destination then condition
            # (tracked, NOT consumed — a symbolic condition rides
            # through opaquely; decode rebuilds the exact constraint
            # terms the interpreter's JUMPI handler would append)
            spec = BY_NAME["JUMPI"]
            dest_item = prov._pop()
            cond_item = prov._pop()
            ops.append(MicroOp("jumpi", None, spec.gas_min, spec.gas_max,
                               "JUMPI"))
            op_pcs.append(ins.address)
            end_pc = ins.address + _instr_width(ins)
            fork = ForkInfo(ins.address, 0, 0)
            # stash raw provenance items; converted after the loop
            fork_items = (dest_item, cond_item)
            break
        if allow_halt and name in ("RETURN", "STOP"):
            # terminal halt: RETURN pops offset then length (tracked,
            # NOT consumed — the stepper's halt epilogue needs the
            # exact popped objects, and an opaque operand bails the
            # row per the lane's tag sim); STOP pops nothing. The
            # halting instruction's pre hooks fire host-side in the
            # epilogue on the reconstructed pre-halt state, and its
            # transaction-end path runs the interpreter's own
            # machinery — so no hook gating is needed here.
            spec = BY_NAME[name]
            kind = name.lower()
            halt_items = (None, None)
            if kind == "return":
                offset_item = prov._pop()
                length_item = prov._pop()
                halt_items = (offset_item, length_item)
            ops.append(MicroOp(kind, None, spec.gas_min, spec.gas_max,
                               name))
            op_pcs.append(ins.address)
            end_pc = ins.address + _instr_width(ins)
            halt = HaltInfo(ins.address, kind)
            break
        lane_op = (name == "CALLDATALOAD" and allow_symbolic)
        if not is_fast_op(name) and not lane_op:
            break
        guards = None
        if i == start_idx:
            if first_post_blocked(name):
                return None
        elif interior_blocked(name):
            guards = guards_for(name) if guards_for is not None else None
            if guards is None or name not in ("MSTORE", "MSTORE8"):
                # only value-writing stores are guardable: the predicate
                # needs a dynamically-known written word to judge
                break
        if lane_op:
            spec = BY_NAME["CALLDATALOAD"]
            op = MicroOp("calldataload", None, spec.gas_min,
                         spec.gas_max, name)
        else:
            op = _compile_one(ins)
        if op is None:
            break
        prov.apply(op)
        if op.kind == "mload":
            has_mem = has_mload = True
        elif op.kind in ("mstore", "mstore8"):
            if guards:
                mem_guards.append((mem_log_count, tuple(guards)))
            mem_log_count += 1
            has_mem = True
        elif op.kind == "calldataload":
            has_calldataload = True
        ops.append(op)
        op_pcs.append(ins.address)
        end_pc = ins.address + _instr_width(ins)
        cut_name = None
    # fork runs need one prefix op (the fork is the win even on short
    # runs); halt runs may be BARE — a cohort landing directly on a
    # STOP/RETURN settles through the halt epilogue with no kernel
    # work, which is exactly what removes the per-state STOP wall on
    # dispatch fall-throughs; calldataload-bearing runs are worth a
    # batch at 2 ops (the [PUSH offset, CALLDATALOAD] ladder shape)
    if fork is not None:
        min_ops = 2
    elif halt is not None:
        min_ops = 1
    elif has_calldataload:
        min_ops = 2
    else:
        min_ops = MIN_RUN_OPS
    if len(ops) < min_ops:
        return None
    touch = prov.below

    def _source(item):
        return -1 if item is None else touch - item[1]

    if fork is not None:
        dest_item, cond_item = fork_items
        fork.dest_source = _source(dest_item)
        fork.cond_source = _source(cond_item)
    if halt is not None and halt.kind == "return":
        offset_item, length_item = halt_items
        halt.offset_source = _source(offset_item)
        halt.length_source = _source(length_item)
    return Run(
        ops, pc, end_pc,
        touch=touch, out_len=len(prov.virtual),
        max_height=prov.max_height,
        has_mem=has_mem, has_mload=has_mload,
        first_instr=block.instrs[start_idx],
        op_pcs=op_pcs,
        consumed_windows=[touch - d for d in prov.consumed],
        out_sources=[-1 if item is None else touch - item[1]
                     for item in prov.virtual],
        fork=fork, mem_guards=mem_guards,
        cut_at_jumpi=(fork is None and cut_name == "JUMPI"),
        halt=halt, has_calldataload=has_calldataload,
        cut_at_halt=(halt is None and cut_name in ("RETURN", "STOP")),
        cut_at_calldataload=(cut_name == "CALLDATALOAD"),
        # process-unique token: the kernel's jit cache keys compiled
        # programs by it (object ids would be unsafe — the allocator
        # recycles them, and a stale hit would run the WRONG program)
        key=next(_RUN_TOKENS),
    )


_RUN_TOKENS = iter(range(1, 1 << 62))

"""Vmapped symbolic-execution frontier — batched machine states and
straight-line opcode runs as one device step (north star part (a) of the
BASELINE: the path-exploration worklist executed as a vmapped batch).

LaserEVM steps one python GlobalState at a time through term-building
instruction handlers; once solving is cheap that loop IS the wall. This
package packs N sibling states (same code object, same pc — the ragged
work items) into dense padded arrays (dense.py), compiles the fork-free
straight-line run at that pc — identified by the PR-3 CFG — into a
micro-op program over exact 256-bit limb arithmetic (fastset.py,
words.py), and executes the whole frontier slice in one batched step
(kernel.py: eager numpy on host platforms, jit(vmap(...)) on
accelerators). States whose dynamic behavior leaves the fast path
(symbolic operands on entry, memory access beyond the dense window, gas
exhaustion) exit the batch and replay on the existing per-state
interpreter in laser/instructions.py — the unchanged ground-truth
oracle. Storage ops stay on the oracle path too: SLOAD/SSTORE carry
detector and pruner hooks in every shipped configuration, so a dense
storage fast path would never fire (see fastset.py).

Gating: `--no-vmap-frontier` CLI flag, MYTHRIL_TPU_VMAP_FRONTIER=0|1 env
override, on top of the preanalysis master switch (the run extractor
consumes the PR-3 CFG). Off by default for direct engine embedders;
SymExecWrapper turns it on for analysis runs that do not require a full
per-instruction statespace.
"""

import os

from mythril_tpu.laser.frontier.stepper import FrontierStepper  # noqa: F401


def enabled() -> bool:
    """Env override first, then the --no-vmap-frontier flag, on top of
    the preanalysis master switch (mirrors aig_opt.enabled())."""
    env = os.environ.get("MYTHRIL_TPU_VMAP_FRONTIER", "")
    if env in ("0", "off", "false"):
        return False
    from mythril_tpu import preanalysis

    if not preanalysis.enabled():
        return False
    if env in ("1", "on", "true"):
        return True
    from mythril_tpu.support.args import args

    return not getattr(args, "no_vmap_frontier", False)


def fork_enabled() -> bool:
    """Device-side branching: fork symbolic JUMPI batch-wise inside the
    dense representation. MYTHRIL_TPU_FRONTIER_FORK env override first,
    then the --no-frontier-fork flag, on top of the vmap-frontier
    switch (a fork run IS a frontier run)."""
    env = os.environ.get("MYTHRIL_TPU_FRONTIER_FORK", "")
    if env in ("0", "off", "false"):
        return False
    if not enabled():
        return False
    if env in ("1", "on", "true"):
        return True
    from mythril_tpu.support.args import args

    return not getattr(args, "no_frontier_fork", False)


def symlane_enabled() -> bool:
    """Symbolic-value lanes in the dense representation
    (laser/frontier/symlane.py): stack slots may carry opaque
    term-handles instead of concrete limbs, CALLDATALOAD promotes
    in-batch, and RETURN/STOP become terminal micro-ops. Registered as
    an autotune knob (MYTHRIL_TPU_FRONTIER_SYMLANE, default on) on top
    of the vmap-frontier switch."""
    if not enabled():
        return False
    from mythril_tpu.support.env import env_int

    return env_int("MYTHRIL_TPU_FRONTIER_SYMLANE", 1) != 0


def multipc_width() -> int:
    """Cross-fork re-batching width (MYTHRIL_TPU_FRONTIER_MULTIPC):
    how many fork-cohort groups — distinct (code-hash, pc) keys of one
    fork step's successor set — may chain through their next dense run
    without re-entering the worklist. 0 disables re-batching (every
    cohort pays the one-iteration worklist stall); default 2 covers
    both sides of a fork. An autotune knob."""
    from mythril_tpu.support.env import env_int

    return max(env_int("MYTHRIL_TPU_FRONTIER_MULTIPC", 2), 0)


def fork_depth_cap() -> int:
    """MYTHRIL_TPU_FRONTIER_FORK_DEPTH: rows at or past this state depth
    take the per-state JUMPI instead of the batched fork (an operator
    brake on fork fan-out, never a semantic change). 0 = uncapped."""
    try:
        return max(
            int(os.environ.get("MYTHRIL_TPU_FRONTIER_FORK_DEPTH", "0")
                or 0), 0)
    except ValueError:
        return 0


def clear_caches() -> None:
    from mythril_tpu.laser.frontier import kernel

    kernel.clear_caches()

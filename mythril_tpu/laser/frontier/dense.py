"""Dense machine-state representation of a frontier batch.

N sibling GlobalStates (same code object, same pc) densify into padded
numpy arrays the batched kernel consumes:

  stack        (N, touch, 32) int32   big-endian byte limbs of the top
                                      `touch` stack entries the run can
                                      read (position 0 = deepest)
  depth        (N,)           int32   full per-state stack depth (the
                                      untouched part below the window
                                      stays host-side in python)
  mem          (N, W)         int32   dense byte window of memory
  mem_written  (N, W)         bool    kernel write mask (write-back set)
  msize        (N,)           int32   active memory size (extension gas)
  pc / min_gas / max_gas / gas_limit  (N,) int32
  live         (N,)           bool    real state vs jit-shape padding

Encode never mutates a state; decode commits results only for states the
kernel finished (`ok`), writing the new stack slice (as interned constant
terms — the same values the per-state interpreter's eager constant
folding produces), the written memory bytes (through Memory.write_byte,
so the SMT store chain and the concrete shadow stay in sync), msize, gas,
and the run-end pc. States that bailed mid-run keep their original
objects untouched and replay on the per-state interpreter.
"""

from typing import List, Optional

import numpy as np

from mythril_tpu.laser.frontier import words
from mythril_tpu.laser.frontier.fastset import Run
from mythril_tpu.laser.state.machine_state import STACK_LIMIT
from mythril_tpu.smt import BitVec, symbol_factory

# encode-side gas guard: every kernel gas quantity must stay far from the
# int32 edge (jax under default config has no int64); runs add at most a
# few thousand units per opcode plus window-bounded memory fees
GAS_ENCODE_CAP = 1 << 30


def encodable_word(entry) -> Optional[int]:
    """Concrete, annotation-free 256-bit stack entry -> int, else None.
    Annotations are the taint channel — a dense round-trip would drop
    them, so tainted values keep the state on the per-state path."""
    if not isinstance(entry, BitVec):
        return None
    if entry.annotations or not entry.raw.is_const:
        return None
    return entry.raw.value


def state_encodable(global_state, run: Run) -> bool:
    """Per-state batch admission for `run` (the stepper has already
    checked engine-level and code-level conditions)."""
    mstate = global_state.mstate
    stack = mstate.stack
    if len(stack) < run.touch:
        return False  # underflow: per-state path raises the exact error
    if len(stack) - run.touch + run.capacity > STACK_LIMIT:
        return False  # could overflow mid-run
    if (mstate.gas_limit > GAS_ENCODE_CAP
            or mstate.min_gas_used > GAS_ENCODE_CAP
            or mstate.max_gas_used > GAS_ENCODE_CAP
            or mstate.memory.size > GAS_ENCODE_CAP):
        return False
    # only window slots some compute op CONSUMES must be concrete and
    # taint-free; purely-shuffled slots pass through as opaque host
    # values (decode reuses the original BitVec objects)
    base = len(stack) - run.touch
    for j in run.consumed_windows:
        if encodable_word(stack[base + j]) is None:
            return False
    if run.has_mload and mstate.memory.dense_window(run.window) is None:
        return False
    return True


class DenseFrontier:
    __slots__ = ("stack", "depth", "mem", "mem_written", "msize", "pc",
                 "min_gas", "max_gas", "gas_limit", "live")

    def __init__(self, n: int, touch: int, window: int):
        self.stack = np.zeros((n, touch, words.LIMBS), dtype=np.int32)
        self.depth = np.zeros(n, dtype=np.int32)
        self.mem = np.zeros((n, window), dtype=np.int32)
        self.mem_written = np.zeros((n, window), dtype=bool)
        self.msize = np.zeros(n, dtype=np.int32)
        self.pc = np.zeros(n, dtype=np.int32)
        self.min_gas = np.zeros(n, dtype=np.int32)
        self.max_gas = np.zeros(n, dtype=np.int32)
        self.gas_limit = np.zeros(n, dtype=np.int32)
        self.live = np.zeros(n, dtype=bool)

    @property
    def batch(self) -> int:
        return self.stack.shape[0]


def encode_frontier(states: List, run: Run,
                    pad_to: Optional[int] = None) -> DenseFrontier:
    """Densify `states` (all pre-checked with state_encodable) for `run`,
    padding the batch axis to `pad_to` slots (jit shape bucketing) with
    dead copies of state 0's row shapes."""
    n = len(states)
    slots = max(pad_to or n, n)
    dense = DenseFrontier(slots, run.touch, run.window)
    for i, global_state in enumerate(states):
        mstate = global_state.mstate
        stack = mstate.stack
        base = len(stack) - run.touch
        for j in range(run.touch):
            value = encodable_word(stack[base + j])
            if value is None:
                continue  # passthrough-only slot: limbs are never read
            dense.stack[i, j] = np.frombuffer(
                value.to_bytes(32, "big"), dtype=np.uint8)
        dense.depth[i] = len(stack)
        if run.has_mem:
            window = mstate.memory.dense_window(run.window)
            if window is not None:
                dense.mem[i] = np.frombuffer(bytes(window), dtype=np.uint8)
            # write-only runs on a non-densifiable memory: reads never
            # happen, writes ride the mask — window content is irrelevant
        dense.msize[i] = mstate.memory.size
        dense.pc[i] = mstate.pc
        dense.min_gas[i] = mstate.min_gas_used
        dense.max_gas[i] = mstate.max_gas_used
        dense.gas_limit[i] = mstate.gas_limit
        dense.live[i] = True
    return dense


def decode_state(global_state, run: Run, stack_out, mem, mem_written,
                 msize, min_gas, max_gas, i: int, mem_log=None) -> None:
    """Commit row `i` of the kernel result into `global_state`.

    Memory write-back prefers the kernel's per-store log (`mem_log`):
    replaying each MSTORE/MSTORE8 through write_word_at/write_byte in
    execution order rebuilds the SMT store chain byte-identically to the
    per-state interpreter — a later symbolic-index read over the chain
    then sees the same term structure on either path. Without a log
    (representation-level round-trips) the write mask is applied in
    index order instead."""
    mstate = global_state.mstate
    stack = mstate.stack
    old_window = list(stack[len(stack) - run.touch:]) if run.touch else []
    if run.touch:
        del stack[len(stack) - run.touch:]
    for j in range(run.out_len):
        source = run.out_sources[j]
        if source >= 0:
            # passthrough slot: the SAME object the interpreter's
            # shuffles would have left here (identity + annotations)
            stack.append(old_window[source])
        else:
            stack.append(symbol_factory.BitVecVal(
                words.int_from_limbs(stack_out[i, j]), 256))
    if run.has_mem:
        memory = mstate.memory
        if mem_log is not None:
            log_index = 0
            for op in run.ops:
                if op.kind == "mstore":
                    off, value = mem_log[log_index]
                    log_index += 1
                    memory.write_word_at(
                        int(off[i]), words.int_from_limbs(value[i]))
                elif op.kind == "mstore8":
                    off, value = mem_log[log_index]
                    log_index += 1
                    memory.write_byte(int(off[i]), int(value[i, 31]))
        else:
            for index in np.nonzero(mem_written[i])[0]:
                memory.write_byte(int(index), int(mem[i, index]))
        new_msize = int(msize[i])
        if new_msize > memory.size:
            memory._msize = new_msize
    mstate.min_gas_used = int(min_gas[i])
    mstate.max_gas_used = int(max_gas[i])
    mstate.pc = run.end_pc

"""Dense machine-state representation of a frontier batch.

N sibling GlobalStates (same code object, same pc) densify into padded
numpy arrays the batched kernel consumes:

  stack        (N, touch, 32) int32   big-endian byte limbs of the top
                                      `touch` stack entries the run can
                                      read (position 0 = deepest)
  depth        (N,)           int32   full per-state stack depth (the
                                      untouched part below the window
                                      stays host-side in python)
  mem          (N, W)         int32   dense byte window of memory
  mem_written  (N, W)         bool    kernel write mask (write-back set)
  msize        (N,)           int32   active memory size (extension gas)
  pc / min_gas / max_gas / gas_limit  (N,) int32
  live         (N,)           bool    real state vs jit-shape padding

Encode never mutates a state; decode commits results only for states the
kernel finished (`ok`), writing the new stack slice (as interned constant
terms — the same values the per-state interpreter's eager constant
folding produces), the written memory bytes (through Memory.write_byte,
so the SMT store chain and the concrete shadow stay in sync), msize, gas,
and the run-end pc. States that bailed mid-run keep their original
objects untouched and replay on the per-state interpreter.
"""

from typing import List, Optional

import numpy as np

from mythril_tpu.laser.frontier import words
from mythril_tpu.laser.frontier.fastset import Run
from mythril_tpu.laser.state.machine_state import STACK_LIMIT
from mythril_tpu.smt import BitVec, symbol_factory

# encode-side gas guard: every kernel gas quantity must stay far from the
# int32 edge (jax under default config has no int64); runs add at most a
# few thousand units per opcode plus window-bounded memory fees
GAS_ENCODE_CAP = 1 << 30


def encodable_word(entry) -> Optional[int]:
    """Concrete, annotation-free 256-bit stack entry -> int, else None.
    Annotations are the taint channel — a dense round-trip would drop
    them, so tainted values keep the state on the per-state path."""
    if not isinstance(entry, BitVec):
        return None
    if entry.annotations or not entry.raw.is_const:
        return None
    return entry.raw.value


def state_prechecks(global_state, run: Run):
    """Engine-level admission checks shared by the kernel path and the
    symbolic lane: None when the state may enter a batch at all, else
    the fallback-reason bucket ("dynamic" for shape/gas refusals,
    "symbolic" when the memory window cannot densify)."""
    mstate = global_state.mstate
    stack = mstate.stack
    if len(stack) < run.touch:
        return "dynamic"  # underflow: per-state path raises the error
    if len(stack) - run.touch + run.capacity > STACK_LIMIT:
        return "dynamic"  # could overflow mid-run
    if (mstate.gas_limit > GAS_ENCODE_CAP
            or mstate.min_gas_used > GAS_ENCODE_CAP
            or mstate.max_gas_used > GAS_ENCODE_CAP
            or mstate.memory.size > GAS_ENCODE_CAP):
        return "dynamic"
    if run.has_mload and mstate.memory.dense_window(run.window) is None:
        return "symbolic"
    return None


def consumed_windows_concrete(global_state, run: Run) -> bool:
    """Only window slots some compute op CONSUMES must be concrete and
    taint-free; purely-shuffled slots pass through as opaque host
    values (decode reuses the original BitVec objects)."""
    stack = global_state.mstate.stack
    base = len(stack) - run.touch
    for j in run.consumed_windows:
        if encodable_word(stack[base + j]) is None:
            return False
    return True


def state_encodable(global_state, run: Run) -> bool:
    """Per-state KERNEL-path batch admission for `run` (the stepper has
    already checked engine-level and code-level conditions). The
    symbolic lane (symlane.admit) relaxes the consumed-window
    concreteness requirement per row; this predicate is the lane-off
    behavior and the "kernel" verdict's definition. (The stepper's
    _admit composes the two halves itself so the prechecks — and the
    dense-window build they imply — run once per sibling.)"""
    if state_prechecks(global_state, run) is not None:
        return False
    return consumed_windows_concrete(global_state, run)


class DenseFrontier:
    __slots__ = ("stack", "depth", "mem", "mem_written", "msize", "pc",
                 "min_gas", "max_gas", "gas_limit", "live", "sym_tags",
                 "handles")

    def __init__(self, n: int, touch: int, window: int):
        self.stack = np.zeros((n, touch, words.LIMBS), dtype=np.int32)
        self.depth = np.zeros(n, dtype=np.int32)
        self.mem = np.zeros((n, window), dtype=np.int32)
        self.mem_written = np.zeros((n, window), dtype=bool)
        self.msize = np.zeros(n, dtype=np.int32)
        self.pc = np.zeros(n, dtype=np.int32)
        self.min_gas = np.zeros(n, dtype=np.int32)
        self.max_gas = np.zeros(n, dtype=np.int32)
        self.gas_limit = np.zeros(n, dtype=np.int32)
        self.live = np.zeros(n, dtype=bool)
        # the symbolic-value lane (populated only under encode's `lane`
        # mode): per-slot tag (True = the limbs are a placeholder and
        # the slot's value is an opaque term handle) + the per-row
        # handle table — the row's window entries as the ORIGINAL
        # BitVec objects, snapshotted at encode time. The kernel never
        # reads them; the lane's structural replay initializes its
        # shadow stack from exactly this table.
        self.sym_tags = None
        self.handles = None

    @property
    def batch(self) -> int:
        return self.stack.shape[0]


def encode_frontier(states: List, run: Run,
                    pad_to: Optional[int] = None,
                    lane: bool = False) -> DenseFrontier:
    """Densify `states` (all pre-checked with state_encodable or the
    symbolic lane's admit) for `run`, padding the batch axis to
    `pad_to` slots (jit shape bucketing) with dead copies of state 0's
    row shapes. With `lane`, each row additionally carries the
    symbolic-value lane's tag vector and handle table (the window's
    ORIGINAL BitVec objects) — what the structural replay decodes
    opaque rows from."""
    n = len(states)
    slots = max(pad_to or n, n)
    dense = DenseFrontier(slots, run.touch, run.window)
    if lane:
        dense.sym_tags = np.zeros((slots, run.touch), dtype=bool)
        dense.handles = [None] * slots
    for i, global_state in enumerate(states):
        mstate = global_state.mstate
        stack = mstate.stack
        base = len(stack) - run.touch
        if lane:
            dense.handles[i] = list(stack[base:]) if run.touch else []
        for j in range(run.touch):
            value = encodable_word(stack[base + j])
            if value is None:
                # opaque lane: the limbs stay a placeholder; the tag
                # plus the per-row handle table carry the slot's real
                # value (the original BitVec object) host-side for the
                # passthrough/structural-replay decode
                if lane:
                    dense.sym_tags[i, j] = True
                continue
            dense.stack[i, j] = np.frombuffer(
                value.to_bytes(32, "big"), dtype=np.uint8)
        dense.depth[i] = len(stack)
        if run.has_mem:
            window = mstate.memory.dense_window(run.window)
            if window is not None:
                dense.mem[i] = np.frombuffer(bytes(window), dtype=np.uint8)
            # write-only runs on a non-densifiable memory: reads never
            # happen, writes ride the mask — window content is irrelevant
        dense.msize[i] = mstate.memory.size
        dense.pc[i] = mstate.pc
        dense.min_gas[i] = mstate.min_gas_used
        dense.max_gas[i] = mstate.max_gas_used
        dense.gas_limit[i] = mstate.gas_limit
        dense.live[i] = True
    return dense


def guard_tripped(run: Run, mem_log, i: int) -> bool:
    """Row `i` wrote a value some conditionally-transparent hook is NOT
    inert for (Run.mem_guards, e.g. the hevm assertion marker): the row
    must bail and replay per-state so the hook fires exactly as the
    interpreter would have fired it."""
    for log_index, predicates in run.mem_guards:
        value = words.int_from_limbs(mem_log[log_index][1][i])
        if any(predicate(value) for predicate in predicates):
            return True
    return False


def fork_operands(global_state, run: Run, fork_out, i: int):
    """Row `i`'s popped (destination, condition) BitVecs for a fork run,
    read from the UNTOUCHED pre-decode state: a window-sourced operand
    is the original stack object (identity + annotations, exactly what
    the interpreter's pops would see), a kernel-computed one interns the
    kernel's word — the same constant eager folding would have left."""
    stack = global_state.mstate.stack
    base = len(stack) - run.touch

    def operand(source, word):
        if source >= 0:
            return stack[base + source]
        return symbol_factory.BitVecVal(words.int_from_limbs(word[i]), 256)

    return (operand(run.fork.dest_source, fork_out[0]),
            operand(run.fork.cond_source, fork_out[1]))


def halt_operands(global_state, run: Run, term_out, i: int):
    """Row `i`'s popped (offset, length) BitVecs for a RETURN-halting
    run, with fork_operands' exact source discipline (original window
    object, or the kernel word interned). Both are dynamically concrete
    for admitted rows — the lane's tag sim bails opaque operands to the
    per-state interpreter, whose handler concretizes via the solver."""
    stack = global_state.mstate.stack
    base = len(stack) - run.touch

    def operand(source, word):
        if source >= 0:
            return stack[base + source]
        return symbol_factory.BitVecVal(words.int_from_limbs(word[i]), 256)

    return (operand(run.halt.offset_source, term_out[0]),
            operand(run.halt.length_source, term_out[1]))


class PendingFork:
    """One forked row's pending path-condition table entry: the exact
    BitVec literals the interpreter's JUMPI handler would append (same
    term identity and annotation discipline as the opaque-slot
    passthrough), held dense-side until the coalesced feasibility
    verdict decides which cohort materializes — an infeasible side is
    masked dead before it ever becomes a Python GlobalState."""

    __slots__ = ("state", "dest", "branch", "negated", "take_fall",
                 "take_jump", "fall_constrains", "jump_constrains")

    def __init__(self, state, dest, branch, negated, take_fall,
                 take_jump, fall_constrains, jump_constrains):
        self.state = state
        self.dest = dest
        self.branch = branch        # cond != 0 (taken-side literal)
        self.negated = negated      # cond == 0 (fall-through literal)
        self.take_fall = take_fall
        self.take_jump = take_jump
        self.fall_constrains = fall_constrains
        self.jump_constrains = jump_constrains

    @property
    def symbolic(self) -> bool:
        """Both sides live — the row genuinely forks and its sibling
        feasibility pair rides the coalesced fork bundle."""
        return self.take_fall and self.take_jump

    def side_constraints(self):
        """(fall-side, taken-side) full constraint lists for the
        feasibility bundle, built WITHOUT cloning the state — the base
        list plus the pending literal, exactly the set the interpreter
        path would hand the exec-loop fork pruner."""
        base = list(self.state.world_state.constraints.get_all_constraints())
        return base + [self.negated], base + [self.branch]

    def materialize(self, keep_fall: bool = True,
                    keep_jump: bool = True) -> List:
        """Commit the surviving sides, mirroring the interpreter's
        JUMPI handler object discipline: the fall-through side CLONES
        the row's state (pc is already at the fall-through address from
        decode), the taken side mutates the original in place; the
        pending literals append to each survivor's constraints."""
        successors = []
        state = self.state
        if self.take_fall and keep_fall:
            fallthrough = state.clone()
            fallthrough.mstate.depth += 1
            if self.fall_constrains:
                fallthrough.world_state.constraints.append(self.negated)
            successors.append(fallthrough)
        if self.take_jump and keep_jump:
            state.mstate.pc = self.dest
            state.mstate.depth += 1
            if self.jump_constrains:
                state.world_state.constraints.append(self.branch)
            successors.append(state)
        return successors


def build_pending_fork(global_state, dest_obj,
                       cond_obj) -> Optional[PendingFork]:
    """Mirror of the interpreter's JUMPI handler term construction for
    one decoded row, as a PENDING entry: which sides exist, which append
    a constraint, and the literal terms themselves — bit-identical to
    what jumpi_ would have produced. None when the destination is
    symbolic (the per-state replay raises the exact exception)."""
    from mythril_tpu.laser.instructions import bv, concrete_or_none
    from mythril_tpu.smt import is_false, is_true, simplify

    dest_c = concrete_or_none(dest_obj)
    if dest_c is None:
        return None
    branch = simplify(cond_obj != bv(0))
    negated = simplify(cond_obj == bv(0))
    take_fall = not is_false(negated)
    take_jump = (
        dest_c in global_state.environment.code.valid_jump_destinations
        and not is_false(branch))
    return PendingFork(
        global_state, dest_c, branch, negated,
        take_fall=take_fall, take_jump=take_jump,
        fall_constrains=not is_true(negated),
        jump_constrains=not is_true(branch))


def decode_state(global_state, run: Run, stack_out, mem, mem_written,
                 msize, min_gas, max_gas, i: int, mem_log=None) -> None:
    """Commit row `i` of the kernel result into `global_state`.

    Memory write-back prefers the kernel's per-store log (`mem_log`):
    replaying each MSTORE/MSTORE8 through write_word_at/write_byte in
    execution order rebuilds the SMT store chain byte-identically to the
    per-state interpreter — a later symbolic-index read over the chain
    then sees the same term structure on either path. Without a log
    (representation-level round-trips) the write mask is applied in
    index order instead."""
    mstate = global_state.mstate
    stack = mstate.stack
    old_window = list(stack[len(stack) - run.touch:]) if run.touch else []
    if run.touch:
        del stack[len(stack) - run.touch:]
    for j in range(run.out_len):
        source = run.out_sources[j]
        if source >= 0:
            # passthrough slot: the SAME object the interpreter's
            # shuffles would have left here (identity + annotations)
            stack.append(old_window[source])
        else:
            stack.append(symbol_factory.BitVecVal(
                words.int_from_limbs(stack_out[i, j]), 256))
    if run.has_mem:
        memory = mstate.memory
        if mem_log is not None:
            log_index = 0
            for op in run.ops:
                if op.kind == "mstore":
                    off, value = mem_log[log_index]
                    log_index += 1
                    memory.write_word_at(
                        int(off[i]), words.int_from_limbs(value[i]))
                elif op.kind == "mstore8":
                    off, value = mem_log[log_index]
                    log_index += 1
                    memory.write_byte(int(off[i]), int(value[i, 31]))
        else:
            for index in np.nonzero(mem_written[i])[0]:
                memory.write_byte(int(index), int(mem[i, index]))
        new_msize = int(msize[i])
        if new_msize > memory.size:
            memory._msize = new_msize
    mstate.min_gas_used = int(min_gas[i])
    mstate.max_gas_used = int(max_gas[i])
    mstate.pc = run.end_pc

"""Concrete-value transaction setup (reference
laser/ethereum/transaction/concolic.py:172).

Used by the VMTests conformance harness and concolic mode: all tx fields
(caller, calldata, value, gas) are concrete. Unlike the symbolic setup,
NO caller-in-ACTORS constraint is added — replayed transactions come from
arbitrary recorded senders (reference concolic.py:123-149 has its own
_setup_global_state_for_execution without the actor disjunction)."""

from typing import List, Optional

from mythril_tpu.laser.state.calldata import BasicConcreteCalldata
from mythril_tpu.laser.transaction.models import MessageCallTransaction
from mythril_tpu.smt import symbol_factory


def _setup_concrete_state_for_execution(laser_evm, transaction) -> None:
    """Seed the worklist WITHOUT the symbolic actor constraint. A concrete
    transaction.block_number pins NUMBER (replayed transactions come from a
    known block — this is what makes the BlockNumberDynamicJump*
    conformance vectors executable, where the jump target derives from
    NUMBER); inner frames inherit it in svm._start_inner_transaction."""
    global_state = transaction.initial_global_state()
    global_state.transaction_stack.append((transaction, None))
    global_state.world_state.transaction_sequence.append(transaction)
    global_state.node = laser_evm.new_node(
        transaction, global_state.world_state.constraints
    )
    laser_evm.work_list.append(global_state)


def execute_transaction(
    laser_evm,
    callee_address,
    caller_address,
    data: Optional[List[int]] = None,
    gas_price: int = 10,
    gas_limit: int = 8_000_000,
    value: int = 0,
    origin_address=None,
    code=None,
    track_gas: bool = False,
    block_number=None,
):
    """Seed and run one concrete message call on every open world state."""
    if isinstance(callee_address, int):
        callee_address = symbol_factory.BitVecVal(callee_address, 256)
    if isinstance(caller_address, int):
        caller_address = symbol_factory.BitVecVal(caller_address, 256)
    if origin_address is None:
        origin_address = caller_address
    elif isinstance(origin_address, int):
        origin_address = symbol_factory.BitVecVal(origin_address, 256)
    open_states = laser_evm.open_states[:]
    del laser_evm.open_states[:]
    for world_state in open_states:
        callee_account = world_state.accounts_exist_or_load(callee_address)
        tx_code = callee_account.code
        if code is not None:
            from mythril_tpu.disasm import Disassembly

            tx_code = code if isinstance(code, Disassembly) else Disassembly(code)
        transaction = MessageCallTransaction(
            world_state=world_state,
            callee_account=callee_account,
            caller=caller_address,
            call_data=BasicConcreteCalldata("concrete", list(data or [])),
            gas_price=symbol_factory.BitVecVal(gas_price, 256),
            gas_limit=gas_limit,
            origin=origin_address,
            code=tx_code,
            call_value=symbol_factory.BitVecVal(value, 256),
            block_number=block_number,
        )
        _setup_concrete_state_for_execution(laser_evm, transaction)
    return laser_evm.exec(track_gas=track_gas)


def execute_message_call(
    laser_evm,
    callee_address,
    caller_address,
    origin_address,
    data,
    gas_limit,
    gas_price,
    value,
    code=None,
    track_gas=False,
    block_number=None,
):
    """Reference-shaped alias (concolic.py:73) used by the VMTests harness."""
    return execute_transaction(
        laser_evm,
        callee_address,
        caller_address,
        data=list(data),
        gas_price=gas_price,
        gas_limit=gas_limit,
        value=value,
        origin_address=origin_address,
        code=code,
        track_gas=track_gas,
        block_number=block_number,
    )

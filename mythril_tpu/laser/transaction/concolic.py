"""Concrete-value transaction setup (reference
laser/ethereum/transaction/concolic.py:172).

Used by the VMTests-style conformance harness and concolic mode: all tx
fields (caller, calldata, value, gas) are concrete."""

from typing import List, Optional

from mythril_tpu.laser.state.calldata import BasicConcreteCalldata
from mythril_tpu.laser.transaction.models import MessageCallTransaction
from mythril_tpu.smt import symbol_factory


def execute_transaction(
    laser_evm,
    callee_address,
    caller_address,
    data: Optional[List[int]] = None,
    gas_price: int = 10,
    gas_limit: int = 8_000_000,
    value: int = 0,
    track_gas: bool = False,
) -> None:
    """Seed and run one concrete message call on every open world state."""
    if isinstance(callee_address, int):
        callee_address = symbol_factory.BitVecVal(callee_address, 256)
    if isinstance(caller_address, int):
        caller_address = symbol_factory.BitVecVal(caller_address, 256)
    open_states = laser_evm.open_states[:]
    del laser_evm.open_states[:]
    for world_state in open_states:
        callee_account = world_state.accounts_exist_or_load(callee_address)
        transaction = MessageCallTransaction(
            world_state=world_state,
            callee_account=callee_account,
            caller=caller_address,
            call_data=BasicConcreteCalldata("concrete", list(data or [])),
            gas_price=symbol_factory.BitVecVal(gas_price, 256),
            gas_limit=gas_limit,
            origin=caller_address,
            call_value=symbol_factory.BitVecVal(value, 256),
        )
        from mythril_tpu.laser.transaction.symbolic import (
            _setup_global_state_for_execution,
        )

        _setup_global_state_for_execution(laser_evm, transaction)
    laser_evm.exec(track_gas=track_gas)

from mythril_tpu.laser.transaction.models import (  # noqa: F401
    BaseTransaction,
    ContractCreationTransaction,
    MessageCallTransaction,
    TransactionEndSignal,
    TransactionStartSignal,
    tx_id_manager,
)
from mythril_tpu.laser.transaction.symbolic import (  # noqa: F401
    ACTORS,
    execute_contract_creation,
    execute_message_call,
)

"""Symbolic transaction setup (reference laser/ethereum/transaction/symbolic.py).

ACTORS are the well-known analysis addresses (creator/attacker/someguy);
execute_message_call drains open world states and seeds the worklist with a
fully symbolic tx per state, constraining caller ∈ ACTORS (reference
:214-216)."""

from typing import List, Optional

from mythril_tpu.laser.state.calldata import SymbolicCalldata
from mythril_tpu.laser.state.world_state import WorldState
from mythril_tpu.laser.transaction.models import (
    ContractCreationTransaction,
    MessageCallTransaction,
)
from mythril_tpu.smt import Or, symbol_factory

CREATOR_ADDRESS = 0xAFFEAFFEAFFEAFFEAFFEAFFEAFFEAFFEAFFEAFFE
ATTACKER_ADDRESS = 0xDEADBEEFDEADBEEFDEADBEEFDEADBEEFDEADBEEF
SOMEGUY_ADDRESS = 0xAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAAA


class Actors:
    def __init__(self):
        self.addresses = {
            "CREATOR": symbol_factory.BitVecVal(CREATOR_ADDRESS, 256),
            "ATTACKER": symbol_factory.BitVecVal(ATTACKER_ADDRESS, 256),
            "SOMEGUY": symbol_factory.BitVecVal(SOMEGUY_ADDRESS, 256),
        }

    @property
    def creator(self):
        return self.addresses["CREATOR"]

    @property
    def attacker(self):
        return self.addresses["ATTACKER"]

    @property
    def someguy(self):
        return self.addresses["SOMEGUY"]

    def __getitem__(self, item):
        return self.addresses[item]


ACTORS = Actors()


def generate_function_constraints(calldata, func_hashes: List[bytes]):
    """Constrain the 4-byte selector when --transaction-sequences pins
    functions (reference symbolic.py:74-100)."""
    if not func_hashes:
        return []
    constraints = []
    options = []
    for func_hash in func_hashes:
        if func_hash == -1:  # fallback: calldatasize < 4
            options.append(calldata.calldatasize < 4)
        else:
            selector = int.from_bytes(func_hash, "big") if isinstance(
                func_hash, bytes
            ) else func_hash
            word = calldata.get_word_at(0)
            from mythril_tpu.smt import Extract

            options.append(
                Extract(255, 224, word)
                == symbol_factory.BitVecVal(selector, 32)
            )
    constraints.append(Or(*options))
    return constraints


def execute_message_call(laser_evm, callee_address, func_hashes=None) -> None:
    """One fully symbolic message call per open world state
    (reference :103-148)."""
    open_states = laser_evm.open_states[:]
    del laser_evm.open_states[:]
    for world_state in open_states:
        if callee_address.symbolic is False and (
            callee_address.concrete_value not in world_state.accounts
        ):
            continue
        transaction = build_message_call_transaction(
            world_state, callee_address, func_hashes
        )
        _setup_global_state_for_execution(laser_evm, transaction)
    laser_evm.exec()


def build_message_call_transaction(world_state: WorldState, callee_address,
                                   func_hashes=None):
    callee_account = world_state.accounts_exist_or_load(callee_address)
    tx = MessageCallTransaction(
        world_state=world_state,
        callee_account=callee_account,
        caller=symbol_factory.BitVecSym("sender", 256),  # renamed per-tx below
        call_data=None,
        init_call_data=False,
    )
    tx.caller = symbol_factory.BitVecSym(f"sender_{tx.id}", 256)
    tx.call_data = SymbolicCalldata(tx.id)
    tx.origin = tx.caller  # analysis assumption: EOA caller (origin==caller)
    tx.func_hashes = func_hashes
    return tx


def execute_contract_creation(
    laser_evm,
    contract_initialization_code,
    contract_name=None,
    world_state: Optional[WorldState] = None,
) -> "Account":
    """Symbolic creation tx from the CREATOR actor (reference :151-196)."""
    from mythril_tpu.disasm import Disassembly

    world_state = world_state or WorldState()
    open_states = [world_state]
    del laser_evm.open_states[:]
    new_account = None
    for open_world_state in open_states:
        prev_world_state = open_world_state.clone()
        code_bytes = (
            bytes.fromhex(contract_initialization_code.replace("0x", ""))
            if isinstance(contract_initialization_code, str)
            else contract_initialization_code
        )
        # split off constructor arguments appended after the init code
        account = open_world_state.create_account(
            address=None,
            concrete_storage=True,
            creator=None,
        )
        account.contract_name = contract_name or account.contract_name
        tx = ContractCreationTransaction(
            world_state=open_world_state,
            callee_account=account,
            caller=ACTORS.creator,
            origin=ACTORS.creator,
            code=Disassembly(code_bytes),
            # symbolic calldata on purpose — constructor args live past the
            # init code and are modelled via CODESIZE/CODECOPY special cases
            # (reference symbolic.py:173-175)
            call_data=None,
            gas_price=None,
            call_value=symbol_factory.BitVecSym("creation_value", 256),
            prev_world_state=prev_world_state,
            contract_name=contract_name,
        )
        _setup_global_state_for_execution(laser_evm, tx)
        new_account = account
    laser_evm.exec(True)
    return new_account


def _setup_global_state_for_execution(laser_evm, transaction) -> None:
    """Seed the worklist with the tx's initial state (reference :199-230)."""
    global_state = transaction.initial_global_state()
    global_state.transaction_stack.append((transaction, None))
    # caller is one of the analysis actors
    if isinstance(transaction, MessageCallTransaction):
        global_state.world_state.constraints.append(
            Or(
                transaction.caller == ACTORS.creator,
                transaction.caller == ACTORS.attacker,
                transaction.caller == ACTORS.someguy,
            )
        )
        func_hashes = getattr(transaction, "func_hashes", None)
        if func_hashes:
            for constraint in generate_function_constraints(
                transaction.call_data, func_hashes
            ):
                global_state.world_state.constraints.append(constraint)
    global_state.world_state.transaction_sequence.append(transaction)
    global_state.node = laser_evm.new_node(
        transaction, global_state.world_state.constraints
    )
    laser_evm.work_list.append(global_state)

"""Transaction models (reference laser/ethereum/transaction/transaction_models.py).

MessageCallTransaction / ContractCreationTransaction produce the initial
GlobalState of a call frame; `end()` raises TransactionEndSignal, caught by
the engine to pop the frame (reference :199-208, svm.py:475-519)."""

from typing import List, Optional

from mythril_tpu.disasm import Disassembly
from mythril_tpu.laser.state.calldata import (
    BaseCalldata,
    ConcreteCalldata,
    SymbolicCalldata,
)
from mythril_tpu.laser.state.environment import Environment
from mythril_tpu.laser.state.global_state import GlobalState
from mythril_tpu.laser.state.machine_state import MachineState
from mythril_tpu.laser.state.world_state import WorldState
from mythril_tpu.smt import BitVec, UGE, symbol_factory


class _TxIdManager:
    def __init__(self):
        self._next = 0

    def get_next_tx_id(self) -> str:
        self._next += 1
        return str(self._next)

    def restart_counter(self):
        self._next = 0


tx_id_manager = _TxIdManager()


class TransactionStartSignal(Exception):
    """Raised by call/create opcodes to push a new frame."""

    def __init__(self, transaction, op_code: str, global_state: GlobalState):
        self.transaction = transaction
        self.op_code = op_code
        self.global_state = global_state


class TransactionEndSignal(Exception):
    """Raised by STOP/RETURN/REVERT/SELFDESTRUCT to pop the frame."""

    def __init__(self, global_state: GlobalState, revert: bool = False):
        self.global_state = global_state
        self.revert = revert


class BaseTransaction:
    def __init__(
        self,
        world_state: WorldState,
        callee_account=None,
        caller: Optional[BitVec] = None,
        call_data: Optional[BaseCalldata] = None,
        gas_price=None,
        gas_limit=None,
        origin: Optional[BitVec] = None,
        code: Optional[Disassembly] = None,
        call_value=None,
        init_call_data: bool = True,
        static: bool = False,
        base_fee=None,
        block_number=None,
    ):
        self.id = tx_id_manager.get_next_tx_id()
        self.world_state = world_state
        self.callee_account = callee_account
        self.caller = caller if caller is not None else symbol_factory.BitVecVal(0, 256)
        self.origin = (
            origin
            if origin is not None
            else symbol_factory.BitVecSym(f"origin{self.id}", 256)
        )
        self.gas_price = (
            gas_price
            if gas_price is not None
            else symbol_factory.BitVecSym(f"gasprice{self.id}", 256)
        )
        self.gas_limit = gas_limit if gas_limit is not None else 8_000_000
        self.call_value = (
            call_value
            if call_value is not None
            else symbol_factory.BitVecSym(f"call_value{self.id}", 256)
        )
        self.base_fee = (
            base_fee
            if base_fee is not None
            else symbol_factory.BitVecSym(f"basefee{self.id}", 256)
        )
        self.block_number = block_number
        if call_data is not None:
            self.call_data = call_data
        elif init_call_data:
            # Default to symbolic calldata — the reference does this even for
            # creation txs ("easier to model the calldata symbolically",
            # transaction_models.py:112-113, symbolic.py:173-175) and
            # compensates in CODESIZE/CODECOPY/CALLDATACOPY.
            self.call_data = SymbolicCalldata(self.id)
        else:
            self.call_data = None
        self.code = code
        self.static = static
        self.return_data = None
        self.return_data_size = None

    def initial_global_state_from_environment(self, environment, active_function):
        world_state = self.world_state
        if self.block_number is not None:
            # concrete replay (concolic/VMTests): NUMBER is pinned for this
            # frame; inner frames inherit it in svm._start_inner_transaction
            environment.block_number = (
                self.block_number
                if isinstance(self.block_number, BitVec)
                else symbol_factory.BitVecVal(self.block_number, 256)
            )
        global_state = GlobalState(
            world_state, environment,
            machine_state=MachineState(gas_limit=self.gas_limit),
        )
        global_state.environment.active_function_name = active_function
        sender = environment.sender
        receiver = environment.active_account.address
        value = environment.callvalue
        # transfer constraint: sender must afford the value
        global_state.world_state.constraints.append(
            UGE(global_state.world_state.balances[sender], value)
        )
        global_state.world_state.balances[sender] = (
            global_state.world_state.balances[sender] - value
        )
        global_state.world_state.balances[receiver] = (
            global_state.world_state.balances[receiver] + value
        )
        return global_state

    def end(self, global_state: GlobalState, return_data=None, revert=False):
        self.return_data = return_data
        raise TransactionEndSignal(global_state, revert)

    def __str__(self):
        return (
            f"{type(self).__name__} {self.id} from "
            f"{self.caller} to {getattr(self.callee_account, 'address', '?')}"
        )


class MessageCallTransaction(BaseTransaction):
    def initial_global_state(self) -> GlobalState:
        environment = Environment(
            self.callee_account,
            self.caller,
            self.call_data,
            self.gas_price,
            self.call_value,
            self.origin,
            code=self.code or self.callee_account.code,
            static=self.static,
            basefee=self.base_fee,
        )
        return self.initial_global_state_from_environment(
            environment, active_function="fallback"
        )


class ContractCreationTransaction(BaseTransaction):
    def __init__(self, *args, prev_world_state: Optional[WorldState] = None,
                 contract_name: Optional[str] = None, **kwargs):
        # snapshot the pre-tx world for exploit replay (reference :229)
        self.prev_world_state = prev_world_state
        self.contract_name = contract_name
        super().__init__(*args, **kwargs)

    def initial_global_state(self) -> GlobalState:
        environment = Environment(
            self.callee_account,
            self.caller,
            self.call_data,
            self.gas_price,
            self.call_value,
            self.origin,
            code=self.code,
            basefee=self.base_fee,
        )
        return self.initial_global_state_from_environment(
            environment, active_function="constructor"
        )

    def end(self, global_state: GlobalState, return_data=None, revert=False):
        """Assign returned runtime bytecode to the new account
        (reference :283-290)."""
        if return_data is not None and not revert:
            if isinstance(return_data, bytes):
                self.callee_account.code = Disassembly(return_data)
            global_state.environment.active_account = self.callee_account
        self.return_data = return_data
        raise TransactionEndSignal(global_state, revert)

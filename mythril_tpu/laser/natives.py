"""Precompiled contracts 0x01-0x0a (reference laser/ethereum/natives.py:279).

This environment has no coincurve/py_ecc/blake2b native deps, so everything
is implemented here: secp256k1 recovery (pure Python), SHA-256 (hashlib),
RIPEMD-160 (pure Python), modexp (pow), alt_bn128 group ops, BLAKE2b F.
Symbolic inputs raise NativeContractException -> the caller falls back to a
fresh symbolic return buffer."""

import hashlib
from typing import Callable, List

from mythril_tpu.utils.keccak import keccak256


class NativeContractException(Exception):
    pass


def _concrete_bytes(data) -> bytes:
    """data: list of BitVec(8)/ints -> bytes; raises on symbolic bytes."""
    out = bytearray()
    for byte in data:
        if isinstance(byte, int):
            out.append(byte & 0xFF)
            continue
        if byte.symbolic:
            raise NativeContractException("symbolic input to precompile")
        out.append(byte.concrete_value & 0xFF)
    return bytes(out)


# -- secp256k1 ecrecover -----------------------------------------------------

_P = 2 ** 256 - 2 ** 32 - 977
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _inv_mod(a: int, m: int) -> int:
    return pow(a, m - 2, m)


def _ec_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % _P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1) * _inv_mod(2 * y1, _P) % _P
    else:
        lam = (y2 - y1) * _inv_mod((x2 - x1) % _P, _P) % _P
    x3 = (lam * lam - x1 - x2) % _P
    y3 = (lam * (x1 - x3) - y1) % _P
    return (x3, y3)


def _ec_mul(point, scalar: int):
    result = None
    addend = point
    while scalar:
        if scalar & 1:
            result = _ec_add(result, addend)
        addend = _ec_add(addend, addend)
        scalar >>= 1
    return result


def ecrecover_raw(msg_hash: bytes, v: int, r: int, s: int) -> bytes:
    """Returns the 20-byte address or b'' on failure."""
    if v not in (27, 28) or not (1 <= r < _N) or not (1 <= s < _N):
        return b""
    x = r
    alpha = (pow(x, 3, _P) + 7) % _P
    beta = pow(alpha, (_P + 1) // 4, _P)
    y = beta if (beta % 2 == 0) == (v == 27) else _P - beta
    if pow(y, 2, _P) != alpha:
        return b""
    e = int.from_bytes(msg_hash, "big")
    point = _ec_add(
        _ec_mul((x, y), s),
        _ec_mul((_GX, _GY), (-e) % _N),
    )
    if point is None:
        return b""
    recovered = _ec_mul(point, _inv_mod(x, _N))
    if recovered is None:
        return b""
    rx, ry = recovered
    pub = rx.to_bytes(32, "big") + ry.to_bytes(32, "big")
    return keccak256(pub)[12:]


def ecrecover(data: List) -> List[int]:
    raw = _concrete_bytes(data)
    raw = raw + b"\x00" * (128 - len(raw)) if len(raw) < 128 else raw[:128]
    msg_hash = raw[0:32]
    v = int.from_bytes(raw[32:64], "big")
    r = int.from_bytes(raw[64:96], "big")
    s = int.from_bytes(raw[96:128], "big")
    try:
        address = ecrecover_raw(msg_hash, v, r, s)
    except Exception:
        return []
    if not address:
        return []
    return list(b"\x00" * 12 + address)


# -- sha256 / ripemd160 / identity ------------------------------------------


def sha256_native(data: List) -> List[int]:
    return list(hashlib.sha256(_concrete_bytes(data)).digest())


def _ripemd160_py(message: bytes) -> bytes:
    # Pure-Python RIPEMD-160 (public domain algorithm constants).
    def rol(value, amount):
        return ((value << amount) | (value >> (32 - amount))) & 0xFFFFFFFF

    h = [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0]
    r1 = [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
          7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
          3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
          1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
          4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13]
    r2 = [5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
          6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
          15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
          8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
          12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11]
    s1 = [11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
          7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
          11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
          11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
          9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6]
    s2 = [8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
          9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
          9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
          15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
          8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11]

    def f(j, x, y, z):
        if j < 16:
            return x ^ y ^ z
        if j < 32:
            return (x & y) | (~x & z)
        if j < 48:
            return (x | ~y) ^ z
        if j < 64:
            return (x & z) | (y & ~z)
        return x ^ (y | ~z)

    def k1(j):
        return [0x00000000, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xA953FD4E][j // 16]

    def k2(j):
        return [0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9, 0x00000000][j // 16]

    padded = message + b"\x80"
    padded += b"\x00" * ((56 - len(padded) % 64) % 64)
    padded += (len(message) * 8).to_bytes(8, "little")
    for block_start in range(0, len(padded), 64):
        block = padded[block_start:block_start + 64]
        x = [int.from_bytes(block[4 * i:4 * i + 4], "little") for i in range(16)]
        a1, b1, c1, d1, e1 = h
        a2, b2, c2, d2, e2 = h
        for j in range(80):
            t = (rol((a1 + f(j, b1, c1, d1) + x[r1[j]] + k1(j)) & 0xFFFFFFFF,
                     s1[j]) + e1) & 0xFFFFFFFF
            a1, e1, d1, c1, b1 = e1, d1, rol(c1, 10), b1, t
            t = (rol((a2 + f(79 - j, b2, c2, d2) + x[r2[j]] + k2(j)) & 0xFFFFFFFF,
                     s2[j]) + e2) & 0xFFFFFFFF
            a2, e2, d2, c2, b2 = e2, d2, rol(c2, 10), b2, t
        t = (h[1] + c1 + d2) & 0xFFFFFFFF
        h = [t,
             (h[2] + d1 + e2) & 0xFFFFFFFF,
             (h[3] + e1 + a2) & 0xFFFFFFFF,
             (h[4] + a1 + b2) & 0xFFFFFFFF,
             (h[0] + b1 + c2) & 0xFFFFFFFF]
    return b"".join(v.to_bytes(4, "little") for v in h)


def ripemd160(data: List) -> List[int]:
    raw = _concrete_bytes(data)
    try:
        digest = hashlib.new("ripemd160", raw).digest()
    except Exception:
        digest = _ripemd160_py(raw)
    return list(b"\x00" * 12 + digest)


def identity(data: List) -> List:
    return list(data)


# -- modexp ------------------------------------------------------------------


def native_modexp(data: List) -> List[int]:
    raw = _concrete_bytes(data)
    raw = raw + b"\x00" * max(0, 96 - len(raw))
    base_len = int.from_bytes(raw[0:32], "big")
    exp_len = int.from_bytes(raw[32:64], "big")
    mod_len = int.from_bytes(raw[64:96], "big")
    if base_len + exp_len + mod_len > 4096:
        raise NativeContractException("modexp input too large")
    body = raw[96:] + b"\x00" * (base_len + exp_len + mod_len)
    base = int.from_bytes(body[0:base_len], "big")
    exponent = int.from_bytes(body[base_len:base_len + exp_len], "big")
    modulus = int.from_bytes(
        body[base_len + exp_len:base_len + exp_len + mod_len], "big"
    )
    if modulus == 0:
        return list(b"\x00" * mod_len)
    result = pow(base, exponent, modulus)
    return list(result.to_bytes(mod_len, "big"))


# -- alt_bn128 ---------------------------------------------------------------

_BN_P = 21888242871839275222246405745257275088696311157297823662689037894645226208583


def _bn_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % _BN_P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1) * pow(2 * y1, _BN_P - 2, _BN_P) % _BN_P
    else:
        lam = (y2 - y1) * pow((x2 - x1) % _BN_P, _BN_P - 2, _BN_P) % _BN_P
    x3 = (lam * lam - x1 - x2) % _BN_P
    y3 = (lam * (x1 - x3) - y1) % _BN_P
    return (x3, y3)


def _bn_point(x: int, y: int):
    if x == 0 and y == 0:
        return None
    if (y * y - x * x * x - 3) % _BN_P != 0:
        raise NativeContractException("point not on alt_bn128")
    return (x, y)


def ec_add(data: List) -> List[int]:
    raw = _concrete_bytes(data)
    raw = raw + b"\x00" * max(0, 128 - len(raw))
    x1, y1, x2, y2 = (int.from_bytes(raw[i:i + 32], "big") for i in range(0, 128, 32))
    result = _bn_add(_bn_point(x1, y1), _bn_point(x2, y2))
    if result is None:
        return list(b"\x00" * 64)
    return list(result[0].to_bytes(32, "big") + result[1].to_bytes(32, "big"))


def ec_mul(data: List) -> List[int]:
    raw = _concrete_bytes(data)
    raw = raw + b"\x00" * max(0, 96 - len(raw))
    x, y, scalar = (int.from_bytes(raw[i:i + 32], "big") for i in range(0, 96, 32))
    point = _bn_point(x, y)
    result = None
    addend = point
    while scalar:
        if scalar & 1:
            result = _bn_add(result, addend)
        addend = _bn_add(addend, addend)
        scalar >>= 1
    if result is None:
        return list(b"\x00" * 64)
    return list(result[0].to_bytes(32, "big") + result[1].to_bytes(32, "big"))


def ec_pairing(data: List) -> List[int]:
    # full pairing check not implemented; treat as unknowable
    raise NativeContractException("alt_bn128 pairing unsupported")


# -- blake2b F ---------------------------------------------------------------

_BLAKE2_SIGMA = [
    [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
    [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
    [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
    [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
    [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
    [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
    [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
    [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
    [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
    [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
]
_BLAKE2_IV = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B,
    0xA54FF53A5F1D36F1, 0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]
_M64 = (1 << 64) - 1


def _blake2_g(v, a, b, c, d, x, y):
    v[a] = (v[a] + v[b] + x) & _M64
    v[d] = _ror64(v[d] ^ v[a], 32)
    v[c] = (v[c] + v[d]) & _M64
    v[b] = _ror64(v[b] ^ v[c], 24)
    v[a] = (v[a] + v[b] + y) & _M64
    v[d] = _ror64(v[d] ^ v[a], 16)
    v[c] = (v[c] + v[d]) & _M64
    v[b] = _ror64(v[b] ^ v[c], 63)


def _ror64(value, amount):
    return ((value >> amount) | (value << (64 - amount))) & _M64


def blake2b_fcompress(data: List) -> List[int]:
    raw = _concrete_bytes(data)
    if len(raw) != 213:
        raise NativeContractException("blake2f input must be 213 bytes")
    rounds = int.from_bytes(raw[0:4], "big")
    h = [int.from_bytes(raw[4 + 8 * i:12 + 8 * i], "little") for i in range(8)]
    m = [int.from_bytes(raw[68 + 8 * i:76 + 8 * i], "little") for i in range(16)]
    t0 = int.from_bytes(raw[196:204], "little")
    t1 = int.from_bytes(raw[204:212], "little")
    final = raw[212]
    if final not in (0, 1):
        raise NativeContractException("invalid blake2f final flag")
    v = h[:] + _BLAKE2_IV[:]
    v[12] ^= t0
    v[13] ^= t1
    if final:
        v[14] ^= _M64
    for round_index in range(rounds):
        sigma = _BLAKE2_SIGMA[round_index % 10]
        _blake2_g(v, 0, 4, 8, 12, m[sigma[0]], m[sigma[1]])
        _blake2_g(v, 1, 5, 9, 13, m[sigma[2]], m[sigma[3]])
        _blake2_g(v, 2, 6, 10, 14, m[sigma[4]], m[sigma[5]])
        _blake2_g(v, 3, 7, 11, 15, m[sigma[6]], m[sigma[7]])
        _blake2_g(v, 0, 5, 10, 15, m[sigma[8]], m[sigma[9]])
        _blake2_g(v, 1, 6, 11, 12, m[sigma[10]], m[sigma[11]])
        _blake2_g(v, 2, 7, 8, 13, m[sigma[12]], m[sigma[13]])
        _blake2_g(v, 3, 4, 9, 14, m[sigma[14]], m[sigma[15]])
    out = bytearray()
    for i in range(8):
        out += (h[i] ^ v[i] ^ v[i + 8]).to_bytes(8, "little")
    return list(out)


PRECOMPILE_FUNCTIONS: List[Callable] = [
    ecrecover,
    sha256_native,
    ripemd160,
    identity,
    native_modexp,
    ec_add,
    ec_mul,
    ec_pairing,
    blake2b_fcompress,
]
PRECOMPILE_COUNT = len(PRECOMPILE_FUNCTIONS)


def native_contracts(address: int, data: List) -> List[int]:
    """Dispatch by precompile address (1-based)."""
    if not (1 <= address <= PRECOMPILE_COUNT):
        raise NativeContractException(f"not a precompile: {address}")
    return PRECOMPILE_FUNCTIONS[address - 1](data)

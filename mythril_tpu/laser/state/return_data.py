"""Return buffer of the last call (reference state/return_data.py:33)."""

from typing import List, Union

from mythril_tpu.smt import BitVec, symbol_factory


class ReturnData:
    def __init__(self, return_data: List[BitVec], return_data_size: Union[BitVec, int]):
        self.return_data = return_data
        if isinstance(return_data_size, int):
            return_data_size = symbol_factory.BitVecVal(return_data_size, 256)
        self.return_data_size = return_data_size

    @property
    def size(self) -> BitVec:
        return self.return_data_size

"""Machine frame: stack / memory / pc / gas
(reference laser/ethereum/state/machine_state.py:263)."""

from typing import List

from mythril_tpu.laser.evm_exceptions import StackOverflowException, StackUnderflowException
from mythril_tpu.laser.state.memory import Memory

STACK_LIMIT = 1024


class MachineStack(list):
    def append(self, element) -> None:
        if len(self) >= STACK_LIMIT:
            raise StackOverflowException(
                f"stack limit {STACK_LIMIT} reached"
            )
        super().append(element)

    def pop(self, index=-1):
        try:
            return super().pop(index)
        except IndexError:
            raise StackUnderflowException("pop from empty stack") from None


class MachineState:
    def __init__(
        self,
        gas_limit: int,
        pc: int = 0,
        stack=None,
        subroutine_stack=None,
        memory: Memory = None,
        depth: int = 0,
        max_gas_used: int = 0,
        min_gas_used: int = 0,
    ):
        self.gas_limit = gas_limit
        self.pc = pc
        self.stack = MachineStack(stack or [])
        self.subroutine_stack = MachineStack(subroutine_stack or [])
        self.memory = memory or Memory()
        self.depth = depth
        self.max_gas_used = max_gas_used
        self.min_gas_used = min_gas_used

    def check_gas(self) -> None:
        from mythril_tpu.laser.evm_exceptions import OutOfGasException

        if self.min_gas_used > self.gas_limit:
            raise OutOfGasException()

    @property
    def memory_size(self) -> int:
        return self.memory.size

    def mem_extend(self, start, size) -> None:
        """Grow memory (concrete bounds only; symbolic bounds left unexpanded)."""
        if isinstance(start, int) and isinstance(size, int):
            self.memory.extend_to(start, size)

    def pop(self, amount: int = 1):
        values = [self.stack.pop() for _ in range(amount)]
        return values[0] if amount == 1 else values

    def clone(self) -> "MachineState":
        dup = MachineState.__new__(MachineState)
        dup.gas_limit = self.gas_limit
        dup.pc = self.pc
        dup.stack = MachineStack(self.stack)
        dup.subroutine_stack = MachineStack(self.subroutine_stack)
        dup.memory = self.memory.clone()
        dup.depth = self.depth
        dup.max_gas_used = self.max_gas_used
        dup.min_gas_used = self.min_gas_used
        return dup

    def __deepcopy__(self, memo):
        return self.clone()

    def as_dict(self):
        return {
            "pc": self.pc,
            "stack": list(self.stack),
            "memory": self.memory,
            "memsize": self.memory_size,
            "gas": self.gas_limit - self.max_gas_used,
        }

"""Machine frame: stack / memory / pc / gas
(reference laser/ethereum/state/machine_state.py:263)."""

from typing import List

from mythril_tpu.laser.evm_exceptions import StackOverflowException, StackUnderflowException
from mythril_tpu.laser.state.memory import Memory

STACK_LIMIT = 1024

# EVM memory-expansion gas (yellow paper appendix G; reference
# laser/ethereum/state/machine_state.py:171-191 via instruction_data.py).
GAS_MEMORY = 3
GAS_MEMORY_QUADRATIC_DENOMINATOR = 512


def _ceil32(value: int) -> int:
    return ((value + 31) // 32) * 32


def memory_expansion_fee(words):
    """Total memory fee for a memory of `words` 32-byte words (yellow
    paper appendix G). Kept polynomial — no branches, no floats — so it
    evaluates identically for python ints here and for batched int32
    arrays inside the vmapped frontier step (laser/frontier/kernel.py
    mirrors mem_extend with this exact formula)."""
    return (words * GAS_MEMORY
            + words * words // GAS_MEMORY_QUADRATIC_DENOMINATOR)


class MachineStack(list):
    def append(self, element) -> None:
        if len(self) >= STACK_LIMIT:
            raise StackOverflowException(
                f"stack limit {STACK_LIMIT} reached"
            )
        super().append(element)

    def pop(self, index=-1):
        try:
            return super().pop(index)
        except IndexError:
            raise StackUnderflowException("pop from empty stack") from None


class MachineState:
    def __init__(
        self,
        gas_limit: int,
        pc: int = 0,
        stack=None,
        subroutine_stack=None,
        memory: Memory = None,
        depth: int = 0,
        max_gas_used: int = 0,
        min_gas_used: int = 0,
    ):
        self.gas_limit = gas_limit
        self.pc = pc
        self.stack = MachineStack(stack or [])
        self.subroutine_stack = MachineStack(subroutine_stack or [])
        self.memory = memory or Memory()
        self.depth = depth
        self.max_gas_used = max_gas_used
        self.min_gas_used = min_gas_used

    def check_gas(self) -> None:
        from mythril_tpu.laser.evm_exceptions import OutOfGasException

        if self.min_gas_used > self.gas_limit:
            raise OutOfGasException()

    @property
    def memory_size(self) -> int:
        return self.memory.size

    def calculate_extension_size(self, start: int, size: int) -> int:
        """Word-aligned growth needed to cover [start, start+size)
        (reference machine_state.py:152-168)."""
        if self.memory_size > start + size:
            return 0
        new_size = _ceil32(start + size) // 32
        old_size = self.memory_size // 32
        return (new_size - old_size) * 32

    def calculate_memory_gas(self, start: int, size: int) -> int:
        """Quadratic memory-expansion fee (reference machine_state.py:171-185)."""
        oldsize = self.memory_size // 32
        newsize = _ceil32(start + size) // 32
        return memory_expansion_fee(newsize) - memory_expansion_fee(oldsize)

    def mem_extend(self, start, size) -> None:
        """Grow memory, charging the expansion fee; symbolic bounds are left
        unexpanded (reference machine_state.py:187-208)."""
        if not isinstance(start, int):
            if getattr(start, "symbolic", True):
                return
            start = start.concrete_value
        if not isinstance(size, int):
            if getattr(size, "symbolic", True):
                return
            size = size.concrete_value
        if size == 0:
            return
        if self.calculate_extension_size(start, size):
            extend_gas = self.calculate_memory_gas(start, size)
            self.min_gas_used += extend_gas
            self.max_gas_used += extend_gas
            self.check_gas()
            self.memory.extend_to(start, size)

    def pop(self, amount: int = 1):
        values = [self.stack.pop() for _ in range(amount)]
        return values[0] if amount == 1 else values

    def clone(self) -> "MachineState":
        dup = MachineState.__new__(MachineState)
        dup.gas_limit = self.gas_limit
        dup.pc = self.pc
        dup.stack = MachineStack(self.stack)
        dup.subroutine_stack = MachineStack(self.subroutine_stack)
        dup.memory = self.memory.clone()
        dup.depth = self.depth
        dup.max_gas_used = self.max_gas_used
        dup.min_gas_used = self.min_gas_used
        return dup

    def __deepcopy__(self, memo):
        return self.clone()

    def as_dict(self):
        return {
            "pc": self.pc,
            "stack": list(self.stack),
            "memory": self.memory,
            "memsize": self.memory_size,
            "gas": self.gas_limit - self.max_gas_used,
        }

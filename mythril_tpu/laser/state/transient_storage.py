"""EIP-1153 transient storage, cleared between user transactions
(reference state/transient_storage.py:70; cleared at svm.py:263-265)."""

from mythril_tpu.smt import BitVec, symbol_factory
from mythril_tpu.smt.array_expr import K


class TransientStorage:
    def __init__(self):
        # (address is part of the key: keccak-free composite keying via
        # one array per account would need dynamic allocation; a single
        # 512-bit-keyed array keeps it functional)
        self._arrays = {}

    def _array_for(self, address: BitVec):
        key = address.concrete_value if not address.symbolic else hash(address.raw)
        if key not in self._arrays:
            self._arrays[key] = K(256, 256, 0)
        return self._arrays[key]

    def get(self, address: BitVec, index: BitVec) -> BitVec:
        return self._array_for(address)[index]

    def set(self, address: BitVec, index: BitVec, value: BitVec) -> None:
        self._array_for(address)[index] = value

    def clear(self) -> None:
        self._arrays.clear()

    def clone(self) -> "TransientStorage":
        dup = TransientStorage.__new__(TransientStorage)
        dup._arrays = {k: v.clone() for k, v in self._arrays.items()}
        return dup

    def __deepcopy__(self, memo):
        return self.clone()

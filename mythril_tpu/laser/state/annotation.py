"""State annotations — the metadata/taint channel used by every detection
module and plugin (reference laser/ethereum/state/annotation.py:74)."""


class StateAnnotation:
    @property
    def persist_to_world_state(self) -> bool:
        """Carried from the tx-final state into the world state."""
        return False

    @property
    def persist_over_calls(self) -> bool:
        """Survives into nested call frames."""
        return False

    @property
    def search_importance(self) -> int:
        """Weight used by beam search (higher = keep)."""
        return 1


class MergeableStateAnnotation(StateAnnotation):
    """Annotations that state merging knows how to combine."""

    def check_merge_annotation(self, other) -> bool:
        raise NotImplementedError

    def merge_annotation(self, other):
        raise NotImplementedError

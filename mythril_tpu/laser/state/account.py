"""Account + Storage (reference laser/ethereum/state/account.py:228).

Storage is a functional SMT array: concrete-create contracts start from
K(0) (all slots zero); on-chain/unknown contracts get a free symbolic array.
`printable_storage` tracks writes for reports. A DynLoader hook lazily pulls
concrete slots for on-chain analysis (reference :43-75)."""

from typing import Dict, Optional

from mythril_tpu.disasm import Disassembly
from mythril_tpu.smt import BitVec, symbol_factory
from mythril_tpu.smt.array_expr import Array, K


class Storage:
    def __init__(self, concrete: bool = False, address: Optional[BitVec] = None,
                 dynamic_loader=None):
        self.concrete = concrete
        self.address = address
        self.dynld = dynamic_loader
        if concrete:
            self._array = K(256, 256, 0)
        else:
            tag = (
                f"Storage{address.concrete_value}"
                if address is not None and not address.symbolic
                else f"Storage{id(self)}"
            )
            self._array = Array(tag, 256, 256)
        self.printable_storage: Dict = {}
        self._loaded_slots = set()

    def __getitem__(self, item: BitVec) -> BitVec:
        if (
            self.dynld is not None
            and self.address is not None
            and not self.address.symbolic
            and not item.symbolic
            and item.concrete_value not in self._loaded_slots
        ):
            self._lazy_load(item.concrete_value)
        return self._array[item]

    def _lazy_load(self, slot: int) -> None:
        self._loaded_slots.add(slot)
        try:
            value = self.dynld.read_storage(
                f"0x{self.address.concrete_value:040x}", slot
            )
        except Exception:
            return
        if value is not None:
            self._array[slot] = int(value, 16) if isinstance(value, str) else value
            self.printable_storage[slot] = self._array[slot]

    def __setitem__(self, key: BitVec, value: BitVec) -> None:
        if isinstance(value, int):
            value = symbol_factory.BitVecVal(value, 256)
        self._array[key] = value
        self.printable_storage[
            key.concrete_value if not key.symbolic else key
        ] = value

    def clone(self) -> "Storage":
        dup = Storage.__new__(Storage)
        dup.concrete = self.concrete
        dup.address = self.address
        dup.dynld = self.dynld
        dup._array = self._array.clone()
        dup.printable_storage = dict(self.printable_storage)
        dup._loaded_slots = set(self._loaded_slots)
        return dup

    def __deepcopy__(self, memo):
        return self.clone()


class Account:
    def __init__(
        self,
        address,
        code: Optional[Disassembly] = None,
        contract_name: Optional[str] = None,
        balances: Optional["Array"] = None,
        concrete_storage: bool = False,
        dynamic_loader=None,
        nonce: int = 0,
    ):
        if isinstance(address, int):
            address = symbol_factory.BitVecVal(address, 256)
        self.address = address
        self.code = code or Disassembly(b"")
        self.contract_name = contract_name or "Unknown"
        self.nonce = nonce
        self.deleted = False
        self.storage = Storage(
            concrete=concrete_storage, address=address, dynamic_loader=dynamic_loader
        )
        # balance reads go through the world-state global balance array
        self._balances = balances

    def set_balance_array(self, balances) -> None:
        self._balances = balances

    @property
    def balance(self):
        """Callable kept for parity with reference account.balance()."""
        return lambda: self._balances[self.address]

    def add_balance(self, value) -> None:
        self._balances[self.address] = self._balances[self.address] + value

    def sub_balance(self, value) -> None:
        self._balances[self.address] = self._balances[self.address] - value

    @property
    def serialised_code(self) -> str:
        from mythril_tpu.disasm.disassembly import _concrete_projection

        return _concrete_projection(self.code.bytecode).hex()

    def clone(self, balances=None) -> "Account":
        dup = Account.__new__(Account)
        dup.address = self.address
        dup.code = self.code  # immutable
        dup.contract_name = self.contract_name
        dup.nonce = self.nonce
        dup.deleted = self.deleted
        dup.storage = self.storage.clone()
        dup._balances = balances if balances is not None else self._balances
        return dup

    def as_dict(self) -> Dict:
        return {
            "nonce": self.nonce,
            "code": self.code,
            "balance": self.balance(),
            "storage": self.storage,
        }

"""WorldState (reference laser/ethereum/state/world_state.py:259).

Holds the account registry, the GLOBAL balance array (one SMT array indexed
by address — the key trick enabling EtherThief/UnexpectedEther predicates),
the per-path constraints, and the transaction sequence."""

from typing import Dict, List, Optional

from mythril_tpu.disasm import Disassembly
from mythril_tpu.laser.state.account import Account
from mythril_tpu.laser.state.constraints import Constraints
from mythril_tpu.smt import symbol_factory
from mythril_tpu.smt.array_expr import Array
from mythril_tpu.utils.keccak import keccak256


class WorldState:
    next_balance_id = 1

    def __init__(self, transaction_sequence=None, annotations=None):
        self._accounts: Dict[int, Account] = {}
        self.balances = Array(f"balance_{WorldState.next_balance_id}", 256, 256)
        WorldState.next_balance_id += 1
        self.starting_balances = self.balances.clone()
        self.constraints = Constraints()
        self.transaction_sequence: List = transaction_sequence or []
        self.annotations: List = list(annotations or [])
        self.node = None  # CFG bookkeeping

    @property
    def accounts(self) -> Dict[int, Account]:
        return self._accounts

    def put_account(self, account: Account) -> None:
        assert not account.address.symbolic
        self._accounts[account.address.concrete_value] = account
        account.set_balance_array(self.balances)

    def create_account(
        self,
        balance=0,
        address: Optional[int] = None,
        concrete_storage: bool = False,
        dynamic_loader=None,
        creator: Optional[int] = None,
        code: Optional[Disassembly] = None,
        nonce: int = 0,
    ) -> Account:
        if address is None:
            address = self._generate_new_address(creator)
        account = Account(
            address,
            code=code,
            balances=self.balances,
            concrete_storage=concrete_storage,
            dynamic_loader=dynamic_loader,
            nonce=nonce,
        )
        if balance:
            account.add_balance(symbol_factory.BitVecVal(balance, 256)
                                if isinstance(balance, int) else balance)
        self.put_account(account)
        return account

    def accounts_exist_or_load(self, address, dynamic_loader=None) -> Account:
        """Fetch the account, lazily creating/loading unknown ones."""
        if isinstance(address, str):
            address = int(address, 16)
        if isinstance(address, int):
            addr_int = address
        elif not address.symbolic:
            addr_int = address.concrete_value
        else:
            # symbolic callee: fresh unconstrained account
            return Account(address, balances=self.balances)
        if addr_int in self._accounts:
            return self._accounts[addr_int]
        code = None
        if dynamic_loader is not None:
            try:
                code_hex = dynamic_loader.dynld(f"0x{addr_int:040x}")
                if code_hex:
                    code = (
                        code_hex
                        if isinstance(code_hex, Disassembly)
                        else Disassembly(code_hex)
                    )
            except Exception:
                code = None
        return self.create_account(
            address=addr_int, dynamic_loader=dynamic_loader, code=code
        )

    def _generate_new_address(self, creator: Optional[int]) -> int:
        """CREATE address: last 20 bytes of keccak(rlp([creator, nonce]))
        (reference world_state.py:239-251)."""
        if creator is None:
            # fresh pseudo-address for detached account creation
            seed = len(self._accounts).to_bytes(8, "big")
            return int.from_bytes(keccak256(seed)[12:], "big")
        nonce = self._accounts[creator].nonce if creator in self._accounts else 0
        rlp = _rlp_encode_pair(creator.to_bytes(20, "big"), nonce)
        return int.from_bytes(keccak256(rlp)[12:], "big")

    def __getitem__(self, item) -> Account:
        if hasattr(item, "symbolic"):
            assert not item.symbolic
            item = item.concrete_value
        return self._accounts[item]

    def clone(self) -> "WorldState":
        dup = WorldState.__new__(WorldState)
        dup.balances = self.balances.clone()
        dup.starting_balances = self.starting_balances.clone()
        dup._accounts = {}
        for addr, account in self._accounts.items():
            dup._accounts[addr] = account.clone(balances=dup.balances)
        dup.constraints = self.constraints.copy()
        dup.transaction_sequence = list(self.transaction_sequence)
        # per-path mutable metadata (traces, dependency maps) must not leak
        # between forks: prefer this codebase's clone() convention, fall
        # back to __copy__ (same form as GlobalState.copy)
        import copy as _copy

        dup.annotations = [
            a.clone() if hasattr(a, "clone") else _copy.copy(a)
            for a in self.annotations
        ]
        dup.node = self.node
        return dup

    __copy__ = clone

    def __deepcopy__(self, memo) -> "WorldState":
        return self.clone()

    def annotate(self, annotation) -> None:
        self.annotations.append(annotation)

    def get_annotations(self, annotation_type):
        return [a for a in self.annotations if isinstance(a, annotation_type)]


def _rlp_encode_pair(address_bytes: bytes, nonce: int) -> bytes:
    """Minimal RLP for [20-byte-address, small-int-nonce]."""

    def enc_bytes(b: bytes) -> bytes:
        if len(b) == 1 and b[0] < 0x80:
            return b
        assert len(b) < 56
        return bytes([0x80 + len(b)]) + b

    def enc_int(n: int) -> bytes:
        if n == 0:
            return b"\x80"
        raw = n.to_bytes((n.bit_length() + 7) // 8, "big")
        return enc_bytes(raw)

    payload = enc_bytes(address_bytes) + enc_int(nonce)
    assert len(payload) < 56
    return bytes([0xC0 + len(payload)]) + payload

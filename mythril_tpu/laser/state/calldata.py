"""Calldata models (reference laser/ethereum/state/calldata.py:326).

ConcreteCalldata — fixed byte list; SymbolicCalldata — unbounded SMT array
with a fresh symbolic size; BasicConcreteCalldata — plain list access."""

from typing import Any, List, Union

from mythril_tpu.smt import BitVec, Concat, Extract, If, symbol_factory
from mythril_tpu.smt.array_expr import Array, K


def _index_bv(item) -> BitVec:
    if isinstance(item, int):
        return symbol_factory.BitVecVal(item, 256)
    return item


class BaseCalldata:
    def __init__(self, tx_id: str):
        self.tx_id = tx_id

    @property
    def calldatasize(self) -> BitVec:
        raise NotImplementedError

    @property
    def size(self) -> Union[BitVec, int]:
        raise NotImplementedError

    def get_word_at(self, offset) -> BitVec:
        """Big-endian 32-byte word; out-of-range bytes read as zero."""
        parts = [self[_index_bv(offset) + i] for i in range(32)]
        return Concat(parts)

    def __getitem__(self, item) -> Any:
        if isinstance(item, slice):
            start = item.start or 0
            stop = item.stop
            assert stop is not None and (item.step or 1) == 1
            current = _index_bv(start)
            out = []
            length = stop - start if isinstance(stop, int) and isinstance(start, int) else None
            assert length is not None, "symbolic slice bounds use concretize()"
            for i in range(length):
                out.append(self._load(_index_bv(start + i)))
            return out
        return self._load(_index_bv(item))

    def _load(self, index: BitVec) -> BitVec:
        raise NotImplementedError

    def concrete(self, model) -> List[int]:
        """Concrete byte list under a model."""
        raise NotImplementedError


def _byte_bv(value) -> BitVec:
    """Coerce an int or BitVec(8) entry to BitVec(8) (inner-call calldata is
    read out of symbolic memory, so entries may already be expressions)."""
    if isinstance(value, BitVec):
        return value
    return symbol_factory.BitVecVal(value, 8)


class ConcreteCalldata(BaseCalldata):
    def __init__(self, tx_id: str, calldata: List):
        super().__init__(tx_id)
        self._calldata = list(calldata)
        # array form so symbolic indexing works
        self._array = K(256, 8, 0)
        for i, byte in enumerate(self._calldata):
            self._array[i] = _byte_bv(byte)

    @property
    def calldatasize(self) -> BitVec:
        return symbol_factory.BitVecVal(len(self._calldata), 256)

    @property
    def size(self) -> int:
        return len(self._calldata)

    def _load(self, index: BitVec) -> BitVec:
        if not index.symbolic:
            i = index.concrete_value
            if i < len(self._calldata):
                return _byte_bv(self._calldata[i])
            return symbol_factory.BitVecVal(0, 8)
        return self._array[index]

    def concrete(self, model) -> List[int]:
        return [
            byte.concrete_value if isinstance(byte, BitVec) and not byte.symbolic
            else (model.eval_int(byte) if isinstance(byte, BitVec) else byte)
            for byte in self._calldata
        ]


class BasicConcreteCalldata(BaseCalldata):
    """Fixed-length byte list without the array form; entries may be
    symbolic BitVec(8) (inner-call data read from memory)."""

    def __init__(self, tx_id: str, calldata: List):
        super().__init__(tx_id)
        self._calldata = list(calldata)

    @property
    def calldatasize(self) -> BitVec:
        return symbol_factory.BitVecVal(len(self._calldata), 256)

    @property
    def size(self) -> int:
        return len(self._calldata)

    def _load(self, index: BitVec) -> BitVec:
        if not index.symbolic:
            i = index.concrete_value
            if i < len(self._calldata):
                return _byte_bv(self._calldata[i])
            return symbol_factory.BitVecVal(0, 8)
        result = symbol_factory.BitVecVal(0, 8)
        for i, byte in enumerate(self._calldata):
            result = If(index == i, _byte_bv(byte), result)
        return result

    def concrete(self, model) -> List[int]:
        return [
            byte.concrete_value if isinstance(byte, BitVec) and not byte.symbolic
            else (model.eval_int(byte) if isinstance(byte, BitVec) else byte)
            for byte in self._calldata
        ]


class SymbolicCalldata(BaseCalldata):
    def __init__(self, tx_id: str):
        super().__init__(tx_id)
        self._size = symbol_factory.BitVecSym(f"{tx_id}_calldatasize", 256)
        self._array = Array(f"{tx_id}_calldata", 256, 8)

    @property
    def calldatasize(self) -> BitVec:
        return self._size

    @property
    def size(self) -> BitVec:
        return self._size

    def _load(self, index: BitVec) -> BitVec:
        # bytes past calldatasize read as zero
        return If(
            index < self._size,
            self._array[index],
            symbol_factory.BitVecVal(0, 8),
        )

    def concrete(self, model) -> List[int]:
        concrete_size = model.eval_int(self._size)
        concrete_size = min(concrete_size, 5000)  # matches exploit size cap
        return [
            model.eval_int(self._load(symbol_factory.BitVecVal(i, 256)))
            for i in range(concrete_size)
        ]

"""Execution environment of the active call frame
(reference laser/ethereum/state/environment.py:82)."""

from typing import Optional

from mythril_tpu.laser.state.calldata import BaseCalldata
from mythril_tpu.smt import BitVec, symbol_factory


class Environment:
    def __init__(
        self,
        active_account,
        sender: BitVec,
        calldata: BaseCalldata,
        gasprice: BitVec,
        callvalue: BitVec,
        origin: BitVec,
        code=None,
        static: bool = False,
        basefee: Optional[BitVec] = None,
    ):
        self.active_account = active_account
        self.sender = sender
        self.calldata = calldata
        self.gasprice = gasprice
        self.callvalue = callvalue
        self.origin = origin
        self.code = code if code is not None else active_account.code
        self.static = static
        self.basefee = basefee if basefee is not None else symbol_factory.BitVecSym(
            "basefee", 256
        )
        self.chainid = symbol_factory.BitVecVal(1, 256)
        self.block_number = symbol_factory.BitVecSym("block_number", 256)
        self.active_function_name = ""

    @property
    def address(self) -> BitVec:
        return self.active_account.address

    def clone(self, world_state=None) -> "Environment":
        """Rebind active_account into the given cloned world state."""
        dup = Environment.__new__(Environment)
        dup.__dict__.update(self.__dict__)
        if world_state is not None:
            addr = self.active_account.address
            if not addr.symbolic and addr.concrete_value in world_state.accounts:
                dup.active_account = world_state.accounts[addr.concrete_value]
        return dup

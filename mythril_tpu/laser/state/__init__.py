"""Symbolic machine/world state objects carried by every explored path."""

"""Symbolic byte-addressed EVM memory.

Reference (laser/ethereum/state/memory.py) keeps a dict of byte cells; here
memory is a functional SMT array (256-bit index -> 8-bit cells). The term
layer's eager read-over-write elimination makes concrete-index access fold
away, and symbolic-index access is handled by the solver's store-chain
unwinding — one mechanism instead of two."""

from typing import List, Union

from mythril_tpu.smt import BitVec, Concat, Extract, If, symbol_factory
from mythril_tpu.smt.array_expr import K
from mythril_tpu.smt import terms as _terms

APPROX_ITR = 100  # cap for symbolic-length copy loops (reference memory.py:30)


def _to_index(index) -> BitVec:
    if isinstance(index, int):
        return symbol_factory.BitVecVal(index, 256)
    return index


class Memory:
    def __init__(self):
        self._memory = K(256, 8, 0)
        self._msize = 0
        # concrete shadow of the store chain, maintained incrementally so
        # the vmapped frontier (laser/frontier/) can densify the touched
        # window without walking the SMT array byte by byte:
        #   _shadow     concrete index -> concrete byte value (int 0-255)
        #   _sym_bytes  concrete indices last written with a SYMBOLIC value
        #   _poisoned   a write at a SYMBOLIC index happened — the store
        #               chain may alias any concrete index, so no dense
        #               view of this memory is trustworthy for reads
        self._shadow = {}
        self._sym_bytes = set()
        self._poisoned = False
        # last dense_window result, invalidated by any write: batch
        # admission and encode both densify the same untouched memory,
        # and the window build (bytearray + full shadow scan) is the
        # expensive part of the probe
        self._dense_cache = None

    @property
    def size(self) -> int:
        return self._msize

    def extend(self, size: int) -> None:
        self._msize += size

    def extend_to(self, offset: int, length: int) -> None:
        """Word-aligned growth covering [offset, offset+length)."""
        if length == 0:
            return
        needed = ((offset + length + 31) // 32) * 32
        if needed > self._msize:
            self._msize = needed

    def __getitem__(self, item) -> Union[BitVec, List[BitVec]]:
        if isinstance(item, slice):
            start = item.start or 0
            stop = item.stop
            step = item.step or 1
            assert step == 1 and stop is not None, "memory slices must be contiguous"
            return [self.get_byte(i) for i in range(start, stop)]
        return self.get_byte(item)

    def __setitem__(self, key, value) -> None:
        if isinstance(key, slice):
            start = key.start or 0
            assert (key.step or 1) == 1
            for offset, byte in enumerate(value):
                self.write_byte(start + offset, byte)
        else:
            self.write_byte(key, value)

    def get_byte(self, index) -> BitVec:
        return self._memory[_to_index(index)]

    def write_byte(self, index, value) -> None:
        if isinstance(value, int):
            value = symbol_factory.BitVecVal(value, 8)
        elif value.size != 8:
            value = Extract(7, 0, value)
        # shadow maintenance: the term layer folds concrete arithmetic
        # eagerly, so raw.is_const is a sufficient concreteness test here
        # (no simplify call on the hot write path)
        if isinstance(index, int):
            concrete_index = index
        elif index.raw.is_const:
            concrete_index = index.raw.value
        else:
            concrete_index = None
        if concrete_index is None:
            self._poisoned = True
        elif value.raw.is_const and not value.annotations:
            self._shadow[concrete_index] = value.raw.value
            self._sym_bytes.discard(concrete_index)
        else:
            self._shadow.pop(concrete_index, None)
            self._sym_bytes.add(concrete_index)
        self._dense_cache = None
        self._memory[_to_index(index)] = value

    def get_word_at(self, index) -> BitVec:
        """Big-endian 32-byte word starting at `index`."""
        if isinstance(index, int):
            parts = [self.get_byte(index + i) for i in range(32)]
        else:
            parts = [
                self.get_byte(index + symbol_factory.BitVecVal(i, 256))
                for i in range(32)
            ]
        return Concat(parts)

    def write_word_at(self, index, value) -> None:
        if isinstance(value, int):
            value = symbol_factory.BitVecVal(value, 256)
        elif isinstance(value, bool):
            value = If(
                value,
                symbol_factory.BitVecVal(1, 256),
                symbol_factory.BitVecVal(0, 256),
            )
        if value.size < 256:
            from mythril_tpu.smt import ZeroExt

            value = ZeroExt(256 - value.size, value)
        for i in range(32):
            byte = Extract(255 - 8 * i, 248 - 8 * i, value)
            if isinstance(index, int):
                self.write_byte(index + i, byte)
            else:
                self.write_byte(index + symbol_factory.BitVecVal(i, 256), byte)

    def copy_from_bytes(self, offset, data: bytes) -> None:
        for i, byte in enumerate(data):
            self.write_byte(offset + i, byte)

    def read_bytes_concrete(self, offset: int, length: int) -> List[BitVec]:
        return [self.get_byte(offset + i) for i in range(length)]

    def dense_window(self, window: int):
        """Concrete bytes [0, window) as a bytearray, or None when a dense
        read view would be unsound: a symbolic-index write may alias any
        byte, a symbolic byte value sits inside the window, or the array
        carries taint annotations a dense read would fail to propagate.
        Unwritten bytes are 0 — identical to the K(256, 8, 0) base array."""
        cached = self._dense_cache
        if cached is not None and cached[0] == window:
            return cached[1]
        if self._poisoned or self._memory.annotations:
            result = None
        elif self._sym_bytes and any(i < window for i in self._sym_bytes):
            result = None
        else:
            result = bytearray(window)
            for index, value in self._shadow.items():
                if index < window:
                    result[index] = value
        self._dense_cache = (window, result)
        return result

    def clone(self) -> "Memory":
        dup = Memory.__new__(Memory)
        dup._memory = self._memory.clone()
        dup._msize = self._msize
        dup._shadow = dict(self._shadow)
        dup._sym_bytes = set(self._sym_bytes)
        dup._poisoned = self._poisoned
        dup._dense_cache = None
        return dup

    def __deepcopy__(self, memo) -> "Memory":
        return self.clone()

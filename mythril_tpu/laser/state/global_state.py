"""GlobalState: one node of the exploration frontier
(reference laser/ethereum/state/global_state.py:185).

Bundles (world_state, environment, machine_state, tx stack, annotations).
Forks clone via `clone()` — explicit structural copy instead of the
reference's deepcopy (svm hot-spot, instructions.py:1629)."""

from typing import Iterable, List, Optional, Tuple

from mythril_tpu.laser.state.environment import Environment
from mythril_tpu.laser.state.machine_state import MachineState
from mythril_tpu.laser.state.transient_storage import TransientStorage
from mythril_tpu.laser.state.world_state import WorldState
from mythril_tpu.smt import BitVec, symbol_factory


class GlobalState:
    def __init__(
        self,
        world_state: WorldState,
        environment: Environment,
        node=None,
        machine_state: Optional[MachineState] = None,
        transaction_stack: Optional[List[Tuple]] = None,
        last_return_data=None,
        annotations: Optional[Iterable] = None,
        transient_storage: Optional[TransientStorage] = None,
    ):
        self.world_state = world_state
        self.environment = environment
        self.node = node
        self.mstate = machine_state or MachineState(gas_limit=8_000_000)
        self.transaction_stack: List[Tuple] = transaction_stack or []
        self.last_return_data = last_return_data
        self.annotations: List = list(annotations or [])
        self.transient_storage = transient_storage or TransientStorage()
        # (start_pc, end_pc) span of the vmapped-frontier run this state
        # last exited mid-batch (laser/frontier/stepper.py): while its pc
        # is inside the span it replays on the per-state interpreter
        # instead of re-entering a batch at every interior pc of the same
        # run. Deliberately NOT copied by clone() — forks leave the span.
        self._frontier_skip_span = None

    @property
    def accounts(self):
        return self.world_state.accounts

    @property
    def current_transaction(self):
        try:
            return self.transaction_stack[-1][0]
        except IndexError:
            return None

    @property
    def instruction(self) -> dict:
        instr = self.environment.code.instruction_at(self.mstate.pc)
        if instr is None:
            # pc past end of code -> implicit STOP handled by caller
            return None
        return instr

    def get_current_instruction(self):
        return self.instruction

    def new_bitvec(self, name: str, size: int = 256, annotations=None) -> BitVec:
        """Fresh symbol namespaced by transaction id (reference :147)."""
        tx = self.current_transaction
        tx_id = tx.id if tx is not None else "pre"
        return symbol_factory.BitVecSym(f"{tx_id}_{name}", size, annotations)

    def clone(self) -> "GlobalState":
        import copy as _copy

        world_state = self.world_state.clone()
        environment = self.environment.clone(world_state)
        dup = GlobalState(
            world_state,
            environment,
            node=self.node,
            machine_state=self.mstate.clone(),
            transaction_stack=list(self.transaction_stack),
            last_return_data=self.last_return_data,
            # annotations are mutable per-path metadata (loop traces, taint):
            # each fork needs its own copies
            annotations=[
                a.clone() if hasattr(a, "clone") else _copy.deepcopy(a)
                for a in self.annotations
            ],
            transient_storage=self.transient_storage.clone(),
        )
        return dup

    def __copy__(self):
        return self.clone()

    def __deepcopy__(self, memo):
        return self.clone()

    # annotation API (reference global_state.py + annotation.py)
    def annotate(self, annotation) -> None:
        self.annotations.append(annotation)
        if getattr(annotation, "persist_to_world_state", False):
            self.world_state.annotate(annotation)

    def get_annotations(self, annotation_type):
        return [a for a in self.annotations if isinstance(a, annotation_type)]

"""Per-path constraint set (reference laser/ethereum/state/constraints.py).

A list of Bool expressions; keccak axioms are injected at solve time via
get_all_constraints (reference :77,132-133) rather than stored per state."""

from typing import Iterable, List, Optional

from mythril_tpu.smt import Bool, simplify
from mythril_tpu.smt.solver.frontend import UnsatError, SolverTimeOutException


class Constraints(list):
    def __init__(self, constraint_list: Optional[Iterable[Bool]] = None):
        super().__init__(constraint_list or [])
        self._is_possible: Optional[bool] = None

    def append(self, constraint: Bool) -> None:
        if isinstance(constraint, bool):
            constraint = Bool.value(constraint)
        super().append(simplify(constraint))
        self._is_possible = None

    def pop(self, index: int = -1) -> Bool:
        self._is_possible = None
        return super().pop(index)

    @property
    def is_possible(self) -> bool:
        """SAT probe with caching; unknown counts as possible (can't prune)."""
        if self._is_possible is not None:
            return self._is_possible
        from mythril_tpu.support.model import get_model

        try:
            get_model(self.get_all_constraints())
            self._is_possible = True
        except UnsatError:
            self._is_possible = False
        except SolverTimeOutException:
            self._is_possible = True
        return self._is_possible

    def get_all_constraints(self) -> List[Bool]:
        from mythril_tpu.laser.function_managers import keccak_function_manager

        return list(self) + keccak_function_manager.create_conditions()

    as_list = get_all_constraints

    def copy(self) -> "Constraints":
        dup = Constraints(self)
        dup._is_possible = self._is_possible
        return dup

    __copy__ = copy

    def __deepcopy__(self, memo) -> "Constraints":
        return self.copy()

    def __add__(self, other) -> "Constraints":
        dup = self.copy()
        for constraint in other:
            dup.append(constraint)
        return dup

    def __iadd__(self, other) -> "Constraints":
        for constraint in other:
            self.append(constraint)
        return self

    def __hash__(self):  # hashable for the model cache
        return hash(tuple(hash(c) for c in self))

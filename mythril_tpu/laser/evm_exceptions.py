"""VM exception family (reference laser/ethereum/evm_exceptions.py:43)."""


class VmException(Exception):
    pass


class StackUnderflowException(IndexError, VmException):
    pass


class StackOverflowException(VmException):
    pass


class InvalidJumpDestination(VmException):
    pass


class InvalidInstruction(VmException):
    pass


class OutOfGasException(VmException):
    pass


class WriteProtection(VmException):
    """State modification inside STATICCALL."""

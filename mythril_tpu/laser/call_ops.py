"""CALL/CALLCODE/DELEGATECALL/STATICCALL/CREATE/CREATE2 semantics
(reference laser/ethereum/instructions.py:1719-2470 + call.py).

Call frames are pushed by raising TransactionStartSignal; the engine pops
them on TransactionEndSignal and resumes the caller via the return context
stored on the transaction (svm._end_message_call in the reference re-runs
the call op in "post" mode; here the context travels with the signal)."""

from typing import List, Optional, Tuple

from mythril_tpu.disasm import Disassembly
from mythril_tpu.laser import natives
from mythril_tpu.laser.cheat_code import is_cheat_address
from mythril_tpu.laser.evm_exceptions import VmException, WriteProtection
from mythril_tpu.laser.instructions import (
    advance,
    bv,
    concrete_or_none,
    concretize,
    op,
)
from mythril_tpu.laser.state.calldata import BasicConcreteCalldata, BaseCalldata
from mythril_tpu.laser.state.global_state import GlobalState
from mythril_tpu.laser.state.return_data import ReturnData
from mythril_tpu.laser.transaction.models import (
    ContractCreationTransaction,
    MessageCallTransaction,
    TransactionStartSignal,
)
from mythril_tpu.smt import UGE, symbol_factory

GAS_CALLSTIPEND = 2300
SYMBOLIC_CALLDATA_SIZE = 320  # bound for unconstrained inner calldata


class CallReturnContext:
    """Where to resume + where to write return data in the caller frame."""

    def __init__(self, global_state: GlobalState, memory_out_offset,
                 memory_out_size, op_name: str):
        self.global_state = global_state
        self.memory_out_offset = memory_out_offset
        self.memory_out_size = memory_out_size
        self.op_name = op_name


def _read_calldata_from_memory(global_state, mem_offset, mem_size):
    size_c = concrete_or_none(mem_size)
    if size_c is None:
        size_c = min(
            concretize(global_state, mem_size, "call_data_size"),
            SYMBOLIC_CALLDATA_SIZE,
        )
    offset_c = concrete_or_none(mem_offset)
    if offset_c is None and size_c:
        offset_c = concretize(global_state, mem_offset, "call_data_offset")
    data = [
        global_state.mstate.memory.get_byte(offset_c + i) for i in range(size_c)
    ]
    return data, size_c


def _call_family(global_state: GlobalState, op_name: str):
    stack = global_state.mstate.stack
    gas = stack.pop()
    to = stack.pop()
    if op_name in ("CALL", "CALLCODE"):
        value = stack.pop()
    else:
        value = bv(0)
    in_offset = stack.pop()
    in_size = stack.pop()
    out_offset = stack.pop()
    out_size = stack.pop()

    if op_name == "CALL" and global_state.environment.static:
        value_c = concrete_or_none(value)
        if value_c is None or value_c != 0:
            raise WriteProtection("CALL with value inside STATICCALL")

    environment = global_state.environment
    world_state = global_state.world_state
    to_concrete = concrete_or_none(to)

    # inner-call depth limit (reference call_depth_limiter plugin, default 3):
    # beyond the limit the callee is not executed, result is unconstrained
    from mythril_tpu.support.args import args as _args

    inner_depth = sum(
        1 for _tx, snapshot in global_state.transaction_stack if snapshot is not None
    )
    if inner_depth >= _args.call_depth_limit:
        global_state.last_return_data = _symbolic_return_data(global_state)
        stack.append(
            global_state.new_bitvec(f"retval_depthcap_{global_state.mstate.pc}", 256)
        )
        return advance(global_state)

    # cheat-code address: stub success
    if to_concrete is not None and is_cheat_address(to_concrete):
        global_state.last_return_data = ReturnData([], 0)
        stack.append(bv(1))
        return advance(global_state)

    call_data_bytes, _size = _read_calldata_from_memory(
        global_state, in_offset, in_size
    )

    # precompiles execute natively
    if to_concrete is not None and 1 <= to_concrete <= natives.PRECOMPILE_COUNT:
        return _native_call(
            global_state, to_concrete, call_data_bytes, out_offset, out_size
        )

    # only use accounts we actually know about — materializing an empty
    # account here would make a later EXTCODESIZE concretely 0, where the
    # reference models unknown-address code as symbolic absent on-chain
    # data (reference world_state.py accounts_exist_or_load raises without
    # a dynamic loader and callers go symbolic)
    callee_account = None
    if to_concrete is not None:
        callee_account = world_state.accounts.get(to_concrete)

    if (
        callee_account is None
        or len(callee_account.code.bytecode) == 0
    ):
        # unknown or codeless target: value transfer + symbolic result
        if op_name in ("CALL", "CALLCODE"):
            _apply_value_transfer(global_state, environment.address, to, value)
        return_value = global_state.new_bitvec(
            f"retval_{global_state.mstate.pc}", 256
        )
        global_state.last_return_data = _symbolic_return_data(global_state)
        stack.append(return_value)
        # both outcomes possible; keep it symbolic (modules constrain it)
        return advance(global_state)

    # real inner transaction
    caller = environment.address
    callee_address = to
    if op_name == "DELEGATECALL":
        tx = MessageCallTransaction(
            world_state=world_state,
            callee_account=environment.active_account,
            caller=environment.sender,
            call_data=BasicConcreteCalldata("delegate", []),
            origin=environment.origin,
            code=callee_account.code,
            call_value=environment.callvalue,
            static=environment.static,
        )
    elif op_name == "CALLCODE":
        tx = MessageCallTransaction(
            world_state=world_state,
            callee_account=environment.active_account,
            caller=caller,
            origin=environment.origin,
            code=callee_account.code,
            call_value=value,
            static=environment.static,
        )
    else:
        tx = MessageCallTransaction(
            world_state=world_state,
            callee_account=callee_account,
            caller=caller,
            origin=environment.origin,
            code=callee_account.code,
            call_value=value,
            static=environment.static or op_name == "STATICCALL",
        )
    tx.call_data = BasicConcreteCalldata(tx.id, call_data_bytes)
    tx.return_context = CallReturnContext(
        global_state, out_offset, out_size, op_name
    )
    raise TransactionStartSignal(tx, op_name, global_state)


def _apply_value_transfer(global_state, sender, receiver, value):
    world_state = global_state.world_state
    world_state.constraints.append(UGE(world_state.balances[sender], value))
    world_state.balances[sender] = world_state.balances[sender] - value
    world_state.balances[receiver] = world_state.balances[receiver] + value


def _symbolic_return_data(global_state) -> ReturnData:
    size_sym = global_state.new_bitvec(
        f"returndatasize_{global_state.mstate.pc}", 256
    )
    data = [
        global_state.new_bitvec(f"returndata_{global_state.mstate.pc}_{i}", 8)
        for i in range(32)
    ]
    return ReturnData(data, size_sym)


def _native_call(global_state, precompile_address, call_data_bytes,
                 out_offset, out_size):
    stack = global_state.mstate.stack
    try:
        output = natives.native_contracts(precompile_address, call_data_bytes)
    except natives.NativeContractException:
        # symbolic input: unknown result
        global_state.last_return_data = _symbolic_return_data(global_state)
        stack.append(
            global_state.new_bitvec(f"native_{precompile_address}", 256)
        )
        return advance(global_state)
    _write_return_data(global_state, output, out_offset, out_size)
    global_state.last_return_data = ReturnData(list(output), len(output))
    stack.append(bv(1))
    return advance(global_state)


def _write_return_data(global_state, data, out_offset, out_size):
    offset_c = concrete_or_none(out_offset)
    size_c = concrete_or_none(out_size)
    if offset_c is None or size_c is None:
        return
    length = min(size_c, len(data))
    global_state.mstate.mem_extend(offset_c, length)
    for i in range(length):
        global_state.mstate.memory.write_byte(offset_c + i, data[i])


@op("CALL")
def call_(global_state):
    return _call_family(global_state, "CALL")


@op("CALLCODE")
def callcode_(global_state):
    return _call_family(global_state, "CALLCODE")


@op("DELEGATECALL")
def delegatecall_(global_state):
    return _call_family(global_state, "DELEGATECALL")


@op("STATICCALL")
def staticcall_(global_state):
    return _call_family(global_state, "STATICCALL")


# ---------------------------------------------------------------------------
# CREATE / CREATE2


def _create_family(global_state: GlobalState, op_name: str):
    stack = global_state.mstate.stack
    value = stack.pop()
    offset = stack.pop()
    length = stack.pop()
    salt = stack.pop() if op_name == "CREATE2" else None

    code_bytes_sym, size_c = _read_calldata_from_memory(
        global_state, offset, length
    )
    code_bytes = bytearray()
    for byte in code_bytes_sym:
        byte_c = concrete_or_none(byte)
        if byte_c is None:
            # symbolic init code: cannot execute; push symbolic address
            stack.append(global_state.new_bitvec("create_addr", 256))
            return advance(global_state)
        code_bytes.append(byte_c)

    world_state = global_state.world_state
    creator = global_state.environment.address
    creator_int = (
        creator.concrete_value if not creator.symbolic else None
    )
    if op_name == "CREATE2" and salt is not None:
        salt_c = concrete_or_none(salt)
        if salt_c is not None and creator_int is not None:
            from mythril_tpu.utils.keccak import keccak256

            digest = keccak256(
                b"\xff"
                + creator_int.to_bytes(20, "big")
                + salt_c.to_bytes(32, "big")
                + keccak256(bytes(code_bytes))
            )
            new_address = int.from_bytes(digest[12:], "big")
        else:
            stack.append(global_state.new_bitvec("create2_addr", 256))
            return advance(global_state)
    else:
        new_address = None  # rlp-derived inside create_account

    account = world_state.create_account(
        address=new_address,
        concrete_storage=True,
        creator=creator_int,
    )
    if creator_int is not None and creator_int in world_state.accounts:
        world_state.accounts[creator_int].nonce += 1

    tx = ContractCreationTransaction(
        world_state=world_state,
        callee_account=account,
        caller=creator,
        origin=global_state.environment.origin,
        code=Disassembly(bytes(code_bytes)),
        call_value=value,
        prev_world_state=None,
    )
    tx.return_context = CallReturnContext(global_state, None, None, op_name)
    raise TransactionStartSignal(tx, op_name, global_state)


@op("CREATE")
def create_(global_state):
    return _create_family(global_state, "CREATE")


@op("CREATE2")
def create2_(global_state):
    return _create_family(global_state, "CREATE2")

"""Search strategies — the scheduler of the worklist engine
(reference laser/ethereum/strategy/__init__.py; consumed at svm.py:336).

A strategy is an iterator over GlobalStates, drawing from (and owning the
ordering policy of) the engine's work_list. Composable by wrapping."""

from typing import List

from mythril_tpu.laser.state.global_state import GlobalState


class BasicSearchStrategy:
    def __init__(self, work_list: List[GlobalState], max_depth: int, **kwargs):
        self.work_list = work_list
        self.max_depth = max_depth
        # static per-function effect hints (a preanalysis.CodeSummary with
        # `function_effects`: selector -> FunctionEffects), or None when
        # pre-analysis is disabled/unavailable. Strategies MAY use this to
        # deprioritize provably effect-free cones; the engine's fork
        # pruning consumes the same summary to skip feasibility solves
        # for inert states (svm.exec). Dropping states based on it would
        # be unsound — hints only reorder or skip redundant solver work.
        self.effect_hints = kwargs.get("effect_hints")

    def __iter__(self):
        return self

    def get_strategic_global_state(self) -> GlobalState:
        raise NotImplementedError

    def run_check(self) -> bool:
        """Gate consulted by stochastic pruning (reference svm.py:351)."""
        return True

    def __next__(self) -> GlobalState:
        # exhaustion is signalled by get_strategic_global_state (empty pop
        # raises IndexError), NOT by checking work_list here — strategies
        # like DelayConstraintStrategy refill the worklist from a pending
        # pool exactly when it runs dry
        while True:
            try:
                state = self.get_strategic_global_state()
            except IndexError:
                raise StopIteration
            if state.mstate.depth < self.max_depth:
                return state
            # depth-capped states are dropped (their world state was already
            # harvested if a tx ended)


class CriterionSearchStrategy(BasicSearchStrategy):
    """Stop once a criterion is satisfied (concolic search)."""

    def __init__(self, work_list, max_depth, **kwargs):
        super().__init__(work_list, max_depth, **kwargs)
        self._satisfied = False

    def set_criterion_satisfied(self):
        self._satisfied = True

    def __next__(self):
        if self._satisfied:
            raise StopIteration
        return super().__next__()

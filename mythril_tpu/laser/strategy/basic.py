"""DFS/BFS/random strategies (reference laser/ethereum/strategy/basic.py)."""

import random

from mythril_tpu.laser.strategy import BasicSearchStrategy


class DepthFirstSearchStrategy(BasicSearchStrategy):
    def get_strategic_global_state(self):
        return self.work_list.pop()


class BreadthFirstSearchStrategy(BasicSearchStrategy):
    def get_strategic_global_state(self):
        return self.work_list.pop(0)


class ReturnRandomNaivelyStrategy(BasicSearchStrategy):
    def get_strategic_global_state(self):
        if not self.work_list:
            raise IndexError  # exhausted (see BasicSearchStrategy.__next__)
        index = random.randrange(len(self.work_list))
        return self.work_list.pop(index)


class ReturnWeightedRandomStrategy(BasicSearchStrategy):
    """1/(depth+1)-weighted choice (reference basic.py:86)."""

    def get_strategic_global_state(self):
        weights = [
            1 / (state.mstate.depth + 1) for state in self.work_list
        ]
        total = sum(weights)
        pick = random.uniform(0, total)
        acc = 0.0
        for i, weight in enumerate(weights):
            acc += weight
            if acc >= pick:
                return self.work_list.pop(i)
        return self.work_list.pop()

"""Concolic search strategy (reference laser/ethereum/strategy/concolic.py).

Follows a previously recorded concrete (pc, tx_id) trace; when it reaches a
JUMPI whose address the caller asked to flip, it negates the last path
constraint and concretizes a transaction sequence that drives execution
down the other side. States that wander off the trace are dropped.

Unlike the reference, pc here is the byte address itself (our Disassembly
indexes instructions by address), so no instruction_list indirection is
needed when matching flip addresses.
"""

import logging
from copy import copy
from typing import Any, Dict, List, Optional, Tuple

from mythril_tpu.laser.state.annotation import StateAnnotation
from mythril_tpu.laser.state.constraints import Constraints
from mythril_tpu.laser.strategy import CriterionSearchStrategy
from mythril_tpu.smt import Not
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)


class TraceAnnotation(StateAnnotation):
    """Per-world-state trace of executed (pc, tx_id) pairs."""

    def __init__(self, trace: Optional[List[Tuple[int, int]]] = None):
        self.trace = trace or []

    @property
    def persist_over_calls(self) -> bool:
        return True

    def __copy__(self):
        return TraceAnnotation(copy(self.trace))


class ConcolicStrategy(CriterionSearchStrategy):
    def __init__(self, work_list, max_depth,
                 trace: List[List[Tuple[int, int]]],
                 flip_branch_addresses: List[str], **kwargs):
        super().__init__(work_list, max_depth, **kwargs)
        self.trace: List[Tuple[int, int]] = [
            pair for tx_trace in trace for pair in tx_trace
        ]
        self.last_tx_count = len(trace)
        self.flip_branch_addresses = flip_branch_addresses
        self.results: Dict[str, Any] = {}

    def _annotation(self, state) -> TraceAnnotation:
        for annotation in state.world_state.get_annotations(TraceAnnotation):
            return annotation
        annotation = TraceAnnotation()
        state.world_state.annotate(annotation)
        return annotation

    def get_strategic_global_state(self):
        while self.work_list:
            state = self.work_list.pop()
            annotation = self._annotation(state)
            annotation.trace.append(
                (state.mstate.pc, state.current_transaction.id)
            )
            on_trace = annotation.trace == self.trace[: len(annotation.trace)]
            if len(annotation.trace) < 2:
                if not on_trace:
                    continue
                return state
            prev_pc = annotation.trace[-2][0]
            addr = str(prev_pc)
            seq_id = len(state.world_state.transaction_sequence)
            if (on_trace and seq_id == self.last_tx_count
                    and addr in self.flip_branch_addresses
                    and addr not in self.results):
                prev_instr = state.environment.code.instruction_at(prev_pc)
                if prev_instr is None or prev_instr.opcode != "JUMPI":
                    log.error("branch %s is not a JUMPI, skipping", addr)
                    continue
                self._flip(state, addr)
            elif not on_trace:
                continue
            if len(self.results) == len(self.flip_branch_addresses):
                self.set_criterion_satisfied()
            return state
        raise StopIteration

    def _flip(self, state, addr: str) -> None:
        from mythril_tpu.analysis.solver import get_transaction_sequence

        constraints = Constraints(state.world_state.constraints[:-1])
        constraints.append(Not(state.world_state.constraints[-1]))
        try:
            self.results[addr] = get_transaction_sequence(state, constraints)
        except (UnsatError, SolverTimeOutException):
            self.results[addr] = None

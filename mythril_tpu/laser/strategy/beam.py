"""Beam search (reference laser/ethereum/strategy/beam.py:6): keep only
the `beam_width` states with the highest summed annotation
search_importance (PotentialIssuesAnnotation contributes 10 per recorded
issue, analysis/potential_issues.py)."""

from mythril_tpu.laser.strategy import BasicSearchStrategy


class BeamSearch(BasicSearchStrategy):
    def __init__(self, work_list, max_depth, beam_width: int = 8, **kwargs):
        super().__init__(work_list, max_depth, **kwargs)
        self.beam_width = beam_width

    @staticmethod
    def beam_priority(state) -> int:
        return sum(a.search_importance for a in state.annotations)

    def sort_and_eliminate_states(self) -> None:
        self.work_list.sort(key=self.beam_priority, reverse=True)
        del self.work_list[self.beam_width:]

    def get_strategic_global_state(self):
        self.sort_and_eliminate_states()
        if self.work_list:
            return self.work_list.pop(0)
        raise StopIteration  # beam truncation emptied the worklist

"""BoundedLoopsStrategy — skip states that keep repeating a jump-trace
suffix (reference laser/ethereum/strategy/extensions/bounded_loops.py)."""

import logging
from typing import List

from mythril_tpu.laser.state.annotation import StateAnnotation

log = logging.getLogger(__name__)


class JumpdestCountAnnotation(StateAnnotation):
    """Per-state trace of executed JUMPDEST addresses (reference :13)."""

    def __init__(self):
        self._jumpdest_count = {}
        self.trace: List[int] = []

    def clone(self):
        dup = JumpdestCountAnnotation()
        dup.trace = list(self.trace)
        return dup


def _count_key_repetitions(trace: List[int]) -> int:
    """Detect a repeating suffix and count its repetitions
    (reference :84-102: find i<j with trace[i:j] repeating backwards)."""
    size = len(trace)
    if size < 2:
        return 0
    # find the shortest period p of the trace suffix
    for period in range(1, min(size // 2, 32) + 1):
        if trace[-period:] != trace[-2 * period:-period]:
            continue
        # count how many times this period repeats
        count = 2
        idx = size - 2 * period
        while idx - period >= 0 and trace[idx - period:idx] == trace[-period:]:
            count += 1
            idx -= period
        return count
    return 0


class BoundedLoopsStrategy:
    """Wraps another strategy; filters out states past the loop bound."""

    def __init__(self, super_strategy, loop_bound: int = 3, **kwargs):
        self.super_strategy = super_strategy
        self.bound = loop_bound
        self.work_list = super_strategy.work_list
        self.max_depth = super_strategy.max_depth

    def __iter__(self):
        return self

    def run_check(self):
        return self.super_strategy.run_check()

    def vet_state(self, state) -> bool:
        """Per-yield loop accounting: append the state's JUMPDEST trace
        and decide whether it stays under the loop bound. Shared by
        __next__ AND the vmapped frontier's sibling collection
        (laser/frontier/stepper.py) — states pulled into a batch bypass
        __next__, and skipping the accounting there would let loops run
        unbounded through back-to-back batched runs."""
        annotations = [
            a for a in state.annotations
            if isinstance(a, JumpdestCountAnnotation)
        ]
        if not annotations:
            annotation = JumpdestCountAnnotation()
            state.annotate(annotation)
        else:
            annotation = annotations[0]
        instruction = state.instruction
        if instruction is not None and instruction.opcode == "JUMPDEST":
            annotation.trace.append(state.mstate.pc)
            from mythril_tpu.laser.transaction.models import (
                ContractCreationTransaction,
            )

            bound = self.bound
            if isinstance(
                state.current_transaction, ContractCreationTransaction
            ):
                # loops in constructors run real iterations (reference
                # :136-139 raises the bound for creation txs)
                bound = max(bound, 128)
            if _count_key_repetitions(annotation.trace) > bound:
                log.debug(
                    "loop bound %d exceeded at pc %d",
                    bound, state.mstate.pc,
                )
                return False
        return True

    def __next__(self):
        while True:
            state = self.super_strategy.__next__()
            if self.vet_state(state):
                return state

"""DelayConstraintStrategy — "pending" scheduling
(reference laser/ethereum/strategy/constraint_strategy.py:10).

Skips per-fork satisfiability checks during exploration: states whose
reachability was not yet proven are parked in `pending_worklist`; when the
ready worklist drains, pending states are solved (models feeding the
global quick-sat cache) and revived if reachable. Trades solver latency
off the hot path for batched/delayed checks — on the device backend the
drained pending batch is exactly the sibling-path bundle the batched
solver wants.
"""

import logging

from mythril_tpu.laser.strategy import BasicSearchStrategy
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError
from mythril_tpu.support.model import get_model, model_cache

log = logging.getLogger(__name__)


class DelayConstraintStrategy(BasicSearchStrategy):
    def __init__(self, work_list, max_depth, **kwargs):
        super().__init__(work_list, max_depth, **kwargs)
        self.pending_worklist = []

    def run_check(self) -> bool:
        """Forks are accepted unchecked; reachability is decided lazily."""
        return False

    def get_strategic_global_state(self):
        while not self.work_list:
            if not self.pending_worklist:
                raise StopIteration
            state = self.pending_worklist.pop(0)
            try:
                model = get_model(
                    state.world_state.constraints.get_all_constraints())
            except UnsatError:
                continue
            except SolverTimeOutException:
                model = None  # unknown counts as possible: cannot prune
            if model is not None:
                model_cache.put(model)
            self.work_list.append(state)
        return self.work_list.pop(0)

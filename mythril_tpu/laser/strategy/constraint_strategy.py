"""DelayConstraintStrategy — "pending" scheduling
(reference laser/ethereum/strategy/constraint_strategy.py:10).

Skips per-fork satisfiability checks during exploration: states whose
reachability was not yet proven are parked in `pending_worklist`; when the
ready worklist drains, pending states are solved (models feeding the
global quick-sat cache) and revived if reachable. Trades solver latency
off the hot path for batched/delayed checks — on the device backend the
drained pending batch is exactly the sibling-path bundle the batched
solver wants.
"""

import logging

from mythril_tpu.laser.strategy import BasicSearchStrategy
from mythril_tpu.service.scheduler import get_scheduler

log = logging.getLogger(__name__)

# sibling states drained per batched solve — the device fan-out unit
DRAIN_BATCH = 32


class DelayConstraintStrategy(BasicSearchStrategy):
    def __init__(self, work_list, max_depth, **kwargs):
        super().__init__(work_list, max_depth, **kwargs)
        self.pending_worklist = []

    def run_check(self) -> bool:
        """Forks are accepted unchecked; reachability is decided lazily."""
        return False

    def get_strategic_global_state(self):
        while not self.work_list:
            if not self.pending_worklist:
                raise StopIteration
            # drain a sibling-path bundle through ONE batched solve: with
            # --solver-backend=tpu every eligible query rides a single
            # run_round_batch device call (support/model.get_models_batch)
            batch = self.pending_worklist[:DRAIN_BATCH]
            del self.pending_worklist[:DRAIN_BATCH]
            # batched-fork sibling pairs that landed in the same drain
            # slice (laser/frontier dense.PendingFork tags both sides):
            # the fork lane packs each pair's shared cone once and rides
            # both sides on one ragged stream with the fork literals as
            # extra assumption roots — verdict handling is identical
            by_token = {}
            for index, state in enumerate(batch):
                token = getattr(state, "_fork_pair_token", None)
                if token is not None:
                    by_token.setdefault(id(token), []).append(index)
                    state._fork_pair_token = None  # drained once
            pairs = [tuple(indices) for indices in by_token.values()
                     if len(indices) == 2]
            # engine-path pruning verdicts: wrongly pruning costs coverage,
            # not a false "safe" verdict — no UNSAT crosscheck (explicit;
            # matches get_model's non-detection default). The drained
            # bundle rides the coalescing scheduler: one window flush per
            # drain (service/scheduler.py)
            constraint_sets = [
                s.world_state.constraints.get_all_constraints()
                for s in batch
            ]
            if pairs:
                outcomes = get_scheduler().solve_fork_batch(
                    constraint_sets, pairs, crosscheck=False)
            else:
                outcomes = get_scheduler().solve_batch(
                    constraint_sets, crosscheck=False)
            fork_sides = {index for pair in pairs for index in pair}
            for index, (state, (status, _model)) in enumerate(
                    zip(batch, outcomes)):
                if status == "unsat":
                    if index in fork_sides:
                        # a batched-fork side died on a solver-confirmed
                        # (host CDCL) verdict — the fork lane's prune
                        from mythril_tpu.smt.solver.statistics import (
                            SolverStatistics,
                        )

                        SolverStatistics().add_fork_pruned()
                    continue  # proven unreachable: pruned
                # sat (model already fed to the quick-sat cache by
                # get_models_batch) or unknown (cannot prune): revive
                self.work_list.append(state)
        return self.work_list.pop(0)

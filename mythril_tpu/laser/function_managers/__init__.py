from mythril_tpu.laser.function_managers.keccak import (  # noqa: F401
    keccak_function_manager,
)
from mythril_tpu.laser.function_managers.exponent import (  # noqa: F401
    exponent_function_manager,
)

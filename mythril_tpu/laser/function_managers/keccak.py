"""Symbolic keccak-256 modeling (reference
mythril/laser/ethereum/function_managers/keccak_function_manager.py).

Concrete inputs hash natively. A symbolic input of bit-width n flows through
the uninterpreted function keccak256_n; axioms injected at solve time
(via Constraints.get_all_constraints) give each width a disjoint output
interval, congruence with every concretely-hashed value, an inverse function
(injectivity), and result % 64 == 0 — mirroring the reference's trick that
keeps symbolic storage slots for mappings distinct and solvable. Exploit
concretization later rewrites placeholder hashes to real digests
(analysis/solver.py in the reference)."""

from typing import Dict, List, Tuple

from mythril_tpu.smt import And, BitVec, Bool, Function, Or, symbol_factory
from mythril_tpu.utils.keccak import keccak256

TOTAL_PARTS = 10 ** 40
PART = (2 ** 256 - 1) // TOTAL_PARTS
INTERVAL_DIFFERENCE = 10 ** 30


class KeccakFunctionManager:
    def __init__(self):
        self.store_function: Dict[int, Tuple[Function, Function]] = {}
        self.interval_hook_for_size: Dict[int, int] = {}
        # (size) -> list of symbolic input BitVecs seen
        self.symbolic_inputs: Dict[int, List[BitVec]] = {}
        # concretely hashed pairs keyed by (size, value) to avoid relying
        # on BitVec.__eq__ (which returns a Bool expression, not a bool)
        self.concrete_hashes: Dict[Tuple[int, int], Tuple[BitVec, BitVec]] = {}
        self.hash_matcher = "fffffff"  # marker prefix (reference :33)
        self._index_counter = TOTAL_PARTS - 34534

    def reset(self):
        self.__init__()

    def get_function(self, length: int) -> Tuple[Function, Function]:
        try:
            return self.store_function[length]
        except KeyError:
            func = Function(f"keccak256_{length}", [length], 256)
            inverse = Function(f"keccak256_{length}-1", [256], length)
            self.store_function[length] = (func, inverse)
            self.symbolic_inputs[length] = []
            return func, inverse

    @staticmethod
    def get_empty_keccak_hash() -> BitVec:
        return symbol_factory.BitVecVal(
            int.from_bytes(keccak256(b""), "big"), 256
        )

    def create_keccak(self, data: BitVec) -> BitVec:
        length = data.size
        func, _ = self.get_function(length)
        if not data.symbolic:
            concrete_hash = self.find_concrete_keccak(data)
            self.concrete_hashes[(length, data.concrete_value)] = (data, concrete_hash)
            return concrete_hash
        if all(data.raw is not seen.raw for seen in self.symbolic_inputs[length]):
            self.symbolic_inputs[length].append(data)
        return func(data)

    @staticmethod
    def find_concrete_keccak(data: BitVec) -> BitVec:
        return symbol_factory.BitVecVal(
            int.from_bytes(
                keccak256(data.concrete_value.to_bytes(data.size // 8, "big")),
                "big",
            ),
            256,
        )

    def _interval_constraint(self, hashed: BitVec, length: int) -> Bool:
        lower = self._interval_start_for_size(length)
        upper = lower + INTERVAL_DIFFERENCE - 64
        lower_bv = symbol_factory.BitVecVal(lower, 256)
        upper_bv = symbol_factory.BitVecVal(upper, 256)
        cond = And(
            hashed >= lower_bv,
            hashed <= upper_bv,
            (hashed % 64) == symbol_factory.BitVecVal(0, 256),
        )
        # hash may also equal any known concrete digest of the same width
        for (size, _), (_, concrete_hash) in self.concrete_hashes.items():
            if size != length:
                continue
            cond = Or(cond, hashed == concrete_hash)
        return cond

    def _interval_start_for_size(self, length: int) -> int:
        if length not in self.interval_hook_for_size:
            self.interval_hook_for_size[length] = self._index_counter
            self._index_counter -= INTERVAL_DIFFERENCE // PART + 1
        return self.interval_hook_for_size[length] * PART

    def create_conditions(self) -> List[Bool]:
        """Axioms for every symbolic application; appended at solve time."""
        conditions: List[Bool] = []
        for length, inputs in self.symbolic_inputs.items():
            func, inverse = self.store_function[length]
            for data in inputs:
                hashed = func(data)
                conditions.append(inverse(hashed) == data)
                conditions.append(self._interval_constraint(hashed, length))
        for (size, _), (data, concrete_hash) in self.concrete_hashes.items():
            func, inverse = self.get_function(size)
            conditions.append(func(data) == concrete_hash)
            conditions.append(inverse(concrete_hash) == data)
        return conditions


keccak_function_manager = KeccakFunctionManager()

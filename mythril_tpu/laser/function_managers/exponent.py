"""Symbolic EXP modeling (reference
mythril/laser/ethereum/function_managers/exponent_function_manager.py:71).

Concrete base+exponent folds natively. Otherwise EXP becomes an
uninterpreted function exp(base, exponent) with interpolation constraints
for small concrete bases (2, 10, 256) tying sampled powers down."""

from typing import List, Tuple

from mythril_tpu.smt import And, BitVec, Bool, Function, symbol_factory


class ExponentFunctionManager:
    def __init__(self):
        self.exponentiation = Function("exponentiation", [256, 256], 256)
        self.concrete_constraints: List[Bool] = []

    def reset(self):
        self.__init__()

    def create_condition(self, base: BitVec, exponent: BitVec) -> Tuple[BitVec, Bool]:
        """Returns (power_expr, side_constraint)."""
        if not base.symbolic and not exponent.symbolic:
            value = pow(base.concrete_value, exponent.concrete_value, 2 ** 256)
            return symbol_factory.BitVecVal(value, 256), Bool.value(True)
        if not base.symbolic and base.concrete_value > 1 and (
            base.concrete_value & (base.concrete_value - 1)
        ) == 0:
            # power-of-two base: (2^k)^e == 1 << (k*e) exactly, including
            # the wrap to 0 once k*e >= 256 — guard only against the k*e
            # multiply itself wrapping. Solc emits exp(0x100, shift) for
            # packed-storage access; keeping this a shift instead of an
            # uninterpreted function lets div/mod by it reduce to shifts
            # instead of a ~400k-gate restoring divider.
            k = base.concrete_value.bit_length() - 1
            one = symbol_factory.BitVecVal(1, 256)
            from mythril_tpu.smt import If as _If, ULE

            # guard folded INTO the shift amount (shl saturates to 0 at
            # >= 256) so the result stays a pure `1 << s` term that
            # div/mod-by-power-of-two rewrites can see through
            amount = _If(
                ULE(exponent, symbol_factory.BitVecVal(256, 256)),
                exponent * symbol_factory.BitVecVal(k, 256),
                symbol_factory.BitVecVal(256, 256),
            )
            return one << amount, Bool.value(True)
        power = self.exponentiation(base, exponent)
        if not base.symbolic and base.concrete_value in (2, 10, 256):
            base_value = base.concrete_value
            constraints = []
            exponent_bits = 256 if base_value == 2 else (77 if base_value == 10 else 32)
            for sample in range(0, exponent_bits, max(1, exponent_bits // 16)):
                constraints.append(
                    Bool.value(True)
                    if sample == 0
                    else (exponent == sample)
                    == (power == pow(base_value, sample, 2 ** 256))
                )
            condition = And(*constraints) if constraints else Bool.value(True)
            return power, condition
        return power, Bool.value(True)


exponent_function_manager = ExponentFunctionManager()

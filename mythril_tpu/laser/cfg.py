"""CFG nodes/edges for graph + statespace outputs
(reference laser/ethereum/cfg.py:122)."""

from enum import Enum
from typing import List


class JumpType(Enum):
    CONDITIONAL = 1
    UNCONDITIONAL = 2
    CALL = 3
    RETURN = 4
    Transaction = 5


class NodeFlags:
    FUNC_ENTRY = 1
    CALL_RETURN = 2


_next_uid = [0]


class Node:
    def __init__(self, contract_name: str, start_addr: int = 0,
                 constraints=None, function_name: str = "unknown"):
        self.contract_name = contract_name
        self.start_addr = start_addr
        self.constraints = constraints if constraints is not None else []
        self.function_name = function_name
        self.flags = 0
        self.states: List = []
        _next_uid[0] += 1
        self.uid = _next_uid[0]

    def get_dict(self):
        return {
            "contract_name": self.contract_name,
            "start_addr": self.start_addr,
            "function_name": self.function_name,
        }


class Edge:
    def __init__(self, node_from: int, node_to: int,
                 edge_type: JumpType = JumpType.UNCONDITIONAL, condition=None):
        self.node_from = node_from
        self.node_to = node_to
        self.type = edge_type
        self.condition = condition

    def as_dict(self):
        return {"from": self.node_from, "to": self.node_to}

"""LaserEVM — the worklist symbolic-execution engine
(reference mythril/laser/ethereum/svm.py:812).

Holds open world states between transactions, a strategy-ordered worklist of
GlobalStates within a transaction, per-opcode pre/post hook tables for
detection modules, named laser-hook channels for plugins, and the CFG.

Frame discipline (differs from the reference mechanically, same semantics):
states are mutated in place under single ownership; the caller state is
SNAPSHOTTED when an inner transaction starts, so revert restores it exactly
(the reference gets this by copying every instruction — svm.py:459-579)."""

import logging
import random
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

from mythril_tpu.laser import instructions
from mythril_tpu.laser.cfg import Edge, JumpType, Node, NodeFlags
from mythril_tpu.observe.tracer import traced
from mythril_tpu.laser.evm_exceptions import VmException
from mythril_tpu.laser.plugin.signals import PluginSkipState, PluginSkipWorldState
from mythril_tpu.laser.state.global_state import GlobalState
from mythril_tpu.laser.state.world_state import WorldState
from mythril_tpu.laser.strategy.basic import BreadthFirstSearchStrategy
from mythril_tpu.laser.transaction.models import (
    ContractCreationTransaction,
    TransactionEndSignal,
    TransactionStartSignal,
)
from mythril_tpu.support.args import args
from mythril_tpu.support.time_handler import time_handler

log = logging.getLogger(__name__)

LASER_HOOK_CHANNELS = (
    "start_sym_exec",
    "stop_sym_exec",
    "start_sym_trans",
    "stop_sym_trans",
    "start_exec",
    "stop_exec",
    "start_execute_transactions",
    "stop_execute_transactions",
    "add_world_state",
    "execute_state",
    "transaction_start",
    "transaction_end",
)


class SVMError(Exception):
    pass


class LaserEVM:
    def __init__(
        self,
        dynamic_loader=None,
        max_depth: int = 128,
        execution_timeout: Optional[int] = 3600,
        create_timeout: Optional[int] = 30,
        strategy=BreadthFirstSearchStrategy,
        transaction_count: int = 2,
        requires_statespace: bool = True,
        iprof=None,
        use_reachability_check: bool = True,
        beam_width: Optional[int] = None,
        preanalysis=None,
        vmap_frontier: bool = False,
    ):
        self.open_states: List[WorldState] = []
        self.work_list: List[GlobalState] = []
        self.dynamic_loader = dynamic_loader
        self.max_depth = max_depth
        self.execution_timeout = execution_timeout or 0
        self.create_timeout = create_timeout or 0
        self.transaction_count = transaction_count
        self.use_reachability_check = use_reachability_check
        self.requires_statespace = requires_statespace
        self.iprof = iprof

        # static pre-analysis summary of the analyzed contract (a
        # preanalysis.CodeSummary, or None when disabled/unavailable).
        # Handed to the search strategy as `effect_hints` (per-function
        # effect summaries) and gates the fork-prune query-skip below —
        # direct engine users (concolic, vmtests) never set it, so their
        # behavior is untouched.
        self.preanalysis = preanalysis

        # vmapped frontier (laser/frontier/): batched straight-line
        # stepping over sibling states. Opt-in (SymExecWrapper sets it
        # for analysis runs); the stepper is built lazily on first exec
        # so every hook registration is visible to its eligibility gates
        self.vmap_frontier = vmap_frontier
        self._frontier = None

        strategy_kwargs = {}
        if beam_width is not None:
            strategy_kwargs["beam_width"] = beam_width
        if preanalysis is not None:
            strategy_kwargs["effect_hints"] = preanalysis
        self.strategy = strategy(self.work_list, max_depth, **strategy_kwargs)

        # statespace
        self.nodes: Dict[int, Node] = {}
        self.edges: List[Edge] = []

        # metrics
        self.total_states = 0
        self.executed_transactions = False

        # hooks
        self._hooks: Dict[str, List[Callable]] = defaultdict(list)  # named channels
        self.pre_hooks: Dict[str, List[Callable]] = defaultdict(list)
        self.post_hooks: Dict[str, List[Callable]] = defaultdict(list)
        self.instr_pre_hook: Dict[str, List[Callable]] = defaultdict(list)
        self.instr_post_hook: Dict[str, List[Callable]] = defaultdict(list)

        self.time: Optional[float] = None
        self._start_time: Optional[float] = None
        # optional selector-ranking provider (laser/tx_prioritiser.py)
        self.tx_prioritiser = None

    # -- hook registration ---------------------------------------------------

    def register_laser_hooks(self, hook_type: str, hook: Callable):
        if hook_type not in LASER_HOOK_CHANNELS:
            raise ValueError(f"unknown hook channel {hook_type}")
        self._hooks[hook_type].append(hook)

    def register_hooks(self, hook_type: str, hook_dict: Dict[str, List[Callable]]):
        """Detection-module opcode hooks: hook_type 'pre' or 'post'."""
        table = self.pre_hooks if hook_type == "pre" else self.post_hooks
        for op_name, hooks in hook_dict.items():
            table[op_name].extend(hooks)

    def register_instr_hooks(self, hook_type: str, opcode: str, hook: Callable):
        """Plugin per-instruction hooks; empty opcode = all opcodes."""
        table = self.instr_pre_hook if hook_type == "pre" else self.instr_post_hook
        if opcode:
            table[opcode].append(hook)
        else:
            from mythril_tpu.support.opcodes import BY_NAME

            for name in BY_NAME:
                table[name].append(hook)

    def extend_strategy(self, extension, **kwargs):
        self.strategy = extension(self.strategy, **kwargs)

    def _fire(self, channel: str, *fire_args):
        for hook in self._hooks[channel]:
            hook(*fire_args)

    # -- top-level drivers ---------------------------------------------------

    def sym_exec(
        self,
        world_state: Optional[WorldState] = None,
        target_address: Optional[int] = None,
        creation_code: Optional[str] = None,
        contract_name: Optional[str] = None,
    ):
        """Creation-mode (creation_code) or existing-contract analysis."""
        from mythril_tpu.laser.transaction.symbolic import (
            execute_contract_creation,
            execute_message_call,
        )
        from mythril_tpu.smt import symbol_factory

        time_handler.start_execution(self.execution_timeout)
        self._fire("start_sym_exec")
        self._start_time = time.monotonic()

        if creation_code is not None:
            log.info("starting contract creation transaction")
            created_account = execute_contract_creation(
                self, creation_code, contract_name, world_state=world_state
            )
            if not self.open_states:
                log.warning(
                    "no contract was created during the creation transaction"
                )
            self.execute_transactions(created_account.address)
        elif target_address is not None:
            address = (
                symbol_factory.BitVecVal(target_address, 256)
                if isinstance(target_address, int)
                else target_address
            )
            if world_state is not None:
                self.open_states = [world_state]
            self.execute_transactions(address)

        self.time = time.monotonic() - (self._start_time or time.monotonic())
        self._fire("stop_sym_exec")

    def execute_transactions(self, address):
        """The message-call transaction loop (reference svm.py:252-309)."""
        from mythril_tpu.laser.transaction.symbolic import execute_message_call

        pinned_sequences = self._parse_transaction_sequences()
        if pinned_sequences is None and getattr(self, "tx_prioritiser", None):
            # non-ordered exploration: the prioritizer pins the selector
            # order per tx (reference svm.py:241-250 via rf_prioritiser)
            pinned_sequences = self.tx_prioritiser.predict_sequences(
                self.transaction_count)
        self._fire("start_execute_transactions")
        self.executed_transactions = True
        for i in range(self.transaction_count):
            if len(self.open_states) == 0:
                break
            # reachability prune of open states (reference :266-286); the
            # pending strategy probes the model cache before full solves
            # (reference constraint_strategy.py "delayed solving")
            if self.use_reachability_check and i > 0:
                from mythril_tpu.service.scheduler import get_scheduler

                before = len(self.open_states)
                # every open state's reachability query rides the
                # coalescing scheduler: one window flush -> one batched
                # get_models_batch -> level-bucketed router dispatches
                # (with MYTHRIL_TPU_COALESCE_MS=0 this degrades to the
                # direct batched call). Engine-path reachability verdicts
                # (no UNSAT crosscheck: a wrong prune costs coverage, not
                # a false "safe")
                outcomes = get_scheduler().solve_batch(
                    [ws.constraints.get_all_constraints()
                     for ws in self.open_states],
                    crosscheck=False,
                )
                self.open_states = [
                    ws for ws, (status, _model) in zip(self.open_states, outcomes)
                    if status != "unsat"
                ]
                log.info(
                    "tx %d: %d/%d open states reachable",
                    i + 1, len(self.open_states), before,
                )
            log.info(
                "starting message call transaction %d, open states: %d",
                i + 1, len(self.open_states),
            )
            self._fire("start_sym_trans")
            func_hashes = (
                pinned_sequences[i]
                if pinned_sequences and i < len(pinned_sequences)
                else None
            )
            execute_message_call(self, address, func_hashes=func_hashes)
            self._fire("stop_sym_trans")
        self._fire("stop_execute_transactions")

    @staticmethod
    def _parse_transaction_sequences():
        """--transaction-sequences '[[0xa9059cbb],[-1]]' -> per-tx selector
        lists (reference symbolic.py:74-100); -1 means the fallback."""
        import ast

        raw = args.transaction_sequences
        if not raw:
            return None
        parsed = ast.literal_eval(raw) if isinstance(raw, str) else raw
        sequences = []
        for tx_entry in parsed:
            hashes = []
            for selector in tx_entry:
                if selector == -1:
                    hashes.append(-1)
                else:
                    hashes.append(int(selector).to_bytes(4, "big"))
            sequences.append(hashes)
        return sequences

    # -- the hot loop --------------------------------------------------------

    @traced("laser.exec", cat="laser")
    def exec(self, create: bool = False, track_gas: bool = False):
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        self._fire("start_exec")
        # states that produced no successors — the ended/leaf states the
        # VMTests harness asserts gas ranges on (reference svm.py:362-363)
        final_states: List[GlobalState] = []
        start = time.monotonic()
        stats = SolverStatistics()
        if self.vmap_frontier and self._frontier is None:
            from mythril_tpu.laser.frontier import FrontierStepper

            self._frontier = FrontierStepper(self)
        # interleaved-corpus yield point (service/interleave.py): under
        # the round-robin corpus driver the baton rotates between
        # contracts every quantum of exec iterations; one global load +
        # None check when no coordinator is live
        from mythril_tpu.service.interleave import tick as interleave_tick

        for global_state in self.strategy:
            interleave_tick()
            if create and self.create_timeout:
                if time.monotonic() - start > self.create_timeout:
                    log.info("create timeout reached")
                    break
            if not create and self.execution_timeout:
                # time_handler covers the analyzer path; the local clock
                # covers direct engine use (concolic/tests) where
                # start_execution was never called
                if (
                    time_handler.time_remaining() <= 0
                    or time.monotonic() - start > self.execution_timeout
                ):
                    log.info("execution timeout reached")
                    break
            step_start = time.monotonic()
            solver_before = stats.solver_time
            try:
                # batched frontier step first: a straight-line run over
                # every eligible sibling as one device step. op_code None
                # keeps manage_cfg out for straight-line runs; a batched
                # FORK returns op_code "JUMPI" so its successors get the
                # same conditional-edge nodes the per-state handler's
                # states get (feasibility pruning already happened inside
                # the stepper's fork epilogue — one coalesced bundle); a
                # batched HALT returns "RETURN"/"STOP" so frame
                # successors get RETURN nodes (the transaction end
                # already ran through _end_transaction inside the halt
                # epilogue); and a fork whose cohorts chained through
                # their next run (cross-fork re-batching) comes back as
                # op_code None — the stepper ran manage_cfg for the
                # fork's own successors before chaining
                batched = (
                    self._frontier.try_step(global_state)
                    if self._frontier is not None else None
                )
                if batched is not None:
                    new_states = batched
                    op_code = getattr(batched, "op_code", None)
                else:
                    new_states, op_code = self.execute_state(global_state)
            except NotImplementedError:
                log.debug("encountered unimplemented instruction")
                continue
            finally:
                # solver seconds spent INSIDE handlers (concretization,
                # tx-end confirmations) are already attributed to
                # solver_time — subtract them so interp_wall isolates the
                # stepping machinery the frontier targets
                stats.add_interp_seconds(
                    max(0.0, (time.monotonic() - step_start)
                        - (stats.solver_time - solver_before)))

            # stochastic reachability pruning on forks (reference :351-358):
            # with probability pruning_factor, drop fork sides whose path
            # constraints are unsat. Auto: always prune on long-budget runs,
            # never on short ones (reference mythril_analyzer.py:78-82).
            # op_code None = a batched frontier step: its multiple states
            # are SIBLINGS of one straight-line run, not fork sides — no
            # constraint changed, so feasibility solves (or pending-list
            # parking) here would be pure waste. A batched FORK
            # (op_code "JUMPI" with batched set) already pruned and
            # parked inside the stepper — re-solving here would double
            # every fork's feasibility traffic
            if batched is None and op_code is not None \
                    and len(new_states) > 1:
                pruning_factor = args.pruning_factor
                if pruning_factor is None:
                    pruning_factor = 1.0 if self.execution_timeout > 300 else 0.0
                if (
                    pruning_factor > 0.0
                    and self.strategy.run_check()
                    and random.random() < pruning_factor
                ):
                    # ALL fork sides of this exec iteration are submitted
                    # to the coalescing scheduler and demanded together:
                    # one window flush, one device fan-out under
                    # --solver-backend=tpu, instead of serial is_possible
                    from mythril_tpu.service.scheduler import get_scheduler

                    # static effect hints (preanalysis): fork sides whose
                    # remaining cone is provably inert skip the
                    # feasibility solve and are KEPT unchecked — always
                    # findings-sound (issues are solver-confirmed; an
                    # unsat survivor can confirm nothing) and proven
                    # traffic-free (no detector hooks, no effects in the
                    # cone; the next open-state reachability gate still
                    # filters it). Counted as queries_avoided.
                    check_states = new_states
                    if self.preanalysis is not None:
                        from mythril_tpu import preanalysis as pre_mod
                        from mythril_tpu.smt.solver.statistics import (
                            SolverStatistics,
                        )

                        check_states = [
                            s for s in new_states
                            if not pre_mod.prune_check_skippable(s)
                        ]
                        skipped = len(new_states) - len(check_states)
                        if skipped:
                            SolverStatistics().add_queries_avoided(skipped)
                    # engine-path fork pruning: crosscheck off, as above
                    outcomes = get_scheduler().solve_batch(
                        [s.world_state.constraints.get_all_constraints()
                         for s in check_states],
                        crosscheck=False,
                    )
                    pruned = {
                        id(s) for s, (status, _model)
                        in zip(check_states, outcomes) if status == "unsat"
                    }
                    new_states = [
                        s for s in new_states if id(s) not in pruned
                    ]
                elif not self.strategy.run_check():
                    # delayed-solving strategy: forks failing the quick
                    # model-cache probe are parked in pending_worklist and
                    # batch-solved when the ready worklist drains
                    # (strategy/constraint_strategy.py)
                    base = self.strategy
                    while hasattr(base, "super_strategy"):
                        base = base.super_strategy
                    pending = getattr(base, "pending_worklist", None)
                    if pending is not None:
                        from mythril_tpu.support.model import model_cache

                        ready = []
                        for state in new_states:
                            if model_cache.check_quick_sat(
                                state.world_state.constraints
                                .get_all_constraints()
                            ) is not None:
                                ready.append(state)
                            else:
                                pending.append(state)
                        new_states = ready
            self.manage_cfg(op_code, new_states)
            if new_states:
                self.work_list.extend(new_states)
            elif track_gas:
                final_states.append(global_state)
            self.total_states += len(new_states)
        self._fire("stop_exec")
        return final_states if track_gas else None

    def execute_state(
        self, global_state: GlobalState
    ) -> Tuple[List[GlobalState], Optional[str]]:
        # plugin state hooks may skip the state
        try:
            for hook in self._hooks["execute_state"]:
                hook(global_state)
        except PluginSkipState:
            return [], None

        instr = global_state.instruction
        if instr is None:
            # pc beyond code end: implicit STOP (reference harvests :420)
            return self._implicit_stop(global_state)
        op_name = instr.opcode

        # stack arity pre-check
        from mythril_tpu.support.opcodes import BY_NAME

        spec = BY_NAME.get(op_name)
        if spec is not None and len(global_state.mstate.stack) < spec.pops:
            log.debug(
                "stack underflow executing %s at pc %d",
                op_name, global_state.mstate.pc,
            )
            return self.handle_vm_exception(
                global_state, op_name, "stack underflow"
            )

        self._record_state(global_state, instr)

        try:
            for hook in self.pre_hooks[op_name]:
                hook(global_state)
            for hook in self.instr_pre_hook[op_name]:
                hook(global_state)
        except PluginSkipState:
            # a pruner (e.g. dependency_pruner) vetoed this state
            return [], None

        # per-opcode wall histogram of the per-state (fallback) path: the
        # promotion shortlist for the frontier fast set (stats JSON
        # interp_opcode_wall_top). Timed around the handler only — hooks
        # and snapshots are engine overhead, not opcode cost.
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        stats = SolverStatistics()
        op_start = time.monotonic() if stats.enabled else 0.0
        op_solver_before = stats.solver_time
        try:
            try:
                new_states = instructions.execute(global_state, instr)
            except VmException as error:
                # exceptional halt: the frame reverts
                transaction, return_snapshot = \
                    global_state.transaction_stack[-1]
                self._fire_transaction_end_hooks(
                    global_state, transaction, return_snapshot, True
                )
                new_states = self.handle_vm_exception(
                    global_state, op_name, str(error)
                )[0]
            except TransactionStartSignal as signal:
                new_states = self._start_inner_transaction(
                    global_state, signal)
                return new_states, op_name
            except TransactionEndSignal as signal:
                new_states = self._end_transaction(
                    global_state, signal, op_name)
        finally:
            if stats.enabled:
                # solver seconds inside the handler (SHA3/RETURN
                # concretization, tx-end confirmations) are solver cost,
                # not opcode cost — without the subtraction STOP would
                # top every histogram and say nothing about the fast set
                stats.add_interp_opcode_wall(
                    op_name, max(0.0, (time.monotonic() - op_start)
                                 - (stats.solver_time - op_solver_before)))

        kept = []
        for state in new_states:
            try:
                for hook in self.post_hooks[op_name]:
                    hook(state)
                for hook in self.instr_post_hook[op_name]:
                    hook(state)
                kept.append(state)
            except PluginSkipState:
                continue
        return kept, op_name

    def _implicit_stop(self, global_state):
        transaction = global_state.current_transaction
        try:
            transaction.end(global_state, return_data=None, revert=False)
        except TransactionEndSignal as signal:
            return self._end_transaction(global_state, signal, "STOP"), "STOP"

    # -- transaction frame handling -----------------------------------------

    def _start_inner_transaction(
        self, global_state: GlobalState, signal: TransactionStartSignal
    ) -> List[GlobalState]:
        # snapshot the caller for resumption (args already popped, pc at op)
        return_snapshot = signal.global_state.clone()
        new_global_state = signal.transaction.initial_global_state()
        new_global_state.transaction_stack = list(
            signal.global_state.transaction_stack
        ) + [(signal.transaction, return_snapshot)]
        new_global_state.node = global_state.node
        new_global_state.world_state.constraints = (
            signal.global_state.world_state.constraints
        )
        new_global_state.transient_storage = signal.global_state.transient_storage
        # an inner call executes in the SAME block as its caller
        new_global_state.environment.block_number = (
            signal.global_state.environment.block_number
        )
        self._fire("transaction_start", signal.transaction, new_global_state)
        return [new_global_state]

    def _end_transaction(
        self, global_state: GlobalState, signal: TransactionEndSignal, op_name: str
    ) -> List[GlobalState]:
        transaction, return_snapshot = signal.global_state.transaction_stack[-1]
        self._fire_transaction_end_hooks(
            signal.global_state, transaction, return_snapshot, signal.revert
        )
        if return_snapshot is None:
            # top-level transaction complete
            if isinstance(transaction, ContractCreationTransaction):
                self._finalize_creation(transaction, signal)
            keep = (
                not isinstance(transaction, ContractCreationTransaction)
                or transaction.return_data is not None
            ) and not signal.revert
            if keep:
                from mythril_tpu.analysis.potential_issues import (
                    check_potential_issues,
                )

                check_potential_issues(signal.global_state)
                signal.global_state.world_state.node = global_state.node
                self._add_world_state(signal.global_state)
            return []

        # inner frame: resume the caller
        for hook in self.post_hooks[op_name]:
            hook(signal.global_state)
        caller_state = return_snapshot.clone()
        # propagate persist_over_calls annotations
        for annotation in signal.global_state.annotations:
            if getattr(annotation, "persist_over_calls", False):
                caller_state.annotations.append(annotation)
        return self._end_message_call(
            caller_state, signal.global_state, transaction, signal.revert
        )

    def _finalize_creation(self, transaction, signal):
        """Install returned runtime bytecode (reference models :283-290)."""
        from mythril_tpu.disasm import Disassembly
        from mythril_tpu.laser.instructions import concrete_or_none

        return_data = transaction.return_data
        if signal.revert or return_data is None:
            return
        raw = []
        symbolic = False
        for byte in return_data.return_data:
            value = byte if isinstance(byte, int) else concrete_or_none(byte)
            if value is None:
                # deploy-time-patched byte (solidity immutable): keep the
                # symbolic expression in the installed code (reference
                # transaction_models.py:283-290 assigns the raw tuple)
                raw.append(byte)
                symbolic = True
            else:
                raw.append(value)
        code = tuple(raw) if symbolic else bytes(raw)
        transaction.callee_account.code = Disassembly(code)

    def _end_message_call(
        self,
        caller_state: GlobalState,
        ended_state: GlobalState,
        transaction,
        revert: bool,
    ) -> List[GlobalState]:
        from mythril_tpu.laser.call_ops import CallReturnContext, _write_return_data
        from mythril_tpu.laser.instructions import bv

        caller_state.world_state.constraints += (
            ended_state.world_state.constraints
        )
        caller_state.last_return_data = transaction.return_data
        if not revert:
            # adopt the callee's final world state and transient storage
            # (EIP-1153: TSTOREs survive successful frame returns)
            new_world = ended_state.world_state
            caller_state.world_state = new_world
            caller_state.transient_storage = ended_state.transient_storage
            addr = caller_state.environment.active_account.address
            if not addr.symbolic and addr.concrete_value in new_world.accounts:
                caller_state.environment.active_account = new_world.accounts[
                    addr.concrete_value
                ]
            if isinstance(transaction, ContractCreationTransaction):
                self._finalize_creation_inner(transaction, ended_state)
                caller_state.mstate.min_gas_used += ended_state.mstate.min_gas_used
                caller_state.mstate.max_gas_used += ended_state.mstate.max_gas_used

        context: CallReturnContext = getattr(transaction, "return_context", None)
        if context is not None and not revert and transaction.return_data is not None:
            _write_return_data(
                caller_state,
                transaction.return_data.return_data,
                context.memory_out_offset,
                context.memory_out_size,
            )
        if isinstance(transaction, ContractCreationTransaction):
            caller_state.mstate.stack.append(
                bv(0) if revert else transaction.callee_account.address
            )
        else:
            caller_state.mstate.stack.append(bv(0) if revert else bv(1))
        caller_state.mstate.pc += 1
        caller_state.node = ended_state.node
        return [caller_state]

    def _finalize_creation_inner(self, transaction, ended_state):
        from mythril_tpu.disasm import Disassembly
        from mythril_tpu.laser.instructions import concrete_or_none

        return_data = transaction.return_data
        if return_data is None:
            return
        raw = bytearray()
        for byte in return_data.return_data:
            value = byte if isinstance(byte, int) else concrete_or_none(byte)
            if value is None:
                return
            raw.append(value)
        transaction.callee_account.code = Disassembly(bytes(raw))

    def _fire_transaction_end_hooks(self, global_state, transaction,
                                    return_snapshot, revert):
        for hook in self._hooks["transaction_end"]:
            hook(global_state, transaction, return_snapshot, revert)

    def _add_world_state(self, global_state: GlobalState):
        try:
            for hook in self._hooks["add_world_state"]:
                hook(global_state)
        except (PluginSkipWorldState, PluginSkipState):
            return
        # persist_to_world_state annotations move to the world state
        for annotation in global_state.annotations:
            if getattr(annotation, "persist_to_world_state", False):
                if annotation not in global_state.world_state.annotations:
                    global_state.world_state.annotate(annotation)
        self.open_states.append(global_state.world_state)

    def handle_vm_exception(
        self, global_state: GlobalState, op_code: str, error_msg: str
    ) -> Tuple[List[GlobalState], str]:
        """A VmException reverts the current frame (reference svm.py)."""
        transaction, return_snapshot = global_state.transaction_stack[-1]
        log.debug("VmException %s at pc %d: %s", op_code,
                  global_state.mstate.pc, error_msg)
        if return_snapshot is None:
            return [], op_code
        caller_state = return_snapshot.clone()
        transaction.return_data = None
        states = self._end_message_call(
            caller_state, global_state, transaction, revert=True
        )
        return states, op_code

    # -- CFG / statespace ----------------------------------------------------

    def new_node(self, transaction, constraints) -> Node:
        contract_name = getattr(
            getattr(transaction, "callee_account", None), "contract_name", "?"
        )
        node = Node(
            contract_name=contract_name,
            constraints=constraints,
            function_name=(
                "constructor"
                if isinstance(transaction, ContractCreationTransaction)
                else "fallback"
            ),
        )
        self.nodes[node.uid] = node
        return node

    def _record_state(self, global_state: GlobalState, instr):
        if not self.requires_statespace:
            return
        node = global_state.node
        if node is None:
            return
        node.states.append(_StateSnapshot(global_state, instr))

    def manage_cfg(self, op_code: Optional[str], new_states: List[GlobalState]):
        # NOT gated on requires_statespace: function-entry naming rides the
        # CFG nodes, so they must exist even when states aren't recorded
        # (reference svm.py:581 builds nodes unconditionally; only state
        # recording inside nodes is statespace-gated)
        if op_code is None:
            return
        if op_code in ("JUMP", "JUMPI"):
            for state in new_states:
                self._new_node_for_state(
                    state,
                    JumpType.UNCONDITIONAL if op_code == "JUMP" else JumpType.CONDITIONAL,
                    condition=(
                        state.world_state.constraints[-1]
                        if op_code == "JUMPI" and state.world_state.constraints
                        else None
                    ),
                )
        elif op_code in ("CALL", "CALLCODE", "DELEGATECALL", "STATICCALL",
                         "CREATE", "CREATE2"):
            for state in new_states:
                self._new_node_for_state(state, JumpType.CALL)
        elif op_code in ("RETURN", "STOP", "REVERT", "SELFDESTRUCT"):
            for state in new_states:
                self._new_node_for_state(state, JumpType.RETURN)

    def _new_node_for_state(self, state: GlobalState, edge_type, condition=None):
        old_node = state.node
        new_node = Node(
            contract_name=old_node.contract_name if old_node else "?",
            start_addr=state.mstate.pc,
            constraints=state.world_state.constraints,
            function_name=old_node.function_name if old_node else "unknown",
        )
        self.nodes[new_node.uid] = new_node
        state.node = new_node
        if old_node is not None:
            self.edges.append(
                Edge(old_node.uid, new_node.uid, edge_type, condition)
            )
        # function-entry naming from the dispatcher
        entry_name = state.environment.code.function_name_for_pc(state.mstate.pc)
        if entry_name:
            new_node.function_name = entry_name
            new_node.flags |= NodeFlags.FUNC_ENTRY
            state.environment.active_function_name = entry_name


class _StateSnapshot:
    """Lightweight per-instruction record for POST modules and dumps.

    Captures the mutable scalars (stack copy, pc, constraints copy) and
    shares the heavyweight structures — same fidelity tradeoff the
    reference makes by storing shallow per-instruction copies."""

    __slots__ = ("world_state", "environment", "mstate_stack", "pc",
                 "instruction", "transaction", "constraints", "node",
                 "annotations")

    def __init__(self, global_state: GlobalState, instr):
        self.world_state = global_state.world_state
        self.environment = global_state.environment
        self.mstate_stack = list(global_state.mstate.stack)
        self.pc = global_state.mstate.pc
        self.instruction = instr
        self.transaction = global_state.current_transaction
        self.constraints = global_state.world_state.constraints.copy()
        self.node = global_state.node
        self.annotations = global_state.annotations

    def get_current_instruction(self):
        return self.instruction

"""Foundry/hevm cheat-code address recognition
(reference laser/ethereum/cheat_code.py:44). Calls to it are stubbed."""

HEVM_CHEAT_ADDRESS = 0x7109709ECFA91A80626FF3989D68F67F5B1DD12D


def is_cheat_address(address) -> bool:
    if hasattr(address, "symbolic"):
        if address.symbolic:
            return False
        address = address.concrete_value
    return address == HEVM_CHEAT_ADDRESS

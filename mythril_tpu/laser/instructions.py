"""EVM instruction semantics over symbolic state.

Behavioral parity with reference mythril/laser/ethereum/instructions.py
(2.5k LoC, class Instruction with one method per opcode); re-designed as a
dispatch table of handler functions. Each handler mutates the incoming
GlobalState (single-ownership worklist discipline; forks clone explicitly)
and returns the successor list. The engine owns pre/post hooks, stack-arity
checks, and signal handling (svm.py).

Conventions: stack top first. All 256-bit. `/`, `%`, `<`, `>` on BitVec are
UNSIGNED (EVM semantics; see smt/bitvec.py docstring).
"""

from typing import Callable, Dict, List

from mythril_tpu.laser.evm_exceptions import (
    InvalidInstruction,
    InvalidJumpDestination,
    VmException,
    WriteProtection,
)
from mythril_tpu.laser.function_managers import (
    exponent_function_manager,
    keccak_function_manager,
)
from mythril_tpu.laser.state.global_state import GlobalState
from mythril_tpu.laser.state.return_data import ReturnData
from mythril_tpu.smt import (
    AShR,
    BitVec,
    Bool,
    Concat,
    Extract,
    If,
    LShR,
    Not,
    SDiv,
    SignExt,
    SRem,
    UDiv,
    UGE,
    UGT,
    ULT,
    URem,
    ZeroExt,
    is_false,
    is_true,
    simplify,
    symbol_factory,
)
from mythril_tpu.support.opcodes import BY_NAME

HANDLERS: Dict[str, Callable] = {}

TT256 = 2 ** 256
TT256M1 = 2 ** 256 - 1

STATE_MODIFYING_OPS = frozenset(
    ["SSTORE", "CREATE", "CREATE2", "SELFDESTRUCT", "TSTORE",
     "LOG0", "LOG1", "LOG2", "LOG3", "LOG4"]
)


def op(*names):
    def register(func):
        for name in names:
            HANDLERS[name] = func
        return func

    return register


def bv(value: int) -> BitVec:
    return symbol_factory.BitVecVal(value, 256)


def bool_to_bv(condition: Bool) -> BitVec:
    return If(condition, bv(1), bv(0))


def concrete_or_none(value: BitVec):
    value = simplify(value)
    return value.concrete_value if not value.symbolic else None


def concretize(global_state: GlobalState, value: BitVec, name: str) -> int:
    """Force a concrete value via the solver (pins it with a constraint).

    An UNSAT path (or solver timeout) must kill THIS path, not the whole
    exploration — raise a VmException so execute_state retires the state
    like any other exceptional halt."""
    value = simplify(value)
    if not value.symbolic:
        return value.concrete_value
    from mythril_tpu.smt.solver.frontend import (
        SolverTimeOutException,
        UnsatError,
    )
    from mythril_tpu.support.model import get_model

    try:
        model = get_model(
            global_state.world_state.constraints.get_all_constraints()
        )
    except UnsatError:
        raise VmException(f"infeasible path at {name} concretization") \
            from None
    except SolverTimeOutException:
        raise VmException(f"solver timeout at {name} concretization") \
            from None
    concrete = model.eval_int(value)
    global_state.world_state.constraints.append(value == bv(concrete))
    return concrete


def execute(global_state: GlobalState, instr) -> List[GlobalState]:
    """Run one instruction. Raises Transaction*Signal / VmException."""
    name = instr.opcode
    spec = BY_NAME.get(name)
    if spec is None:
        raise InvalidInstruction(f"invalid opcode 0x{instr.byte:02x}")
    if global_state.environment.static and name in STATE_MODIFYING_OPS:
        raise WriteProtection(f"{name} inside STATICCALL")

    if name.startswith("PUSH"):
        states = _push(global_state, instr)
    elif name.startswith("DUP"):
        states = _dup(global_state, int(name[3:]))
    elif name.startswith("SWAP"):
        states = _swap(global_state, int(name[4:]))
    elif name.startswith("LOG"):
        states = _log(global_state, int(name[3:]))
    else:
        handler = HANDLERS.get(name)
        if handler is None:
            raise InvalidInstruction(f"unimplemented opcode {name}")
        states = handler(global_state)
    # opcode gas accrues on the states the handler RETURNED — halting ops
    # (STOP/RETURN/SELFDESTRUCT) raise a signal and never charge their own
    # cost, matching reference StateTransition.accumulate_gas
    # (instructions.py:163-172 runs after the handler)
    for state in states:
        state.mstate.min_gas_used += spec.gas_min
        state.mstate.max_gas_used += spec.gas_max
        state.mstate.check_gas()
    return states


def advance(global_state: GlobalState) -> List[GlobalState]:
    global_state.mstate.pc += 1
    return [global_state]


# ---------------------------------------------------------------------------
# stack ops


def _push(global_state: GlobalState, instr) -> List[GlobalState]:
    value = instr.argument_int if instr.argument is not None else 0
    if value is None:
        # symbolic operand (deploy-time-patched immutable): concat the
        # byte entries big-endian (reference instructions.py push_ tuple arm)
        parts = [
            symbol_factory.BitVecVal(b, 8) if isinstance(b, int) else b
            for b in instr.argument
        ]
        word = Concat(parts) if len(parts) > 1 else parts[0]
        if word.size < 256:
            word = Concat([symbol_factory.BitVecVal(0, 256 - word.size), word])
        global_state.mstate.stack.append(simplify(word))
    else:
        global_state.mstate.stack.append(bv(value))
    width = len(instr.argument) if instr.argument is not None else 0
    global_state.mstate.pc += 1 + width
    return [global_state]


def _dup(global_state: GlobalState, depth: int) -> List[GlobalState]:
    stack = global_state.mstate.stack
    if len(stack) < depth:
        raise VmException(f"DUP{depth} on stack of {len(stack)}")
    stack.append(stack[-depth])
    return advance(global_state)


def _swap(global_state: GlobalState, depth: int) -> List[GlobalState]:
    stack = global_state.mstate.stack
    if len(stack) < depth + 1:
        raise VmException(f"SWAP{depth} on stack of {len(stack)}")
    stack[-1], stack[-depth - 1] = stack[-depth - 1], stack[-1]
    return advance(global_state)


def _log(global_state: GlobalState, topics: int) -> List[GlobalState]:
    global_state.mstate.pop(2 + topics)
    return advance(global_state)


@op("POP")
def pop_(global_state):
    global_state.mstate.pop()
    return advance(global_state)


# ---------------------------------------------------------------------------
# arithmetic


@op("ADD")
def add_(global_state):
    s = global_state.mstate.stack
    s.append(s.pop() + s.pop())
    return advance(global_state)


@op("SUB")
def sub_(global_state):
    s = global_state.mstate.stack
    a, b = s.pop(), s.pop()
    s.append(a - b)
    return advance(global_state)


@op("MUL")
def mul_(global_state):
    s = global_state.mstate.stack
    s.append(s.pop() * s.pop())
    return advance(global_state)


@op("DIV")
def div_(global_state):
    s = global_state.mstate.stack
    a, b = s.pop(), s.pop()
    s.append(UDiv(a, b))
    return advance(global_state)


@op("SDIV")
def sdiv_(global_state):
    s = global_state.mstate.stack
    a, b = s.pop(), s.pop()
    s.append(SDiv(a, b))
    return advance(global_state)


@op("MOD")
def mod_(global_state):
    s = global_state.mstate.stack
    a, b = s.pop(), s.pop()
    s.append(URem(a, b))
    return advance(global_state)


@op("SMOD")
def smod_(global_state):
    s = global_state.mstate.stack
    a, b = s.pop(), s.pop()
    s.append(SRem(a, b))
    return advance(global_state)


@op("ADDMOD")
def addmod_(global_state):
    s = global_state.mstate.stack
    a, b, modulus = s.pop(), s.pop(), s.pop()
    # intermediate sum is NOT truncated to 256 bits
    wide = ZeroExt(1, a) + ZeroExt(1, b)
    result = URem(wide, ZeroExt(1, modulus))
    s.append(Extract(255, 0, result))
    return advance(global_state)


@op("MULMOD")
def mulmod_(global_state):
    s = global_state.mstate.stack
    a, b, modulus = s.pop(), s.pop(), s.pop()
    wide = ZeroExt(256, a) * ZeroExt(256, b)
    result = URem(wide, ZeroExt(256, modulus))
    s.append(Extract(255, 0, result))
    return advance(global_state)


@op("EXP")
def exp_(global_state):
    s = global_state.mstate.stack
    base, exponent = s.pop(), s.pop()
    result, condition = exponent_function_manager.create_condition(base, exponent)
    if not is_true(condition):
        global_state.world_state.constraints.append(condition)
    s.append(result)
    return advance(global_state)


@op("SIGNEXTEND")
def signextend_(global_state):
    s = global_state.mstate.stack
    position, value = s.pop(), s.pop()
    pos_concrete = concrete_or_none(position)
    if pos_concrete is not None:
        if pos_concrete >= 31:
            s.append(value)
        else:
            bits = 8 * (pos_concrete + 1)
            s.append(SignExt(256 - bits, Extract(bits - 1, 0, value)))
    else:
        result = value
        for k in range(31):
            bits = 8 * (k + 1)
            extended = SignExt(256 - bits, Extract(bits - 1, 0, value))
            result = If(position == bv(k), extended, result)
        s.append(result)
    return advance(global_state)


# ---------------------------------------------------------------------------
# comparison / bitwise


@op("LT")
def lt_(global_state):
    s = global_state.mstate.stack
    a, b = s.pop(), s.pop()
    s.append(bool_to_bv(ULT(a, b)))
    return advance(global_state)


@op("GT")
def gt_(global_state):
    s = global_state.mstate.stack
    a, b = s.pop(), s.pop()
    s.append(bool_to_bv(UGT(a, b)))
    return advance(global_state)


@op("SLT")
def slt_(global_state):
    s = global_state.mstate.stack
    a, b = s.pop(), s.pop()
    s.append(bool_to_bv(a.slt(b)))
    return advance(global_state)


@op("SGT")
def sgt_(global_state):
    s = global_state.mstate.stack
    a, b = s.pop(), s.pop()
    s.append(bool_to_bv(a.sgt(b)))
    return advance(global_state)


@op("EQ")
def eq_(global_state):
    s = global_state.mstate.stack
    a, b = s.pop(), s.pop()
    s.append(bool_to_bv(a == b))
    return advance(global_state)


@op("ISZERO")
def iszero_(global_state):
    s = global_state.mstate.stack
    s.append(bool_to_bv(s.pop() == bv(0)))
    return advance(global_state)


@op("AND")
def and_(global_state):
    s = global_state.mstate.stack
    s.append(s.pop() & s.pop())
    return advance(global_state)


@op("OR")
def or_(global_state):
    s = global_state.mstate.stack
    s.append(s.pop() | s.pop())
    return advance(global_state)


@op("XOR")
def xor_(global_state):
    s = global_state.mstate.stack
    s.append(s.pop() ^ s.pop())
    return advance(global_state)


@op("NOT")
def not_(global_state):
    s = global_state.mstate.stack
    s.append(~s.pop())
    return advance(global_state)


@op("BYTE")
def byte_(global_state):
    s = global_state.mstate.stack
    index, value = s.pop(), s.pop()
    result = If(
        ULT(index, bv(32)),
        LShR(value, (bv(31) - index) * bv(8)) & bv(0xFF),
        bv(0),
    )
    s.append(result)
    return advance(global_state)


@op("SHL")
def shl_(global_state):
    s = global_state.mstate.stack
    shift, value = s.pop(), s.pop()
    s.append(value << shift)
    return advance(global_state)


@op("SHR")
def shr_(global_state):
    s = global_state.mstate.stack
    shift, value = s.pop(), s.pop()
    s.append(LShR(value, shift))
    return advance(global_state)


@op("SAR")
def sar_(global_state):
    s = global_state.mstate.stack
    shift, value = s.pop(), s.pop()
    s.append(AShR(value, shift))
    return advance(global_state)


# ---------------------------------------------------------------------------
# keccak


@op("SHA3")
def sha3_(global_state):
    s = global_state.mstate.stack
    offset, length = s.pop(), s.pop()
    length_concrete = concrete_or_none(length)
    if length_concrete is None:
        length_concrete = concretize(global_state, length, "sha3_length")
    if length_concrete == 0:
        s.append(keccak_function_manager.get_empty_keccak_hash())
        return advance(global_state)
    offset_concrete = concrete_or_none(offset)
    if offset_concrete is None:
        offset_concrete = concretize(global_state, offset, "sha3_offset")
    global_state.mstate.mem_extend(offset_concrete, length_concrete)
    data_bytes = [
        global_state.mstate.memory.get_byte(offset_concrete + i)
        for i in range(length_concrete)
    ]
    data = Concat(data_bytes) if len(data_bytes) > 1 else data_bytes[0]
    data = simplify(data)
    s.append(keccak_function_manager.create_keccak(data))
    return advance(global_state)


# ---------------------------------------------------------------------------
# environment


@op("ADDRESS")
def address_(global_state):
    global_state.mstate.stack.append(global_state.environment.address)
    return advance(global_state)


@op("BALANCE")
def balance_(global_state):
    s = global_state.mstate.stack
    address = s.pop()
    s.append(global_state.world_state.balances[address])
    return advance(global_state)


@op("SELFBALANCE")
def selfbalance_(global_state):
    global_state.mstate.stack.append(
        global_state.world_state.balances[global_state.environment.address]
    )
    return advance(global_state)


@op("ORIGIN")
def origin_(global_state):
    global_state.mstate.stack.append(global_state.environment.origin)
    return advance(global_state)


@op("CALLER")
def caller_(global_state):
    global_state.mstate.stack.append(global_state.environment.sender)
    return advance(global_state)


@op("CALLVALUE")
def callvalue_(global_state):
    global_state.mstate.stack.append(global_state.environment.callvalue)
    return advance(global_state)


@op("CALLDATALOAD")
def calldataload_(global_state):
    s = global_state.mstate.stack
    offset = s.pop()
    s.append(global_state.environment.calldata.get_word_at(offset))
    return advance(global_state)


@op("CALLDATASIZE")
def calldatasize_(global_state):
    global_state.mstate.stack.append(
        global_state.environment.calldata.calldatasize
    )
    return advance(global_state)


APPROX_COPY_BYTES = 320  # bound for symbolic-length copies (keeps len FREE)


def _copy_to_memory(global_state, mem_offset, data_offset, length, reader):
    """Shared body of *COPY ops.

    A symbolic length must NOT be solver-concretized: pinning it (the model
    usually picks 0) contradicts later guards like require(len > 0) and
    silently kills every continuing path. Following the reference's
    approximation (instructions.py _calldata_copy_helper: "the excess size
    will get overwritten"), a bounded number of source bytes is copied
    unconditionally and `length` stays unconstrained."""
    mem_offset_c = concrete_or_none(mem_offset)
    if mem_offset_c is None:
        mem_offset_c = concretize(global_state, mem_offset, "copy_dest")
    length_c = concrete_or_none(length)
    memory = global_state.mstate.memory
    if length_c is None:
        length_c = APPROX_COPY_BYTES
    else:
        length_c = min(length_c, 0x10000)  # sanity cap
    global_state.mstate.mem_extend(mem_offset_c, length_c)
    for i in range(length_c):
        memory.write_byte(mem_offset_c + i, reader(data_offset, i))


def _calldata_copy(global_state, mem_offset, data_offset, length):
    calldata = global_state.environment.calldata

    def reader(base, i):
        if isinstance(base, BitVec) and base.symbolic:
            return calldata[base + i]
        base_c = base.concrete_value if isinstance(base, BitVec) else base
        return calldata[base_c + i]

    _copy_to_memory(global_state, mem_offset, data_offset, length, reader)


@op("CALLDATACOPY")
def calldatacopy_(global_state):
    s = global_state.mstate.stack
    mem_offset, data_offset, length = s.pop(), s.pop(), s.pop()
    if _in_creation_tx(global_state):
        # creation calldata is a modelling fiction holding constructor args;
        # a real CALLDATACOPY during creation copies nothing useful
        # (reference instructions.py:887-889)
        return advance(global_state)
    _calldata_copy(global_state, mem_offset, data_offset, length)
    return advance(global_state)


def _in_creation_tx(global_state) -> bool:
    from mythril_tpu.laser.transaction.models import ContractCreationTransaction

    return isinstance(
        global_state.current_transaction, ContractCreationTransaction
    )


@op("CODESIZE")
def codesize_(global_state):
    code = global_state.environment.code
    code_size = len(code.bytecode)
    if _in_creation_tx(global_state):
        # constructor args sit past the init code: report init-code size plus
        # room for them, pinning symbolic calldata's size so selector reads
        # stay consistent (reference instructions.py:989-1000)
        calldata = global_state.environment.calldata
        from mythril_tpu.laser.state.calldata import ConcreteCalldata

        if isinstance(calldata, ConcreteCalldata):
            code_size += calldata.size
        else:
            code_size += 0x200  # space for 16 32-byte constructor args
            global_state.world_state.constraints.append(
                calldata.calldatasize == bv(code_size)
            )
    global_state.mstate.stack.append(bv(code_size))
    return advance(global_state)


@op("CODECOPY")
def codecopy_(global_state):
    s = global_state.mstate.stack
    mem_offset, code_offset, length = s.pop(), s.pop(), s.pop()
    bytecode = global_state.environment.code.bytecode
    code_size = len(bytecode)

    if _in_creation_tx(global_state):
        # reads past the init code are constructor-argument reads; serve them
        # from the (symbolic) creation calldata (reference :1093-1127)
        from mythril_tpu.laser.state.calldata import SymbolicCalldata

        code_offset_c = concrete_or_none(code_offset)
        if (
            isinstance(global_state.environment.calldata, SymbolicCalldata)
            and code_offset_c is not None
            and code_offset_c >= code_size
        ):
            _calldata_copy(
                global_state, mem_offset, bv(code_offset_c - code_size), length
            )
            return advance(global_state)

    def reader(base, i):
        base_c = concrete_or_none(base) if isinstance(base, BitVec) else base
        if base_c is None:
            return global_state.new_bitvec(f"codebyte_{i}", 8)
        index = base_c + i
        return bytecode[index] if index < len(bytecode) else 0

    _copy_to_memory(global_state, mem_offset, code_offset, length, reader)
    return advance(global_state)


@op("GASPRICE")
def gasprice_(global_state):
    global_state.mstate.stack.append(global_state.environment.gasprice)
    return advance(global_state)


@op("EXTCODESIZE")
def extcodesize_(global_state):
    s = global_state.mstate.stack
    address = s.pop()
    addr_c = concrete_or_none(address)
    if addr_c is not None and addr_c in global_state.world_state.accounts:
        code = global_state.world_state.accounts[addr_c].code
        s.append(bv(len(code.bytecode)))
    else:
        s.append(global_state.new_bitvec(f"extcodesize_{address}", 256))
    return advance(global_state)


@op("EXTCODECOPY")
def extcodecopy_(global_state):
    s = global_state.mstate.stack
    address, mem_offset, code_offset, length = s.pop(), s.pop(), s.pop(), s.pop()
    addr_c = concrete_or_none(address)
    if addr_c is not None and addr_c in global_state.world_state.accounts:
        bytecode = global_state.world_state.accounts[addr_c].code.bytecode
    else:
        bytecode = b""

    def reader(base, i):
        base_c = concrete_or_none(base) if isinstance(base, BitVec) else base
        if base_c is None:
            return 0
        index = base_c + i
        return bytecode[index] if index < len(bytecode) else 0

    _copy_to_memory(global_state, mem_offset, code_offset, length, reader)
    return advance(global_state)


@op("EXTCODEHASH")
def extcodehash_(global_state):
    s = global_state.mstate.stack
    address = s.pop()
    addr_c = concrete_or_none(address)
    if addr_c is not None and addr_c in global_state.world_state.accounts:
        code = global_state.world_state.accounts[addr_c].code
        s.append(bv(int.from_bytes(code.bytecode_hash, "big")))
    else:
        s.append(global_state.new_bitvec(f"extcodehash_{address}", 256))
    return advance(global_state)


@op("RETURNDATASIZE")
def returndatasize_(global_state):
    ret = global_state.last_return_data
    if ret is None:
        global_state.mstate.stack.append(bv(0))
    else:
        global_state.mstate.stack.append(ret.size)
    return advance(global_state)


@op("RETURNDATACOPY")
def returndatacopy_(global_state):
    s = global_state.mstate.stack
    mem_offset, data_offset, length = s.pop(), s.pop(), s.pop()
    ret = global_state.last_return_data

    def reader(base, i):
        if ret is None:
            return 0
        base_c = concrete_or_none(base) if isinstance(base, BitVec) else base
        if base_c is None:
            return 0
        index = base_c + i
        if index < len(ret.return_data):
            return ret.return_data[index]
        return 0

    _copy_to_memory(global_state, mem_offset, data_offset, length, reader)
    return advance(global_state)


# ---------------------------------------------------------------------------
# block context


@op("BLOCKHASH")
def blockhash_(global_state):
    s = global_state.mstate.stack
    block_number = s.pop()
    s.append(global_state.new_bitvec(f"blockhash_{block_number}", 256))
    return advance(global_state)


@op("COINBASE")
def coinbase_(global_state):
    global_state.mstate.stack.append(global_state.new_bitvec("coinbase", 256))
    return advance(global_state)


@op("TIMESTAMP")
def timestamp_(global_state):
    global_state.mstate.stack.append(global_state.new_bitvec("timestamp", 256))
    return advance(global_state)


@op("NUMBER")
def number_(global_state):
    global_state.mstate.stack.append(global_state.environment.block_number)
    return advance(global_state)


@op("PREVRANDAO")
def prevrandao_(global_state):
    global_state.mstate.stack.append(global_state.new_bitvec("prevrandao", 256))
    return advance(global_state)


@op("GASLIMIT")
def gaslimit_(global_state):
    global_state.mstate.stack.append(bv(global_state.mstate.gas_limit))
    return advance(global_state)


@op("CHAINID")
def chainid_(global_state):
    global_state.mstate.stack.append(global_state.environment.chainid)
    return advance(global_state)


@op("BASEFEE")
def basefee_(global_state):
    global_state.mstate.stack.append(global_state.environment.basefee)
    return advance(global_state)


@op("BLOBHASH")
def blobhash_(global_state):
    s = global_state.mstate.stack
    index = s.pop()
    s.append(global_state.new_bitvec(f"blobhash_{index}", 256))
    return advance(global_state)


@op("BLOBBASEFEE")
def blobbasefee_(global_state):
    global_state.mstate.stack.append(global_state.new_bitvec("blobbasefee", 256))
    return advance(global_state)


# ---------------------------------------------------------------------------
# memory / storage


@op("MLOAD")
def mload_(global_state):
    s = global_state.mstate.stack
    offset = s.pop()
    offset_c = concrete_or_none(offset)
    if offset_c is not None:
        global_state.mstate.mem_extend(offset_c, 32)
        s.append(global_state.mstate.memory.get_word_at(offset_c))
    else:
        s.append(global_state.mstate.memory.get_word_at(offset))
    return advance(global_state)


@op("MSTORE")
def mstore_(global_state):
    s = global_state.mstate.stack
    offset, value = s.pop(), s.pop()
    offset_c = concrete_or_none(offset)
    if offset_c is not None:
        global_state.mstate.mem_extend(offset_c, 32)
        global_state.mstate.memory.write_word_at(offset_c, value)
    else:
        global_state.mstate.memory.write_word_at(offset, value)
    return advance(global_state)


@op("MSTORE8")
def mstore8_(global_state):
    s = global_state.mstate.stack
    offset, value = s.pop(), s.pop()
    offset_c = concrete_or_none(offset)
    if offset_c is not None:
        global_state.mstate.mem_extend(offset_c, 1)
        global_state.mstate.memory.write_byte(offset_c, Extract(7, 0, value))
    else:
        global_state.mstate.memory.write_byte(offset, Extract(7, 0, value))
    return advance(global_state)


@op("MSIZE")
def msize_(global_state):
    global_state.mstate.stack.append(bv(global_state.mstate.memory_size))
    return advance(global_state)


@op("MCOPY")
def mcopy_(global_state):
    s = global_state.mstate.stack
    dest, src, length = s.pop(), s.pop(), s.pop()
    memory = global_state.mstate.memory

    def reader(base, i):
        base_c = concrete_or_none(base) if isinstance(base, BitVec) else base
        if base_c is None:
            return memory.get_byte(base + i)
        return memory.get_byte(base_c + i)

    # snapshot source region first (overlapping copy semantics)
    length_c = concrete_or_none(length)
    if length_c is None:
        length_c = concretize(global_state, length, "mcopy_len")
    src_bytes = [reader(src, i) for i in range(min(length_c, 0x10000))]
    dest_c = concrete_or_none(dest)
    if dest_c is None:
        dest_c = concretize(global_state, dest, "mcopy_dest")
    global_state.mstate.mem_extend(dest_c, length_c)
    for i, byte in enumerate(src_bytes):
        memory.write_byte(dest_c + i, byte)
    return advance(global_state)


@op("SLOAD")
def sload_(global_state):
    s = global_state.mstate.stack
    index = s.pop()
    s.append(global_state.environment.active_account.storage[index])
    return advance(global_state)


@op("SSTORE")
def sstore_(global_state):
    s = global_state.mstate.stack
    index, value = s.pop(), s.pop()
    global_state.environment.active_account.storage[index] = value
    return advance(global_state)


@op("TLOAD")
def tload_(global_state):
    s = global_state.mstate.stack
    index = s.pop()
    s.append(
        global_state.transient_storage.get(
            global_state.environment.address, index
        )
    )
    return advance(global_state)


@op("TSTORE")
def tstore_(global_state):
    s = global_state.mstate.stack
    index, value = s.pop(), s.pop()
    global_state.transient_storage.set(
        global_state.environment.address, index, value
    )
    return advance(global_state)


# ---------------------------------------------------------------------------
# control flow


@op("JUMP")
def jump_(global_state):
    s = global_state.mstate.stack
    destination = s.pop()
    dest_c = concrete_or_none(destination)
    if dest_c is None:
        raise InvalidJumpDestination("symbolic jump destination")
    if dest_c not in global_state.environment.code.valid_jump_destinations:
        raise InvalidJumpDestination(f"jump to non-JUMPDEST {dest_c}")
    global_state.mstate.pc = dest_c
    return [global_state]


@op("JUMPI")
def jumpi_(global_state):
    s = global_state.mstate.stack
    destination, condition = s.pop(), s.pop()
    dest_c = concrete_or_none(destination)
    if dest_c is None:
        raise InvalidJumpDestination("symbolic jump destination")

    branch_condition = simplify(condition != bv(0))
    negated_condition = simplify(condition == bv(0))
    successors = []

    # fall-through side. Depth counts branch decisions, not instructions —
    # max_depth bounds the number of JUMPIs on a path (reference
    # instructions.py:1636,1661 increments depth only here).
    if not is_false(negated_condition):
        fallthrough = global_state.clone()
        fallthrough.mstate.pc += 1
        fallthrough.mstate.depth += 1
        if not is_true(negated_condition):
            fallthrough.world_state.constraints.append(negated_condition)
        successors.append(fallthrough)

    # jump side
    if dest_c in global_state.environment.code.valid_jump_destinations:
        if not is_false(branch_condition):
            jump_state = global_state  # reuse the original for the taken side
            jump_state.mstate.pc = dest_c
            jump_state.mstate.depth += 1
            if not is_true(branch_condition):
                jump_state.world_state.constraints.append(branch_condition)
            successors.append(jump_state)

    return successors


@op("PC")
def pc_(global_state):
    global_state.mstate.stack.append(bv(global_state.mstate.pc))
    return advance(global_state)


@op("GAS")
def gas_(global_state):
    global_state.mstate.stack.append(global_state.new_bitvec("gas", 256))
    return advance(global_state)


@op("JUMPDEST")
def jumpdest_(global_state):
    return advance(global_state)


@op("STOP")
def stop_(global_state):
    transaction = global_state.current_transaction
    transaction.end(global_state, return_data=None, revert=False)


@op("RETURN")
def return_(global_state):
    s = global_state.mstate.stack
    offset, length = s.pop(), s.pop()
    length_c = concrete_or_none(length)
    if length_c is None:
        length_c = concretize(global_state, length, "return_length")
    length_c = min(length_c, 0x10000)
    offset_c = concrete_or_none(offset)
    if offset_c is None and length_c:
        offset_c = concretize(global_state, offset, "return_offset")
    if length_c:
        global_state.mstate.mem_extend(offset_c, length_c)
    data = [
        global_state.mstate.memory.get_byte(offset_c + i)
        for i in range(length_c)
    ]
    transaction = global_state.current_transaction
    transaction.end(global_state, return_data=ReturnData(data, length_c))


@op("REVERT")
def revert_(global_state):
    s = global_state.mstate.stack
    offset, length = s.pop(), s.pop()
    length_c = concrete_or_none(length) or 0
    length_c = min(length_c, 0x10000)
    offset_c = concrete_or_none(offset)
    data = []
    if offset_c is not None:
        if length_c:
            global_state.mstate.mem_extend(offset_c, length_c)
        data = [
            global_state.mstate.memory.get_byte(offset_c + i)
            for i in range(length_c)
        ]
    transaction = global_state.current_transaction
    transaction.end(
        global_state, return_data=ReturnData(data, length_c), revert=True
    )


@op("INVALID")
def invalid_(global_state):
    raise InvalidInstruction("INVALID / ASSERT_FAIL")


@op("SELFDESTRUCT")
def selfdestruct_(global_state):
    s = global_state.mstate.stack
    beneficiary = simplify(s.pop() & bv((1 << 160) - 1))  # address = low 160 bits
    world_state = global_state.world_state
    account = global_state.environment.active_account
    world_state.accounts_exist_or_load(beneficiary)  # materialize recipient
    balance = world_state.balances[account.address]
    world_state.balances[beneficiary] = (
        world_state.balances[beneficiary] + balance
    )
    world_state.balances[account.address] = bv(0)
    account.deleted = True
    transaction = global_state.current_transaction
    transaction.end(global_state, return_data=None, revert=False)


# calls / creation live in call_ops.py (registered on import)
from mythril_tpu.laser import call_ops  # noqa: E402,F401  (registers handlers)

"""Plugin interfaces (reference laser/plugin/interface.py + builder.py)."""


class LaserPlugin:
    def initialize(self, symbolic_vm) -> None:
        """Register hooks on the virtual machine."""
        raise NotImplementedError


class PluginBuilder:
    name = "plugin"
    author = "mythril_tpu"
    plugin_default_enabled = True

    def __init__(self):
        self.enabled = self.plugin_default_enabled

    def __call__(self, *args, **kwargs) -> LaserPlugin:
        raise NotImplementedError

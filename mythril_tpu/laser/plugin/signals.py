"""Plugin control-flow signals (reference laser/plugin/signals.py)."""


class PluginSignal(Exception):
    pass


class PluginSkipState(PluginSignal):
    """Drop the current global state from exploration."""


class PluginSkipWorldState(PluginSignal):
    """Drop the current world state (do not open it for the next tx)."""

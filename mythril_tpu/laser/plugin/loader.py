"""Singleton plugin loader (reference laser/plugin/loader.py:12-75)."""

import logging
from typing import Dict, List, Optional

from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder

log = logging.getLogger(__name__)


class LaserPluginLoader:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.laser_plugin_builders = {}
            cls._instance.plugin_args = {}
            cls._instance.plugin_list = {}
        return cls._instance

    def reset(self):
        self.laser_plugin_builders = {}
        self.plugin_args = {}
        self.plugin_list = {}

    def load(self, builder: PluginBuilder) -> None:
        if builder.name in self.laser_plugin_builders:
            log.warning("plugin %s already loaded", builder.name)
            return
        self.laser_plugin_builders[builder.name] = builder

    def is_enabled(self, name: str) -> bool:
        builder = self.laser_plugin_builders.get(name)
        return builder is not None and builder.enabled

    def add_args(self, name: str, **kwargs) -> None:
        self.plugin_args[name] = kwargs

    def enable(self, name: str) -> None:
        if name in self.laser_plugin_builders:
            self.laser_plugin_builders[name].enabled = True

    def disable(self, name: str) -> None:
        if name in self.laser_plugin_builders:
            self.laser_plugin_builders[name].enabled = False

    def instrument_virtual_machine(self, symbolic_vm, with_plugins: Optional[List[str]] = None):
        for name, builder in self.laser_plugin_builders.items():
            if not builder.enabled:
                continue
            if with_plugins is not None and name not in with_plugins:
                continue
            plugin = builder(**self.plugin_args.get(name, {}))
            plugin.initialize(symbolic_vm)
            self.plugin_list[name] = plugin

from mythril_tpu.laser.plugin.signals import (  # noqa: F401
    PluginSignal,
    PluginSkipState,
    PluginSkipWorldState,
)
from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder  # noqa: F401
from mythril_tpu.laser.plugin.loader import LaserPluginLoader  # noqa: F401

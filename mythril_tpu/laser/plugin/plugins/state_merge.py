"""State merging — collapse similar open world states after each tx
(reference laser/plugin/plugins/state_merge/, 368 LoC; off by default,
`--enable-state-merging`).

Two open states merge when their accounts agree structurally (nonce,
deleted flag, bytecode), their CFG nodes agree, every annotation pair is
merge-compatible, and their constraint sets differ in at most
CONSTRAINT_DIFFERENCE_LIMIT entries. The merged state keeps the shared
constraint prefix plus Or(d1, d2) of the two unique suffixes; storage and
balances become If(d1, v1, v2). A MergeAnnotation prevents re-merging
(each state merges at most once per round).
"""

import logging
from typing import List, Set

from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder
from mythril_tpu.laser.state.annotation import (
    MergeableStateAnnotation,
    StateAnnotation,
)
from mythril_tpu.laser.state.constraints import Constraints
from mythril_tpu.laser.state.world_state import WorldState
from mythril_tpu.smt import And, If, Or

log = logging.getLogger(__name__)

CONSTRAINT_DIFFERENCE_LIMIT = 15


class MergeAnnotation(StateAnnotation):
    """Marks a world state as already merged once."""


# -- mergeability ----------------------------------------------------------


def _accounts_mergeable(account1, account2) -> bool:
    return (account1.nonce == account2.nonce
            and account1.deleted == account2.deleted
            and account1.code.bytecode == account2.code.bytecode)


def _nodes_mergeable(node1, node2) -> bool:
    if node1 is None or node2 is None:
        return node1 is node2
    return (node1.function_name == node2.function_name
            and node1.contract_name == node2.contract_name
            and node1.start_addr == node2.start_addr)


def _constraints_mergeable(constraints1, constraints2) -> bool:
    set1 = {hash(c) for c in constraints1}
    set2 = {hash(c) for c in constraints2}
    diff = len(set1 - set2) + len(set2 - set1)
    return diff <= CONSTRAINT_DIFFERENCE_LIMIT


def _annotations_mergeable(state1: WorldState, state2: WorldState) -> bool:
    if len(state1.annotations) != len(state2.annotations):
        return False
    for a1, a2 in zip(state1.annotations, state2.annotations):
        if type(a1) is not type(a2):
            return False
        if isinstance(a1, MergeableStateAnnotation):
            if not a1.check_merge_annotation(a2):
                return False
        elif a1 is not a2 and not isinstance(a1, MergeAnnotation):
            # unmergeable distinct mutable annotations: refuse
            return False
    return True


def check_ws_merge_condition(state1: WorldState,
                             state2: WorldState) -> bool:
    if not _nodes_mergeable(state1.node, state2.node):
        return False
    if set(state1.accounts) != set(state2.accounts):
        return False
    for address, account2 in state2.accounts.items():
        if not _accounts_mergeable(state1.accounts[address], account2):
            return False
    if not _constraints_mergeable(state1.constraints, state2.constraints):
        return False
    return _annotations_mergeable(state1, state2)


# -- the merge -------------------------------------------------------------


def _split_constraints(constraints1, constraints2):
    """(shared, unique1, unique2) by structural hash."""
    hashes2 = {hash(c) for c in constraints2}
    hashes1 = {hash(c) for c in constraints1}
    shared = [c for c in constraints1 if hash(c) in hashes2]
    unique1 = [c for c in constraints1 if hash(c) not in hashes2]
    unique2 = [c for c in constraints2 if hash(c) not in hashes1]
    return shared, unique1, unique2


def merge_states(state1: WorldState, state2: WorldState) -> None:
    """Merge state2 into state1 (in place)."""
    shared, unique1, unique2 = _split_constraints(
        state1.constraints, state2.constraints)
    condition1 = And(*unique1) if unique1 else None
    merged = Constraints(shared)
    if unique1 or unique2:
        disjunct1 = And(*unique1) if unique1 else None
        disjunct2 = And(*unique2) if unique2 else None
        if disjunct1 is not None and disjunct2 is not None:
            merged.append(Or(disjunct1, disjunct2))
        # one side empty => its disjunct is True => Or is True: drop it
    state1.constraints = merged

    if condition1 is None:
        # state1's path subsumes state2's: keep state1's data as-is
        state1.annotate(MergeAnnotation())
        return

    state1.balances = If(condition1, state1.balances, state2.balances)
    state1.starting_balances = If(
        condition1, state1.starting_balances, state2.starting_balances)
    for address, account2 in state2.accounts.items():
        account1 = state1.accounts[address]
        account1.set_balance_array(state1.balances)
        _merge_storage(account1.storage, account2.storage, condition1)
    for a1, a2 in zip(state1.annotations, state2.annotations):
        if isinstance(a1, MergeableStateAnnotation):
            a1.merge_annotation(a2)
    state1.annotate(MergeAnnotation())
    if state1.node is not None and state2.node is not None:
        state1.node.states += state2.node.states
        state1.node.flags |= state2.node.flags
        state1.node.constraints = state1.constraints


def _merge_storage(storage1, storage2, condition1) -> None:
    storage1._array = If(condition1, storage1._array, storage2._array)
    storage1._loaded_slots |= storage2._loaded_slots
    for key, value in storage2.printable_storage.items():
        if key in storage1.printable_storage:
            storage1.printable_storage[key] = If(
                condition1, storage1.printable_storage[key], value)
        else:
            storage1.printable_storage[key] = If(condition1, 0, value)


# -- the plugin ------------------------------------------------------------


class StateMergePlugin(LaserPlugin):
    name = "state-merge"

    def initialize(self, symbolic_vm) -> None:
        def stop_sym_trans_hook():
            open_states = symbolic_vm.open_states
            if len(open_states) <= 1:
                return
            before = len(open_states)
            symbolic_vm.open_states = self._merge_round(open_states)
            log.info("state merge: %d -> %d open states",
                     before, len(symbolic_vm.open_states))

        symbolic_vm.register_laser_hooks("stop_sym_trans",
                                         stop_sym_trans_hook)

    def _merge_round(self, states: List[WorldState]) -> List[WorldState]:
        """Repeated pairwise merging until a fixpoint."""
        current = list(states)
        while True:
            merged_any = False
            result: List[WorldState] = []
            consumed: Set[int] = set()
            for i, state in enumerate(current):
                if i in consumed:
                    continue
                if list(state.get_annotations(MergeAnnotation)):
                    result.append(state)
                    continue
                for j in range(i + 1, len(current)):
                    if j in consumed:
                        continue
                    other = current[j]
                    if (not list(other.get_annotations(MergeAnnotation))
                            and check_ws_merge_condition(state, other)):
                        merge_states(state, other)
                        consumed.add(j)
                        merged_any = True
                        break
                result.append(state)
            current = result
            if not merged_any:
                return current


class StateMergePluginBuilder(PluginBuilder):
    name = "state-merge"

    def __call__(self, *args, **kwargs):
        return StateMergePlugin()

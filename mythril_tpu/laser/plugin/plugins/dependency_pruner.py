"""Dependency pruner (reference laser/plugin/plugins/dependency_pruner.py:337).

Learns which storage slots each basic block's paths depend on during
transaction N-1. In transaction N, a path arriving at a JUMPDEST whose known
dependencies cannot alias any slot written by earlier transactions is
skipped — re-executing it cannot exhibit new behavior. Blocks containing
calls (or not yet learned) are never skipped."""

import logging
from typing import Dict, Set

from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder
from mythril_tpu.laser.plugin.signals import PluginSkipState
from mythril_tpu.laser.state.annotation import StateAnnotation

log = logging.getLogger(__name__)


def _slot_key(slot):
    raw = slot.raw if hasattr(slot, "raw") else slot
    if raw.is_const:
        return raw.value
    return "sym"  # symbolic slots conservatively alias everything


class DependencyAnnotation(StateAnnotation):
    """Per-path record of blocks visited and slots read on the path."""

    def __init__(self):
        self.path_blocks: Set[int] = set()
        self.storage_loaded: Set = set()

    def clone(self):
        dup = DependencyAnnotation()
        dup.path_blocks = set(self.path_blocks)
        dup.storage_loaded = set(self.storage_loaded)
        return dup


def get_dependency_annotation(state) -> DependencyAnnotation:
    annotations = state.get_annotations(DependencyAnnotation)
    if annotations:
        return annotations[0]
    annotation = DependencyAnnotation()
    state.annotate(annotation)
    return annotation


class DependencyPruner(LaserPlugin):
    def __init__(self):
        self.iteration = 0
        # block pc -> slot keys any path through the block has loaded
        self.block_dependencies: Dict[int, Set] = {}
        # blocks whose paths performed calls/creates (never skip those)
        self.blocks_with_calls: Set[int] = set()
        # slots written by any transaction so far
        self.all_writes: Set = set()
        self._learned_blocks: Set[int] = set()

    def initialize(self, symbolic_vm):
        self.__init__()

        def start_sym_trans_hook():
            self.iteration += 1

        def sstore_hook(global_state):
            self.all_writes.add(_slot_key(global_state.mstate.stack[-1]))

        def sload_hook(global_state):
            key = _slot_key(global_state.mstate.stack[-1])
            annotation = get_dependency_annotation(global_state)
            annotation.storage_loaded.add(key)
            # attribute the read to every block on the current path: any of
            # them re-executed leads here again
            for block in annotation.path_blocks:
                self.block_dependencies.setdefault(block, set()).add(key)

        def call_hook(global_state):
            annotation = get_dependency_annotation(global_state)
            for block in annotation.path_blocks:
                self.blocks_with_calls.add(block)

        def jumpdest_hook(global_state):
            block = global_state.mstate.pc
            annotation = get_dependency_annotation(global_state)
            annotation.path_blocks.add(block)
            if self.iteration < 2:
                self._learned_blocks.add(block)
                return
            if block not in self._learned_blocks:
                self._learned_blocks.add(block)
                return  # never seen: must explore
            if block in self.blocks_with_calls:
                return
            deps = self.block_dependencies.get(block, set())
            if "sym" in deps or "sym" in self.all_writes:
                return
            if deps & self.all_writes:
                return
            # the block's storage dependencies were not touched by any
            # previous transaction: the paths from here are redundant
            log.debug(
                "dependency pruning block %d in tx %d", block, self.iteration
            )
            raise PluginSkipState

        symbolic_vm.register_laser_hooks(
            "start_sym_trans", start_sym_trans_hook
        )
        symbolic_vm.register_hooks(
            "pre",
            {
                "SSTORE": [sstore_hook],
                "SLOAD": [sload_hook],
                "CALL": [call_hook],
                "STATICCALL": [call_hook],
                "DELEGATECALL": [call_hook],
                "CALLCODE": [call_hook],
                "CREATE": [call_hook],
                "CREATE2": [call_hook],
                "SELFDESTRUCT": [call_hook],
            },
        )
        symbolic_vm.register_hooks("pre", {"JUMPDEST": [jumpdest_hook]})


class DependencyPrunerBuilder(PluginBuilder):
    name = "dependency_pruner"

    def __call__(self, *args, **kwargs):
        return DependencyPruner()

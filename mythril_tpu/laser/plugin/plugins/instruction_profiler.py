"""Instruction profiler — per-opcode wall-time min/avg/max
(reference laser/plugin/plugins/instruction_profiler.py:115)."""

import logging
import time
from collections import defaultdict

from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder

log = logging.getLogger(__name__)


class InstructionProfiler(LaserPlugin):
    def __init__(self):
        self.records = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
        # single pending slot: the engine is single-threaded and post-hooks
        # fire immediately after the instruction (possibly on successor
        # objects, so keying by state identity would leak/misattribute)
        self._pending = None

    def initialize(self, symbolic_vm):
        def pre_hook(global_state):
            instr = global_state.instruction
            if instr is not None:
                self._pending = (time.monotonic(), instr.opcode)

        def post_hook(global_state):
            mark = self._pending
            self._pending = None
            if mark is None:
                return
            started, opcode = mark
            duration = time.monotonic() - started
            record = self.records[opcode]
            record[0] += 1
            record[1] += duration
            record[2] = min(record[2], duration)
            record[3] = max(record[3], duration)

        def stop_hook():
            if not self.records:
                return
            lines = []
            total = 0.0
            for opcode, (count, total_op, mn, mx) in sorted(self.records.items()):
                total += total_op
                lines.append(
                    f"[{opcode:14}] count: {count:6d}, "
                    f"avg: {total_op / count * 1e6:8.1f}us, "
                    f"min: {mn * 1e6:8.1f}us, max: {mx * 1e6:8.1f}us"
                )
            log.info(
                "Instruction profile (total %.2fs):\n%s", total, "\n".join(lines)
            )

        # frontier contract: purely observational per-instruction timing.
        # Batched runs skip both hooks as a PAIR (firing only the pre
        # side would leak a pending slot into the next instruction); the
        # profile then covers exactly the per-state fallback path, which
        # is also what the interp_opcode_wall_top histogram reports.
        pre_hook.frontier_transparent = True
        post_hook.frontier_transparent = True
        symbolic_vm.register_instr_hooks("pre", "", pre_hook)
        symbolic_vm.register_instr_hooks("post", "", post_hook)
        symbolic_vm.register_laser_hooks("stop_sym_exec", stop_hook)


class InstructionProfilerBuilder(PluginBuilder):
    name = "instruction_profiler"

    def __call__(self, *args, **kwargs):
        return InstructionProfiler()

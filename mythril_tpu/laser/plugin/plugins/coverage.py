"""Coverage plugin — per-bytecode pc bitmap + per-tx new-coverage logging
(reference laser/plugin/plugins/coverage/coverage_plugin.py:116)."""

import logging
from typing import Dict, List, Tuple

from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder

log = logging.getLogger(__name__)


class InstructionCoveragePlugin(LaserPlugin):
    def __init__(self):
        self.coverage: Dict[str, Tuple[int, List[bool]]] = {}
        self.initial_coverage = 0
        self.tx_id = 0

    def initialize(self, symbolic_vm):
        self.coverage = {}
        self.tx_id = 0

        def execute_state_hook(global_state):
            # keyed by the precomputed bytecode hash: the hook runs for
            # every instruction, hex-encoding the bytecode here would be
            # O(code size) in the engine's hottest loop
            code = global_state.environment.code.bytecode_hash
            if code not in self.coverage:
                number_of_instrs = len(
                    global_state.environment.code.instruction_list
                )
                self.coverage[code] = (
                    number_of_instrs,
                    [False] * number_of_instrs,
                )
            index = global_state.environment.code.index_of_address(
                global_state.mstate.pc
            )
            if index is not None:
                self.coverage[code][1][index] = True

        def stop_sym_exec_hook():
            for code, (total, seen) in self.coverage.items():
                if total == 0:
                    continue
                covered = sum(seen)
                log.info(
                    "achieved %.2f%% coverage for code hash: %s...",
                    covered / total * 100,
                    code[:5].hex(),
                )

        def start_sym_trans_hook():
            self.tx_id += 1
            self.initial_coverage = self._total_covered()

        def stop_sym_trans_hook():
            end_coverage = self._total_covered()
            log.info(
                "number of new instructions covered in tx %d: %d",
                self.tx_id,
                end_coverage - self.initial_coverage,
            )

        def frontier_batch_hook(states, run):
            # batched straight-line runs skip the per-instruction hook;
            # every pc of the run executed for the completed states, so
            # marking the whole run keeps the bitmap exact (the run-start
            # pc was already marked by the once-per-run firing)
            code_obj = states[0].environment.code
            entry = self.coverage.get(code_obj.bytecode_hash)
            if entry is None:
                return
            for pc in run.op_pcs:
                index = code_obj.index_of_address(pc)
                if index is not None:
                    entry[1][index] = True

        # frontier contract (laser/frontier/stepper.py): firing once per
        # batched run is fine — the batch companion repaints the interior
        execute_state_hook.frontier_once_ok = True
        execute_state_hook.frontier_batch = frontier_batch_hook

        symbolic_vm.register_laser_hooks("execute_state", execute_state_hook)
        symbolic_vm.register_laser_hooks("stop_sym_exec", stop_sym_exec_hook)
        symbolic_vm.register_laser_hooks("start_sym_trans", start_sym_trans_hook)
        symbolic_vm.register_laser_hooks("stop_sym_trans", stop_sym_trans_hook)

    def _total_covered(self) -> int:
        return sum(sum(seen) for _total, seen in self.coverage.values())


class CoverageStrategy:
    """Strategy wrapper preferring states whose pc is not yet covered
    (reference plugin/plugins/coverage/coverage_strategy.py:6)."""

    def __init__(self, super_strategy, coverage_plugin:
                 InstructionCoveragePlugin):
        self.super_strategy = super_strategy
        self.coverage_plugin = coverage_plugin
        self.work_list = super_strategy.work_list
        self.max_depth = super_strategy.max_depth

    def __iter__(self):
        return self

    def run_check(self):
        return self.super_strategy.run_check()

    def _is_covered(self, state) -> bool:
        code = state.environment.code
        entry = self.coverage_plugin.coverage.get(code.bytecode_hash)
        if entry is None:
            return False
        index = code.index_of_address(state.mstate.pc)
        return index is not None and entry[1][index]

    def __next__(self):
        for i, state in enumerate(self.work_list):
            if not self._is_covered(state):
                if state.mstate.depth < self.max_depth:
                    del self.work_list[i]
                    return state
        return next(self.super_strategy)


class CoveragePluginBuilder(PluginBuilder):
    name = "coverage"

    def __call__(self, *args, **kwargs):
        return InstructionCoveragePlugin()

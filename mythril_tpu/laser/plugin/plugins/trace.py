"""Trace finder plugin — records the (pc, tx_id) stream of each transaction
(reference laser/plugin/plugins/trace.py:49). The concrete pass of concolic
mode replays txs with this plugin on, and the symbolic flip pass then
follows the recorded trace (concolic/runner.py)."""

from typing import List, Tuple

from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder


class TraceFinder(LaserPlugin):
    name = "trace-finder"

    def __init__(self):
        self.tx_trace: List[List[Tuple[int, int]]] = []

    def initialize(self, symbolic_vm) -> None:
        self.tx_trace = []

        def start_exec_hook():
            # one exec() call == one transaction in the concolic replay flow
            self.tx_trace.append([])

        def execute_state_hook(global_state):
            self.tx_trace[-1].append(
                (global_state.mstate.pc, global_state.current_transaction.id)
            )

        symbolic_vm.register_laser_hooks("start_exec", start_exec_hook)
        symbolic_vm.register_laser_hooks("execute_state", execute_state_hook)


class TraceFinderBuilder(PluginBuilder):
    name = "trace-finder"

    def __call__(self, *args, **kwargs):
        return TraceFinder()

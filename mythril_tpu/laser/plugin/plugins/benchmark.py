"""Benchmark plugin — coverage-over-time and executed-instruction counts
(reference laser/plugin/plugins/benchmark.py:96; off by default).

Records a (wall_seconds, covered_instructions) time series plus the total
executed-instruction count; writes `<name>.json` at stop, and a PNG plot
when matplotlib is importable (it is optional — the data file is the
contract)."""

import json
import logging
import time

from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder

log = logging.getLogger(__name__)


class BenchmarkPlugin(LaserPlugin):
    name = "benchmark"

    def __init__(self, name: str = "benchmark"):
        self.out_name = name
        self.begin = None
        self.coverage_series = []  # (seconds, unique pcs covered)
        self.instructions_executed = 0
        self._covered = set()

    def initialize(self, symbolic_vm) -> None:
        self.begin = time.monotonic()
        self.coverage_series = []
        self.instructions_executed = 0
        self._covered = set()

        def execute_state_hook(global_state):
            self.instructions_executed += 1
            key = (global_state.environment.code.bytecode_hash,
                   global_state.mstate.pc)
            if key not in self._covered:
                self._covered.add(key)
                self.coverage_series.append(
                    (time.monotonic() - self.begin, len(self._covered))
                )

        def stop_sym_exec_hook():
            self._write_output()

        symbolic_vm.register_laser_hooks("execute_state", execute_state_hook)
        symbolic_vm.register_laser_hooks("stop_sym_exec", stop_sym_exec_hook)

    def _write_output(self) -> None:
        data = {
            "instructions_executed": self.instructions_executed,
            "unique_instructions_covered": len(self._covered),
            "coverage_over_time": self.coverage_series,
            "total_seconds": time.monotonic() - self.begin,
        }
        path = f"{self.out_name}.json"
        try:
            with open(path, "w") as handle:
                json.dump(data, handle)
        except OSError:
            log.warning("could not write %s", path)
            return
        self._maybe_plot()

    def _maybe_plot(self) -> None:
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
        except ImportError:
            return
        if not self.coverage_series:
            return
        xs, ys = zip(*self.coverage_series)
        plt.figure()
        plt.plot(xs, ys)
        plt.xlabel("seconds")
        plt.ylabel("instructions covered")
        plt.savefig(f"{self.out_name}.png")
        plt.close()


class BenchmarkPluginBuilder(PluginBuilder):
    name = "benchmark"

    def __call__(self, *args, **kwargs):
        return BenchmarkPlugin(**kwargs)

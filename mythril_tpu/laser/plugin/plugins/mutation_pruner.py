"""Mutation pruner — drop world states whose transaction did not mutate
anything and carried no value (reference laser/plugin/plugins/
mutation_pruner.py:89): such "clean" suffixes cannot enable new behavior."""

import logging

from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder
from mythril_tpu.laser.plugin.signals import PluginSkipWorldState
from mythril_tpu.laser.state.annotation import StateAnnotation
from mythril_tpu.laser.transaction.models import ContractCreationTransaction
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)


class MutationAnnotation(StateAnnotation):
    """Present iff the path performed a state mutation (SSTORE/CALL)."""

    @property
    def persist_over_calls(self) -> bool:
        return True

    def clone(self):
        return self


class MutationPruner(LaserPlugin):
    def initialize(self, symbolic_vm):
        def on_sstore(global_state):
            if not global_state.get_annotations(MutationAnnotation):
                global_state.annotate(MutationAnnotation())

        symbolic_vm.register_hooks(
            "pre",
            {
                "SSTORE": [on_sstore],
                "CALL": [on_sstore],
                "STATICCALL": [on_sstore],
                "CREATE": [on_sstore],
                "CREATE2": [on_sstore],
                "SELFDESTRUCT": [on_sstore],
            },
        )

        def add_world_state_hook(global_state):
            if isinstance(
                global_state.current_transaction, ContractCreationTransaction
            ):
                return
            if global_state.get_annotations(MutationAnnotation):
                return
            # no mutation: world state only matters if value could be forced
            call_value = global_state.current_transaction.call_value
            if call_value is None or not call_value.symbolic:
                if call_value is not None and call_value.concrete_value != 0:
                    return
                raise PluginSkipWorldState
            try:
                get_model(
                    global_state.world_state.constraints.get_all_constraints()
                    + [call_value == 0]
                )
                # value can be zero: the tx is a no-op, drop the world state
                raise PluginSkipWorldState
            except UnsatError:
                return
            except SolverTimeOutException:
                # undecided: keep the world state (conservative)
                return

        symbolic_vm.register_laser_hooks(
            "add_world_state", add_world_state_hook
        )


class MutationPrunerBuilder(PluginBuilder):
    name = "mutation_pruner"

    def __call__(self, *args, **kwargs):
        return MutationPruner()

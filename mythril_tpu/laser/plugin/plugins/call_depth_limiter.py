"""Call-depth limiter plugin (reference laser/plugin/plugins/
call_depth_limiter.py:30). The engine also enforces args.call_depth_limit
directly in call_ops; this plugin makes the limit strategy-visible by
skipping states that exceed it."""

from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder
from mythril_tpu.laser.plugin.signals import PluginSkipState


class CallDepthLimit(LaserPlugin):
    def __init__(self, call_depth_limit: int = 3):
        self.call_depth_limit = call_depth_limit

    def initialize(self, symbolic_vm):
        def execute_state_hook(global_state):
            inner = sum(
                1 for _tx, snap in global_state.transaction_stack
                if snap is not None
            )
            if inner > self.call_depth_limit:
                raise PluginSkipState

        # frontier contract: the depth check reads only the transaction
        # stack, which straight-line runs never change — once per batched
        # run is equivalent to once per instruction
        execute_state_hook.frontier_once_ok = True
        symbolic_vm.register_laser_hooks("execute_state", execute_state_hook)


class CallDepthLimitBuilder(PluginBuilder):
    name = "call_depth_limiter"

    def __call__(self, *args, **kwargs):
        return CallDepthLimit(**kwargs)

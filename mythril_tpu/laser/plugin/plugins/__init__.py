from mythril_tpu.laser.plugin.plugins.coverage import (  # noqa: F401
    CoveragePluginBuilder,
)
from mythril_tpu.laser.plugin.plugins.mutation_pruner import (  # noqa: F401
    MutationPrunerBuilder,
)
from mythril_tpu.laser.plugin.plugins.instruction_profiler import (  # noqa: F401
    InstructionProfilerBuilder,
)
from mythril_tpu.laser.plugin.plugins.call_depth_limiter import (  # noqa: F401
    CallDepthLimitBuilder,
)
from mythril_tpu.laser.plugin.plugins.dependency_pruner import (  # noqa: F401
    DependencyPrunerBuilder,
)
from mythril_tpu.laser.plugin.plugins.state_merge import (  # noqa: F401
    StateMergePluginBuilder,
)
from mythril_tpu.laser.plugin.plugins.trace import (  # noqa: F401
    TraceFinderBuilder,
)

"""Symbolic summaries — record a transaction's parametric effect once,
replay it on later transactions by substitution
(reference laser/plugin/plugins/summary/, 629 LoC; off by default,
`--enable-summaries`).

Mechanism:
* entry (pc==0 of an outermost symbolic message call) — storage arrays,
  the balance array, and the environment symbols (sender/origin/
  callvalue/gasprice/calldata) are swapped for fresh "summary" symbols,
  so the transaction executes parametrically;
* exit (transaction_end) — the accumulated storage/balance expressions,
  the constraints appended during the tx, and any IssueAnnotations are
  recorded as a SymbolicSummary keyed by (entry pc, code); then every
  summary symbol is substituted back to the caller's actual expressions
  so normal exploration continues unchanged;
* apply (a later tx reaches the same entry with summaries available) —
  each summary's effects are substituted into the current world state
  (actual storage/balances in, fresh per-application tx symbols for the
  environment) and pushed as open states; recorded issues are re-solved
  in the new context; the normal execution of the tx is skipped
  (PluginSkipState).

The term-DAG substitution (smt/terms.py substitute) is the engine that
makes replay cheap: no re-execution, just expression rewriting.
"""

import logging
from copy import copy
from typing import List, Optional, Set, Tuple

from mythril_tpu.analysis.issue_annotation import IssueAnnotation
from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder
from mythril_tpu.laser.plugin.plugins.mutation_pruner import MutationAnnotation
from mythril_tpu.laser.plugin.signals import PluginSkipState
from mythril_tpu.laser.state.annotation import StateAnnotation
from mythril_tpu.laser.state.calldata import SymbolicCalldata
from mythril_tpu.laser.state.environment import Environment
from mythril_tpu.laser.transaction.models import (
    ContractCreationTransaction,
    MessageCallTransaction,
)
from mythril_tpu.smt import Array, symbol_factory
from mythril_tpu.smt import terms
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError
from mythril_tpu.support.args import args

log = logging.getLogger(__name__)


class SummaryTrackingAnnotation(StateAnnotation):
    """Tracks one in-progress summary recording."""

    def __init__(self, entry_pc, storage_pairs, environment_pair,
                 balances_pair, code, constraint_mark):
        self.entry_pc = entry_pc
        self.storage_pairs = storage_pairs  # (addr, actual, summary) wrappers
        self.environment_pair = environment_pair  # (original, summary)
        self.balances_pair = balances_pair  # (original, summary)
        self.code = code
        self.constraint_mark = constraint_mark

    @property
    def persist_over_calls(self) -> bool:
        return True

    def clone(self):
        # immutable record (raw terms + entry references): share across
        # forks instead of deep-copying entire environments per fork
        return self


class SymbolicSummary:
    __slots__ = ("entry", "code", "storage_effect", "balance_effect",
                 "conditions", "issues", "revert", "symbols")

    def __init__(self, entry, code, storage_effect, balance_effect,
                 conditions, issues, revert, symbols):
        self.entry = entry
        self.code = code
        self.storage_effect = storage_effect  # [(addr, raw array term)]
        self.balance_effect = balance_effect  # raw array term
        self.conditions = conditions          # [raw bool terms]
        self.issues = issues                  # [IssueAnnotation]
        self.revert = revert
        # the summary symbols to re-bind on application:
        # {"sender": term, "origin": ..., "callvalue": ..., "gasprice": ...,
        #  "calldata": term, "calldatasize": term,
        #  "storage": {addr: term}, "balances": term}
        self.symbols = symbols


def _raw(expression):
    return expression.raw if hasattr(expression, "raw") else expression


class SymbolicSummaryPlugin(LaserPlugin):
    name = "summaries"

    def __init__(self):
        self.summaries: List[SymbolicSummary] = []
        self.issue_cache: Set[Tuple[str, int, bytes]] = set()
        self._apply_counter = 0
        args.use_issue_annotations = True

    def initialize(self, symbolic_vm) -> None:
        self.laser = symbolic_vm

        def execute_state_hook(global_state):
            if (global_state.mstate.pc != 0
                    or len(global_state.transaction_stack) != 1):
                return
            transaction = global_state.current_transaction
            if isinstance(transaction, ContractCreationTransaction):
                return
            if not isinstance(global_state.environment.calldata,
                              SymbolicCalldata):
                return
            if list(global_state.get_annotations(SummaryTrackingAnnotation)):
                return
            self._apply_summaries(global_state)
            self._summary_entry(global_state)

        def transaction_end_hook(global_state, transaction,
                                 return_global_state, revert):
            if return_global_state is not None:
                return  # inner frame
            annotations = list(
                global_state.get_annotations(SummaryTrackingAnnotation))
            if not annotations:
                return
            # reverted paths are discarded by the engine; only record them
            # when an inner frame already proved an issue (reference
            # core.py transaction_end gate) — promoting potential issues
            # on a rolled-back path would be a false-positive source
            if revert and not list(
                    global_state.get_annotations(IssueAnnotation)):
                return
            from mythril_tpu.analysis.potential_issues import (
                check_potential_issues,
            )

            # promote potential issues NOW so IssueAnnotations are attached
            # while the state is still expressed over summary symbols
            if not revert:
                check_potential_issues(global_state)
            self._summary_exit(global_state, annotations[0], revert)

        def stop_sym_exec_hook():
            log.info("generated %d symbolic summaries", len(self.summaries))

        symbolic_vm.register_laser_hooks("execute_state", execute_state_hook)
        symbolic_vm.register_laser_hooks("transaction_end",
                                        transaction_end_hook)
        symbolic_vm.register_laser_hooks("stop_sym_exec", stop_sym_exec_hook)

    # -- recording ---------------------------------------------------------

    def _summary_entry(self, global_state) -> None:
        world_state = global_state.world_state
        n = len(world_state.transaction_sequence)
        # capture RAW terms (immutable), not wrappers: array wrappers are
        # mutated in place by later stores on the shared state object
        storage_pairs = []
        for addr, account in world_state.accounts.items():
            actual_raw = _raw(account.storage._array)
            fresh = Array(f"sum!storage!{addr}!{n}", 256, 256)
            fresh_raw = _raw(fresh)
            account.storage._array = fresh
            storage_pairs.append((addr, actual_raw, fresh_raw))
        prev_balances_raw = _raw(world_state.balances)
        fresh_balances = Array(f"sum!balance!{n}", 256, 256)
        fresh_balances_raw = _raw(fresh_balances)
        world_state.balances = fresh_balances
        for account in world_state.accounts.values():
            account.set_balance_array(fresh_balances)

        prev_env = global_state.environment
        summary_env = Environment(
            active_account=prev_env.active_account,
            sender=symbol_factory.BitVecSym(f"sum!sender!{n}", 256),
            origin=symbol_factory.BitVecSym(f"sum!origin!{n}", 256),
            calldata=SymbolicCalldata(f"sum!{n}"),
            gasprice=symbol_factory.BitVecSym(f"sum!gasprice!{n}", 256),
            callvalue=symbol_factory.BitVecSym(f"sum!callvalue!{n}", 256),
            static=prev_env.static,
            code=prev_env.code,
            basefee=prev_env.basefee,
        )
        summary_env.active_function_name = prev_env.active_function_name
        global_state.environment = summary_env

        global_state.annotate(SummaryTrackingAnnotation(
            entry_pc=global_state.mstate.pc,
            storage_pairs=storage_pairs,
            environment_pair=(prev_env, summary_env),
            balances_pair=(prev_balances_raw, fresh_balances_raw),
            code=prev_env.code.bytecode,
            constraint_mark=len(world_state.constraints),
        ))

    def _summary_exit(self, global_state, annotation, revert) -> None:
        global_state.annotations.remove(annotation)
        recorded = self._record(global_state, annotation, revert)

        # restore: summary symbols -> the caller's actual expressions
        mapping = {}
        for addr, actual_raw, fresh_raw in annotation.storage_pairs:
            mapping[fresh_raw] = actual_raw
        original_balances_raw, summary_balances_raw = annotation.balances_pair
        mapping[summary_balances_raw] = original_balances_raw
        env_original, env_summary = annotation.environment_pair
        for field in ("sender", "origin", "callvalue", "gasprice"):
            mapping[_raw(getattr(env_summary, field))] = \
                _raw(getattr(env_original, field))
        mapping[_raw(env_summary.calldata._array)] = \
            _raw(self._calldata_array(env_original.calldata))
        mapping[_raw(env_summary.calldata.calldatasize)] = \
            _raw(env_original.calldata.calldatasize)

        self._substitute_state(global_state, mapping)
        global_state.environment = env_original

        # report this transaction's own findings in the ACTUAL (restored)
        # context — the recorded conditions are parametric; solving them
        # against the caller's real storage/balances avoids the
        # unconstrained-state false positives direct reporting would give
        if recorded is not None and recorded.issues:
            self._check_issues(global_state, recorded, mapping)

    def _record(self, global_state, annotation,
                revert) -> Optional[SymbolicSummary]:
        has_mutation = bool(
            list(global_state.get_annotations(MutationAnnotation)))
        issues = [copy(a) for a
                  in global_state.get_annotations(IssueAnnotation)]
        if not has_mutation and not issues:
            return None
        world_state = global_state.world_state
        env_summary = annotation.environment_pair[1]
        symbols = {
            "sender": _raw(env_summary.sender),
            "origin": _raw(env_summary.origin),
            "callvalue": _raw(env_summary.callvalue),
            "gasprice": _raw(env_summary.gasprice),
            "calldata": _raw(env_summary.calldata._array),
            "calldatasize": _raw(env_summary.calldata.calldatasize),
            "storage": {addr: fresh_raw
                        for addr, _a, fresh_raw in annotation.storage_pairs},
            "balances": annotation.balances_pair[1],
        }
        summary = SymbolicSummary(
            entry=annotation.entry_pc,
            code=annotation.code,
            storage_effect=[
                (addr, _raw(account.storage._array))
                for addr, account in world_state.accounts.items()
            ],
            balance_effect=_raw(world_state.balances),
            conditions=[
                _raw(c) for c in
                list(world_state.constraints)[annotation.constraint_mark:]
            ],
            issues=issues,
            revert=revert,
            symbols=symbols,
        )
        self.summaries.append(summary)
        return summary

    # -- replay ------------------------------------------------------------

    def _apply_summaries(self, global_state) -> None:
        entry = global_state.mstate.pc
        code = global_state.environment.code.bytecode
        matching = [
            s for s in self.summaries
            if s.entry == entry and s.code == code and not s.revert
            and s.storage_effect
        ]
        if not matching:
            return
        applied = 0
        for summary in matching:
            applied += self._apply_one(global_state, summary)
        if applied:
            raise PluginSkipState
        log.debug("no summary applied at pc %d; executing normally", entry)

    def _application_mapping(self, global_state, summary, tag: str):
        """summary symbols -> current context (actual storage/balances,
        fresh per-application environment symbols)."""
        world_state = global_state.world_state
        from mythril_tpu.smt import Bool  # noqa: F401 (doc import)

        mapping = {}
        for addr, sum_storage in summary.symbols["storage"].items():
            account = world_state.accounts.get(addr)
            if account is None:
                return None  # summary mentions an account we don't have
            mapping[sum_storage] = _raw(account.storage._array)
        mapping[summary.symbols["balances"]] = _raw(world_state.balances)
        for field, size in (("sender", 256), ("origin", 256),
                            ("callvalue", 256), ("gasprice", 256)):
            mapping[summary.symbols[field]] = _raw(
                symbol_factory.BitVecSym(f"sumapp!{field}!{tag}", size))
        fresh_calldata = SymbolicCalldata(f"sumapp!{tag}")
        mapping[summary.symbols["calldata"]] = _raw(fresh_calldata._array)
        mapping[summary.symbols["calldatasize"]] = _raw(
            fresh_calldata.calldatasize)
        return mapping, fresh_calldata

    def _apply_one(self, global_state, summary) -> bool:
        self._apply_counter += 1
        tag = str(self._apply_counter)
        prepared = self._application_mapping(global_state, summary, tag)
        if prepared is None:
            return False
        mapping, fresh_calldata = prepared
        new_state = global_state.clone()
        world_state = new_state.world_state

        roots = ([term for _addr, term in summary.storage_effect]
                 + [summary.balance_effect] + summary.conditions)
        substituted = terms.substitute(roots, mapping)
        storage_terms = substituted[: len(summary.storage_effect)]
        balance_term = substituted[len(summary.storage_effect)]
        condition_terms = substituted[len(summary.storage_effect) + 1:]

        from mythril_tpu.smt.array_expr import BaseArray
        from mythril_tpu.smt.bool_expr import Bool

        for (addr, _), new_term in zip(summary.storage_effect,
                                       storage_terms):
            account = world_state.accounts.get(addr)
            if account is None:
                continue
            wrapper = BaseArray.__new__(type(account.storage._array))
            wrapper.raw = new_term
            wrapper.annotations = set()
            account.storage._array = wrapper
        balances = BaseArray.__new__(type(world_state.balances))
        balances.raw = balance_term
        balances.annotations = set()
        world_state.balances = balances
        for account in world_state.accounts.values():
            account.set_balance_array(balances)
        for term in condition_terms:
            world_state.constraints.append(Bool(term, set()))

        # synthesize the tx record so exploit concretization still works
        transaction = MessageCallTransaction(
            world_state=world_state,
            callee_account=new_state.environment.active_account,
            caller=symbol_factory.BitVecSym(f"sumapp!sender!{tag}", 256),
            call_data=fresh_calldata,
            origin=symbol_factory.BitVecSym(f"sumapp!origin!{tag}", 256),
            call_value=symbol_factory.BitVecSym(f"sumapp!callvalue!{tag}",
                                                256),
        )
        world_state.transaction_sequence.append(transaction)

        self._check_issues(new_state, summary, mapping)
        self.laser._add_world_state(new_state)
        return True

    def _check_issues(self, new_state, summary, mapping) -> None:
        from mythril_tpu.analysis.solver import get_transaction_sequence
        from mythril_tpu.laser.state.constraints import Constraints
        from mythril_tpu.smt.bool_expr import Bool

        for issue_annotation in summary.issues:
            key = (issue_annotation.detector.swc_id,
                   issue_annotation.issue.address,
                   summary.code)
            if key in self.issue_cache:
                continue
            condition_raws = terms.substitute(
                [_raw(c) for c in issue_annotation.conditions], mapping)
            constraints = Constraints(
                list(new_state.world_state.constraints))
            for raw in condition_raws:
                constraints.append(Bool(raw, set()))
            try:
                tx_sequence = get_transaction_sequence(
                    new_state, constraints)
            except (UnsatError, SolverTimeOutException):
                continue
            new_issue = copy(issue_annotation.issue)
            new_issue.transaction_sequence = tx_sequence
            issue_annotation.detector.issues.append(new_issue)
            self.issue_cache.add(key)

    # -- restore helpers ---------------------------------------------------

    @staticmethod
    def _calldata_array(calldata):
        if isinstance(calldata, SymbolicCalldata):
            return calldata._array
        # concrete calldata: materialize as a constant array term
        from mythril_tpu.smt import K

        arr = K(256, 8, 0)
        for i, byte in enumerate(getattr(calldata, "concrete_bytes", [])):
            arr[i] = byte
        return arr

    def _substitute_state(self, global_state, mapping) -> None:
        world_state = global_state.world_state
        from mythril_tpu.smt.array_expr import BaseArray
        from mythril_tpu.smt.bool_expr import Bool

        constraint_raws = [_raw(c) for c in world_state.constraints]
        storage_raws = [_raw(a.storage._array)
                        for a in world_state.accounts.values()]
        balance_raw = _raw(world_state.balances)
        substituted = terms.substitute(
            constraint_raws + storage_raws + [balance_raw], mapping)
        n_constraints = len(constraint_raws)
        from mythril_tpu.laser.state.constraints import Constraints

        new_constraints = Constraints()
        for raw in substituted[:n_constraints]:
            new_constraints.append(Bool(raw, set()))
        world_state.constraints = new_constraints
        for account, new_term in zip(world_state.accounts.values(),
                                     substituted[n_constraints:-1]):
            wrapper = BaseArray.__new__(type(account.storage._array))
            wrapper.raw = new_term
            wrapper.annotations = set()
            account.storage._array = wrapper
        balances = BaseArray.__new__(type(world_state.balances))
        balances.raw = substituted[-1]
        balances.annotations = set()
        world_state.balances = balances
        for account in world_state.accounts.values():
            account.set_balance_array(balances)


class SymbolicSummaryPluginBuilder(PluginBuilder):
    name = "summaries"

    def __call__(self, *args, **kwargs):
        return SymbolicSummaryPlugin()

"""Coverage metrics plugin — instruction + branch coverage time series
written to data.json (reference laser/plugin/plugins/coverage_metrics/,
203 LoC, MythX format)."""

import json
import logging
import time
from typing import Dict

from mythril_tpu.laser.plugin.interface import LaserPlugin, PluginBuilder

log = logging.getLogger(__name__)


class CoverageMetricsPlugin(LaserPlugin):
    name = "coverage-metrics"

    def __init__(self, output_path: str = "data.json"):
        self.output_path = output_path
        self.begin = None
        # bytecode hash -> {"instructions": set pcs, "branches": set (pc, taken)}
        self.per_code: Dict = {}
        self.time_series = []

    def initialize(self, symbolic_vm) -> None:
        self.begin = time.monotonic()
        self.per_code = {}
        self.time_series = []

        def execute_state_hook(global_state):
            code = global_state.environment.code
            entry = self.per_code.setdefault(
                code.bytecode_hash,
                {"total": len(code.instruction_list),
                 "instructions": set(), "branches": set()},
            )
            entry["instructions"].add(global_state.mstate.pc)

        def jumpi_post_hook(global_state):
            # a successor of JUMPI: record which side was reached
            code = global_state.environment.code
            entry = self.per_code.get(code.bytecode_hash)
            if entry is not None:
                entry["branches"].add(global_state.mstate.pc)

        def stop_sym_trans_hook():
            self.time_series.append(self._snapshot())

        def stop_sym_exec_hook():
            self.time_series.append(self._snapshot())
            self._write()

        symbolic_vm.register_laser_hooks("execute_state", execute_state_hook)
        symbolic_vm.register_instr_hooks("post", "JUMPI", jumpi_post_hook)
        symbolic_vm.register_laser_hooks("stop_sym_trans",
                                         stop_sym_trans_hook)
        symbolic_vm.register_laser_hooks("stop_sym_exec", stop_sym_exec_hook)

    def _snapshot(self) -> dict:
        per_code = {}
        for code_hash, entry in self.per_code.items():
            total = entry["total"] or 1
            per_code[code_hash.hex()] = {
                "instruction_coverage": len(entry["instructions"]) / total,
                "branches_covered": len(entry["branches"]),
            }
        return {
            "seconds": time.monotonic() - self.begin,
            "coverage": per_code,
        }

    def _write(self) -> None:
        try:
            with open(self.output_path, "w") as handle:
                json.dump({"time_series": self.time_series}, handle)
        except OSError:
            log.warning("could not write %s", self.output_path)


class CoverageMetricsPluginBuilder(PluginBuilder):
    name = "coverage-metrics"

    def __call__(self, *args, **kwargs):
        return CoverageMetricsPlugin(**kwargs)

"""On-chain access: minimal JSON-RPC client
(reference mythril/ethereum/interface/rpc/)."""

"""Minimal Ethereum JSON-RPC client
(reference mythril/ethereum/interface/rpc/client.py ~500 LoC; only the
calls the analyzer actually issues: eth_getCode, eth_getStorageAt,
eth_getBalance, plus Infura-per-network convenience).

stdlib urllib only — no external HTTP dependency. Tests mock at the
`_call` boundary exactly as the reference's tests mock at the JSON-RPC
client level (reference tests/rpc_test.py).
"""

import json
import urllib.request
from typing import Optional


class RpcError(Exception):
    pass


INFURA_NETWORKS = {
    "mainnet": "mainnet.infura.io",
    "goerli": "goerli.infura.io",
    "sepolia": "sepolia.infura.io",
}


class EthJsonRpc:
    def __init__(self, host: str = "localhost", port: Optional[int] = 8545,
                 tls: bool = False):
        self.host = host
        self.port = port
        self.tls = tls
        self._id = 0

    @classmethod
    def from_cli(cls, rpc: Optional[str], rpctls: bool = False,
                 infura_id: Optional[str] = None) -> "EthJsonRpc":
        """Parse `--rpc host:port`, `--rpc infura-<net>`, or default."""
        if rpc in (None, "", "ganache"):
            return cls("localhost", 8545, rpctls)
        if rpc.startswith("infura-"):
            network = rpc[len("infura-"):]
            host = INFURA_NETWORKS.get(network)
            if host is None:
                raise RpcError(f"unknown infura network {network!r}")
            suffix = f"/v3/{infura_id}" if infura_id else ""
            return cls(host + suffix, None, True)
        host, _, port = rpc.partition(":")
        return cls(host, int(port) if port else 8545, rpctls)

    @property
    def url(self) -> str:
        scheme = "https" if self.tls else "http"
        authority = self.host if self.port is None else \
            f"{self.host}:{self.port}"
        return f"{scheme}://{authority}"

    def _call(self, method: str, params: list):
        self._id += 1
        payload = json.dumps({
            "jsonrpc": "2.0", "id": self._id,
            "method": method, "params": params,
        }).encode()
        request = urllib.request.Request(
            self.url, data=payload,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(request, timeout=10) as response:
                body = json.loads(response.read())
        except OSError as error:
            raise RpcError(f"rpc transport error: {error}")
        if "error" in body:
            raise RpcError(str(body["error"]))
        return body.get("result")

    # -- the three calls the engine needs ---------------------------------

    def eth_getCode(self, address: str, block: str = "latest") -> str:
        return self._call("eth_getCode", [address, block])

    def eth_getStorageAt(self, address: str, position,
                         block: str = "latest") -> str:
        if isinstance(position, int):
            position = hex(position)
        return self._call("eth_getStorageAt", [address, position, block])

    def eth_getBalance(self, address: str, block: str = "latest") -> int:
        result = self._call("eth_getBalance", [address, block])
        return int(result, 16) if result else 0

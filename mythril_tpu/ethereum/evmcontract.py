"""EVMContract — bytecode holder (reference mythril/ethereum/evmcontract.py:115)."""

from typing import Optional

from mythril_tpu.disasm import Disassembly
from mythril_tpu.utils.keccak import keccak256


def _hex_to_bytes(code) -> bytes:
    if code is None:
        return b""
    if isinstance(code, bytes):
        return code
    text = code.strip()
    if text.startswith("0x"):
        text = text[2:]
    return bytes.fromhex(text) if text else b""


class EVMContract:
    def __init__(self, code="", creation_code="", name: str = "MAIN",
                 enable_online_lookup: bool = False):
        self.code_bytes = _hex_to_bytes(code)
        self.creation_code_bytes = _hex_to_bytes(creation_code)
        self.name = name
        self._disassembly: Optional[Disassembly] = None
        self._creation_disassembly: Optional[Disassembly] = None

    @property
    def code(self) -> str:
        return "0x" + self.code_bytes.hex()

    @property
    def creation_code(self) -> Optional[str]:
        if not self.creation_code_bytes:
            return None
        return "0x" + self.creation_code_bytes.hex()

    @property
    def is_create_mode(self) -> bool:
        return bool(self.creation_code_bytes) and not self.code_bytes

    @property
    def bytecode_hash(self) -> str:
        return "0x" + keccak256(self.code_bytes).hex()

    @property
    def disassembly(self) -> Disassembly:
        if self._disassembly is None:
            self._disassembly = Disassembly(self.code_bytes)
        return self._disassembly

    @property
    def creation_disassembly(self) -> Disassembly:
        if self._creation_disassembly is None:
            self._creation_disassembly = Disassembly(self.creation_code_bytes)
        return self._creation_disassembly

    def get_easm(self) -> str:
        return self.disassembly.get_easm()

    def get_creation_easm(self) -> str:
        return self.creation_disassembly.get_easm()

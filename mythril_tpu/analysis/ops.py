"""Statespace op records for POST modules
(reference analysis/ops.py:94 + call_helpers.py:60)."""

from enum import Enum

from mythril_tpu.smt import BitVec


class VarType(Enum):
    CONCRETE = 1
    SYMBOLIC = 2


class Variable:
    def __init__(self, val, var_type: VarType):
        self.val = val
        self.type = var_type

    def __str__(self):
        return str(self.val)


def get_variable(i) -> Variable:
    if isinstance(i, int):
        return Variable(i, VarType.CONCRETE)
    if isinstance(i, BitVec) and not i.symbolic:
        return Variable(i.concrete_value, VarType.CONCRETE)
    return Variable(i, VarType.SYMBOLIC)


class Op:
    def __init__(self, node, state, state_index):
        self.node = node
        self.state = state
        self.state_index = state_index


class Call(Op):
    def __init__(self, node, state, state_index, call_type, to,
                 gas, value=Variable(0, VarType.CONCRETE), data=None):
        super().__init__(node, state, state_index)
        self.to = to
        self.call_type = call_type
        self.gas = gas
        self.value = value
        self.data = data


def get_call_from_state(state, node=None, state_index=0):
    """Decode a call-family instruction's arguments from a state snapshot."""
    instruction = state.get_current_instruction()
    if instruction is None:
        return None
    op = instruction.opcode
    stack = state.mstate_stack if hasattr(state, "mstate_stack") else state.mstate.stack
    try:
        if op in ("CALL", "CALLCODE"):
            gas, to, value = stack[-1], stack[-2], stack[-3]
            return Call(node, state, state_index, op, get_variable(to),
                        get_variable(gas), get_variable(value))
        if op in ("DELEGATECALL", "STATICCALL"):
            gas, to = stack[-1], stack[-2]
            return Call(node, state, state_index, op, get_variable(to),
                        get_variable(gas))
    except IndexError:
        return None
    return None

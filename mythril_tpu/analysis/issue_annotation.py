"""IssueAnnotation — carries (detector, issue, conditions) on states when
`args.use_issue_annotations` is set (summaries mode, reference
analysis/issue_annotation.py:47). The symbolic-summary plugin re-solves
the conditions under substitution when a summary is replayed."""

from typing import List

from mythril_tpu.laser.state.annotation import MergeableStateAnnotation


class IssueAnnotation(MergeableStateAnnotation):
    def __init__(self, conditions: List, issue, detector):
        """conditions: independently-satisfiable Bool conditions proving
        the issue; issue: the Issue record; detector: its module."""
        self.conditions = conditions
        self.issue = issue
        self.detector = detector

    @property
    def persist_to_world_state(self) -> bool:
        return True

    @property
    def persist_over_calls(self) -> bool:
        return True

    def __copy__(self):
        return IssueAnnotation(
            conditions=list(self.conditions),
            issue=self.issue,
            detector=self.detector,
        )

    clone = __copy__

    def check_merge_annotation(self, other: "IssueAnnotation") -> bool:
        return (self.issue.address == other.issue.address
                and type(self.detector) is type(other.detector))

    def merge_annotation(self, other: "IssueAnnotation") -> "IssueAnnotation":
        return self

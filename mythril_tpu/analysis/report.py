"""Issue + Report rendering (reference mythril/analysis/report.py:411).

Formats: text / markdown / json / jsonv2 (SWC standard format)."""

import json
import logging
from typing import Dict, List, Optional

from mythril_tpu.analysis.swc_data import SWC_TO_TITLE
from mythril_tpu.version import __version__

log = logging.getLogger(__name__)


class Issue:
    def __init__(
        self,
        contract: str,
        function_name: str,
        address: int,
        swc_id: str,
        title: str,
        bytecode,
        severity: str,
        description_head: str = "",
        description_tail: str = "",
        gas_used=(None, None),
        transaction_sequence: Optional[Dict] = None,
    ):
        self.contract = contract
        self.function = function_name
        self.address = address
        self.title = title
        self.severity = severity
        self.swc_id = swc_id
        self.description_head = description_head
        self.description_tail = description_tail
        self.min_gas_used, self.max_gas_used = gas_used
        self.filename = ""
        self.code = ""
        self.lineno = None
        self.source_mapping = None
        self.discovery_time = 0
        self.transaction_sequence = transaction_sequence
        if isinstance(bytecode, bytes):
            self.bytecode = bytecode.hex()
        elif isinstance(bytecode, (tuple, list)):
            # code with deploy-time-patched symbolic bytes: hash/report the
            # concrete projection
            from mythril_tpu.disasm.disassembly import _concrete_projection

            self.bytecode = _concrete_projection(bytecode).hex()
        else:
            self.bytecode = str(bytecode or "")
        try:
            from mythril_tpu.utils.keccak import keccak256

            self.bytecode_hash = "0x" + keccak256(
                bytes.fromhex(self.bytecode) if self.bytecode else b""
            ).hex()
        except ValueError:
            self.bytecode_hash = ""

    @property
    def description(self) -> str:
        tail = f"\n{self.description_tail}" if self.description_tail else ""
        return f"{self.description_head}{tail}"

    @property
    def transaction_sequence_users(self):
        """Exploit steps rendered for reports."""
        return self.transaction_sequence

    def as_dict(self) -> Dict:
        issue = {
            "title": self.title,
            "swc-id": self.swc_id,
            "contract": self.contract,
            "description": self.description,
            "function": self.function,
            "severity": self.severity,
            "address": self.address,
            "tx_sequence": self.transaction_sequence,
            "min_gas_used": self.min_gas_used,
            "max_gas_used": self.max_gas_used,
            "sourceMap": self.source_mapping,
            "filename": self.filename,
            "code": self.code,
            "lineno": self.lineno,
        }
        return issue

    def add_code_info(self, contract) -> None:
        """Attach source mapping when the contract carries solidity sources."""
        if not hasattr(contract, "get_source_info"):
            return
        try:
            source_info = contract.get_source_info(
                self.address, constructor=self.function == "constructor"
            )
        except Exception:
            return
        if source_info is None:
            return
        self.filename = source_info.filename
        self.code = source_info.code
        self.lineno = source_info.lineno
        self.source_mapping = source_info.solc_mapping

    def resolve_function_name(self, sig_db=None) -> None:
        """_function_0xselector -> human signature via the signature DB."""
        if not self.function.startswith("_function_0x") or sig_db is None:
            return
        selector = self.function[len("_function_"):]
        matches = sig_db.get(selector)
        if matches:
            self.function = matches[0]


class Report:
    environment = {}

    def __init__(self, contracts=None, exceptions=None,
                 execution_info=None):
        self.issues: Dict[str, Issue] = {}
        self.contracts = contracts or []
        self.exceptions = exceptions or []
        self.execution_info = execution_info or []

    def append_issue(self, issue: Issue) -> None:
        # function is part of the key: distinct functions can share a
        # revert/panic block address (reference report.py:302-309)
        key = (
            f"{issue.contract}-{issue.function}-{issue.address}-"
            f"{issue.swc_id}-{issue.title}"
        )
        self.issues[key] = issue

    def sorted_issues(self) -> List[Issue]:
        return sorted(
            self.issues.values(), key=lambda i: (i.contract, i.address, i.swc_id)
        )

    def as_text(self) -> str:
        if not self.issues:
            return "The analysis was completed successfully. No issues were detected.\n"
        blocks = []
        for issue in self.sorted_issues():
            lines = [
                f"==== {issue.title} ====",
                f"SWC ID: {issue.swc_id}",
                f"Severity: {issue.severity}",
                f"Contract: {issue.contract}",
                f"Function name: {issue.function}",
                f"PC address: {issue.address}",
                f"Estimated Gas Usage: {issue.min_gas_used} - {issue.max_gas_used}",
                issue.description,
            ]
            if issue.filename and issue.lineno:
                lines.append(f"In file: {issue.filename}:{issue.lineno}")
            if issue.code:
                lines.append(f"\n{issue.code}\n")
            if issue.transaction_sequence:
                lines.append("")
                lines.append("Transaction Sequence:")
                lines.append(
                    json.dumps(issue.transaction_sequence, indent=4)
                )
            blocks.append("\n".join(lines))
        return "\n\n".join(blocks) + "\n\n"

    def as_markdown(self) -> str:
        if not self.issues:
            return "# Analysis results\n\nThe analysis was completed successfully. No issues were detected.\n"
        blocks = ["# Analysis results"]
        for issue in self.sorted_issues():
            block = [
                f"## {issue.title}",
                f"- SWC ID: {issue.swc_id}",
                f"- Severity: {issue.severity}",
                f"- Contract: {issue.contract}",
                f"- Function name: `{issue.function}`",
                f"- PC address: {issue.address}",
                f"- Estimated Gas Usage: {issue.min_gas_used} - {issue.max_gas_used}",
                "",
                "### Description",
                issue.description,
            ]
            if issue.filename and issue.lineno:
                block.append(f"\nIn file: {issue.filename}:{issue.lineno}")
            blocks.append("\n".join(block))
        return "\n\n".join(blocks) + "\n"

    def as_json(self) -> str:
        result = {
            "success": True,
            "error": None,
            "issues": [issue.as_dict() for issue in self.sorted_issues()],
        }
        return json.dumps(result, default=str, sort_keys=True)

    def as_swc_standard_format(self) -> str:
        """jsonv2: one result object per analyzed bytecode."""
        results = []
        by_bytecode: Dict[str, List[Issue]] = {}
        for issue in self.sorted_issues():
            by_bytecode.setdefault(issue.bytecode_hash, []).append(issue)
        for bytecode_hash, issues in by_bytecode.items():
            result_issues = []
            for issue in issues:
                result_issues.append(
                    {
                        "swcID": f"SWC-{issue.swc_id}",
                        "swcTitle": SWC_TO_TITLE.get(issue.swc_id, ""),
                        "description": {
                            "head": issue.description_head,
                            "tail": issue.description_tail,
                        },
                        "severity": issue.severity,
                        "locations": [
                            {"bytecode": {"bytecodeOffset": issue.address}}
                        ],
                        "extra": {
                            "discoveryTime": issue.discovery_time,
                            "testCases": [issue.transaction_sequence]
                            if issue.transaction_sequence
                            else [],
                        },
                    }
                )
            results.append(
                {
                    "issues": result_issues,
                    "sourceType": "raw-bytecode",
                    "sourceFormat": "evm-byzantium-bytecode",
                    "sourceList": [bytecode_hash],
                    "meta": {
                        "toolName": "mythril_tpu",
                        "toolVersion": __version__,
                    },
                }
            )
        return json.dumps(results, default=str, sort_keys=True)

"""Analysis layer: detection modules, solver helpers, reports."""

"""DetectionModule base (reference analysis/module/base.py:120).

A module declares hook opcodes (pre/post) or a POST entry point; `execute`
runs the module's `_analyze_state` with an issue cache keyed by
(address, bytecode_hash) so re-visited program points are skipped."""

import logging
from enum import Enum
from typing import List, Optional, Set, Tuple

from mythril_tpu.support import model as model_mod

log = logging.getLogger(__name__)


class EntryPoint(Enum):
    POST = 1        # runs over the recorded statespace after execution
    CALLBACK = 2    # runs from opcode hooks during execution


class DetectionModule:
    name = "detection module"
    swc_id = ""
    description = ""
    entry_point: EntryPoint = EntryPoint.CALLBACK
    pre_hooks: List[str] = []
    post_hooks: List[str] = []
    # static-gating declaration (preanalysis): the opcodes at least one of
    # which must be EXECUTABLE for this module to ever raise an issue.
    # None (default) falls back to pre_hooks + post_hooks — always sound.
    # Override with a tighter set when some hooks are mere taint
    # observers: e.g. TxOrigin hooks JUMPI but cannot fire without ORIGIN
    # having executed. Declaring an opcode here that is NOT required for
    # an issue would be a soundness bug (findings would silently vanish
    # on contracts lacking it).
    trigger_opcodes: Optional[List[str]] = None

    def __init__(self):
        self.issues: List = []
        self.cache: Set[Tuple[int, bytes]] = set()
        # modules managing their own dedupe (e.g. Exceptions keying by last
        # JUMP) set this False (reference base.py auto_cache)
        self.auto_cache: bool = True
        # hook context, set per-invocation by execute(): which opcode fired
        # the hook and whether it was a pre- or post-hook (post-hooks see the
        # state AFTER execution, pc already advanced)
        self.current_opcode: Optional[str] = None
        self.is_prehook: bool = True

    def reset_module(self):
        self.issues = []

    def reset_cache(self):
        """Clear the (address, bytecode-hash) dedupe cache. Called at the
        start of each analysis session (core.fire_lasers) so repeated
        library-level analyses of the same bytecode re-detect issues; the
        reference never needs this because each CLI run is one process."""
        self.cache = set()

    def update_cache(self, issues=None):
        issues = issues if issues is not None else self.issues
        for issue in issues:
            self.cache.add((issue.address, issue.bytecode_hash))

    def _cache_key(self, global_state) -> Tuple[int, str]:
        instruction = global_state.get_current_instruction()
        address = instruction.address if instruction is not None else -1
        return (
            address,
            "0x" + global_state.environment.code.bytecode_hash.hex(),
        )

    def execute(self, target, opcode: Optional[str] = None,
                prehook: bool = True) -> Optional[List]:
        """target: GlobalState for CALLBACK modules, statespace for POST."""
        if self.entry_point == EntryPoint.CALLBACK:
            self.current_opcode = opcode
            self.is_prehook = prehook
            if (
                self.auto_cache
                and prehook
                and self._cache_key(target) in self.cache
            ):
                return None
        # inline detection-context flip (not the contextmanager): this is
        # the engine's hottest path — every opcode x every callback module
        previous_context = model_mod._in_detection_context
        model_mod._in_detection_context = True
        try:
            if self.entry_point == EntryPoint.CALLBACK:
                result = self._analyze_state(target)
            else:
                result = self._analyze_statespace(target)
        finally:
            model_mod._in_detection_context = previous_context
        if result:
            from mythril_tpu.support.args import args

            if args.use_issue_annotations and \
                    self.entry_point == EntryPoint.CALLBACK:
                # summaries mode: direct results would be solved under
                # parametric (summary-symbol) state — a false-positive
                # source; carry them as annotations for substituted
                # re-solving instead (reference base.py:94)
                from mythril_tpu.analysis.issue_annotation import (
                    IssueAnnotation,
                )
                from mythril_tpu.smt import And

                # modules that solved precise conditions (e.g. suicide's
                # attacker constraints) annotate themselves; only add the
                # coarse reachability fallback for issues they didn't, or a
                # weaker duplicate could confirm a false positive on
                # substituted re-solving
                already = {
                    id(a.issue)
                    for a in target.annotations
                    if isinstance(a, IssueAnnotation)
                }
                for issue in result:
                    if id(issue) in already:
                        continue
                    target.annotate(IssueAnnotation(
                        conditions=[And(
                            *target.world_state.constraints)],
                        issue=issue,
                        detector=self,
                    ))
                return result
            self.issues.extend(result)
            if self.auto_cache:
                self.update_cache(result)
        return result

    def _analyze_state(self, global_state) -> List:
        return []

    def _analyze_statespace(self, statespace) -> List:
        return []

    def __repr__(self):
        return f"<DetectionModule {self.name} swc={self.swc_id}>"

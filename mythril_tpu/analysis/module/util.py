"""Hook-table construction for detection modules
(reference analysis/module/util.py:13-43)."""

from collections import defaultdict
from typing import Callable, Dict, List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.module.loader import ModuleLoader
from mythril_tpu.support.opcodes import BY_NAME


def expand_hook_opcodes(names) -> frozenset:
    """Expand a hook-name list (exact names + the reference's PREFIX*
    wildcards, e.g. 'PUSH' -> PUSH1..32) into concrete opcode names."""
    out = set()
    for op_name in names:
        if op_name in BY_NAME:
            out.add(op_name)
        else:
            out.update(n for n in BY_NAME if n.startswith(op_name))
    return frozenset(out)


def module_trigger_opcodes(module: DetectionModule) -> frozenset:
    """The opcodes that must be executable for `module` to ever raise an
    issue: its declared trigger_opcodes, defaulting to the union of its
    hook opcodes (wildcards expanded). Used by the loader's static
    reachability gate."""
    triggers = getattr(module, "trigger_opcodes", None)
    if triggers is None:
        triggers = list(module.pre_hooks) + list(module.post_hooks)
    return expand_hook_opcodes(triggers)


def get_detection_module_hooks(
    modules: List[DetectionModule], hook_type: str = "pre"
) -> Dict[str, List[Callable]]:
    """Build opcode -> [module.execute] tables. Supports the reference's
    PREFIX* wildcard hook names (e.g. 'PUSH' matching PUSH1..32)."""
    hook_dict = defaultdict(list)
    prehook = hook_type == "pre"

    def bind(module, op_name):
        def hook(state, _m=module, _n=op_name, _p=prehook):
            return _m.execute(state, opcode=_n, prehook=_p)

        # conditional frontier transparency: a module may declare a
        # per-opcode value predicate under which its hook is provably
        # inert for batched straight-line runs (laser/frontier/stepper
        # consumes the attribute off the BOUND hook — registration and
        # gating must see the same object)
        predicate = getattr(module, "frontier_transparent_unless",
                            {}).get(op_name)
        if predicate is not None:
            hook.frontier_transparent_unless = predicate
        return hook

    for module in modules:
        if module.entry_point != EntryPoint.CALLBACK:
            continue
        hooks = module.pre_hooks if prehook else module.post_hooks
        for op_name in hooks:
            # one expansion rule for registration AND the gating trigger
            # sets (module_trigger_opcodes): the two must never diverge
            for name in sorted(expand_hook_opcodes([op_name])):
                hook_dict[name].append(bind(module, name))
    return dict(hook_dict)


def reset_callback_modules(module_names: Optional[List[str]] = None):
    for module in ModuleLoader().get_detection_modules(
        white_list=module_names
    ):
        if module.entry_point == EntryPoint.CALLBACK:
            module.reset_module()

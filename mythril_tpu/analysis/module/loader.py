"""ModuleLoader singleton registering the built-in detection modules
(reference analysis/module/loader.py:91-112)."""

import logging
from typing import List, Optional

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint

log = logging.getLogger(__name__)


class ModuleLoader:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._modules = []
            cls._instance._register_mythril_modules()
        return cls._instance

    def register_module(self, module: DetectionModule):
        if not isinstance(module, DetectionModule):
            raise ValueError("registered modules must extend DetectionModule")
        self._modules.append(module)

    def get_detection_modules(
        self,
        entry_point: Optional[EntryPoint] = None,
        white_list: Optional[List[str]] = None,
        reachable_opcodes: Optional[frozenset] = None,
    ) -> List[DetectionModule]:
        """`reachable_opcodes`, when given (preanalysis.gating_opcodes —
        None means "no static information", gate nothing), statically
        gates CALLBACK modules: a module whose trigger opcodes are all
        unreachable in the analyzed bytecode can never fire a hook, so it
        is not attached at all — no hooks, no predicate solves, no solver
        traffic. Every gate is counted (`modules_gated`); POST modules
        always run (they read the statespace, not opcode hooks)."""
        result = self._modules[:]
        if white_list:
            # accept both the reference's class names (`-m Exceptions`,
            # reference loader.py:65-79) and our internal snake_case names
            def names_of(module):
                return {module.name, type(module).__name__}

            available = set().union(*(names_of(m) for m in result))
            unknown = set(white_list) - available
            if unknown:
                raise ValueError(
                    f"unknown detection module(s): {', '.join(sorted(unknown))}"
                )
            wanted = set(white_list)
            result = [m for m in result if names_of(m) & wanted]
        if entry_point:
            result = [m for m in result if m.entry_point == entry_point]
        if reachable_opcodes is not None:
            result = self._gate_unreachable(result, reachable_opcodes)
        return result

    @staticmethod
    def _gate_unreachable(modules: List[DetectionModule],
                          reachable_opcodes: frozenset
                          ) -> List[DetectionModule]:
        from mythril_tpu.analysis.module.util import module_trigger_opcodes
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        kept = []
        stats = SolverStatistics()
        for module in modules:
            if module.entry_point == EntryPoint.CALLBACK:
                triggers = module_trigger_opcodes(module)
                if triggers and not (triggers & reachable_opcodes):
                    stats.add_module_gated()
                    log.info(
                        "preanalysis: gating module %s (trigger opcodes "
                        "%s statically unreachable)",
                        module.name, ",".join(sorted(triggers)))
                    continue
            kept.append(module)
        return kept

    def _register_mythril_modules(self):
        from mythril_tpu.analysis.module.modules.arbitrary_jump import ArbitraryJump
        from mythril_tpu.analysis.module.modules.arbitrary_write import (
            ArbitraryStorage,
        )
        from mythril_tpu.analysis.module.modules.delegatecall import (
            ArbitraryDelegateCall,
        )
        from mythril_tpu.analysis.module.modules.dependence_on_origin import TxOrigin
        from mythril_tpu.analysis.module.modules.dependence_on_predictable_vars import (
            PredictableVariables,
        )
        from mythril_tpu.analysis.module.modules.ether_thief import EtherThief
        from mythril_tpu.analysis.module.modules.exceptions import Exceptions
        from mythril_tpu.analysis.module.modules.external_calls import ExternalCalls
        from mythril_tpu.analysis.module.modules.integer import IntegerArithmetics
        from mythril_tpu.analysis.module.modules.multiple_sends import MultipleSends
        from mythril_tpu.analysis.module.modules.requirements_violation import (
            RequirementsViolation,
        )
        from mythril_tpu.analysis.module.modules.state_change_external_calls import (
            StateChangeAfterCall,
        )
        from mythril_tpu.analysis.module.modules.suicide import AccidentallyKillable
        from mythril_tpu.analysis.module.modules.transaction_order_dependence import (
            TxOrderDependence,
        )
        from mythril_tpu.analysis.module.modules.unchecked_retval import (
            UncheckedRetval,
        )
        from mythril_tpu.analysis.module.modules.unexpected_ether import (
            UnexpectedEther,
        )
        from mythril_tpu.analysis.module.modules.user_assertions import (
            UserAssertions,
        )

        self._modules = [
            ArbitraryJump(),
            ArbitraryStorage(),
            ArbitraryDelegateCall(),
            TxOrigin(),
            PredictableVariables(),
            EtherThief(),
            Exceptions(),
            ExternalCalls(),
            IntegerArithmetics(),
            MultipleSends(),
            RequirementsViolation(),
            StateChangeAfterCall(),
            AccidentallyKillable(),
            TxOrderDependence(),
            UncheckedRetval(),
            UnexpectedEther(),
            UserAssertions(),
        ]

"""UnexpectedEther — SWC-132 strict balance equality broken by forced ether
(reference analysis/module/modules/unexpected_ether.py:143, POST entry)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import UNEXPECTED_ETHER_BALANCE
from mythril_tpu.smt import terms as _terms
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)


def _condition_tests_balance_equality(condition_term, balance_array_names):
    """True if the term contains EQ over a select on a balance array."""
    for node in _terms.walk_terms([condition_term]):
        if node.op != "eq":
            continue
        for child in node.children:
            for sub in _terms.walk_terms([child]):
                if sub.op == "select" and sub.children[0].op == "array":
                    if sub.children[0].params[0] in balance_array_names:
                        return True
    return False


class UnexpectedEther(DetectionModule):
    name = "unexpected_ether"
    swc_id = UNEXPECTED_ETHER_BALANCE
    description = "Strict balance equality can be broken by forcibly sending ether."
    entry_point = EntryPoint.POST

    def _analyze_statespace(self, statespace) -> list:
        issues = []
        seen = set()
        for node in statespace.nodes.values():
            for state in node.states:
                instruction = state.get_current_instruction()
                if instruction is None or instruction.opcode != "JUMPI":
                    continue
                key = (
                    instruction.address,
                    "0x" + state.environment.code.bytecode_hash.hex(),
                )
                if key in seen or key in self.cache:
                    continue
                stack = (
                    state.mstate_stack
                    if hasattr(state, "mstate_stack")
                    else state.mstate.stack
                )
                if len(stack) < 2:
                    continue
                condition = stack[-2]
                if condition.symbolic is False:
                    continue
                # base balance array name under any store chain
                base = state.world_state.balances.raw
                while base.op == "store":
                    base = base.children[0]
                if base.op != "array":
                    continue
                if not _condition_tests_balance_equality(
                    condition.raw, {base.params[0]}
                ):
                    continue
                try:
                    transaction_sequence = get_transaction_sequence(
                        state, state.constraints
                    )
                except (UnsatError, SolverTimeOutException, AttributeError):
                    continue
                except Exception:
                    continue
                seen.add(key)
                issues.append(
                    Issue(
                        contract=state.environment.active_account.contract_name,
                        function_name=state.environment.active_function_name,
                        address=instruction.address,
                        swc_id=UNEXPECTED_ETHER_BALANCE,
                        title="Dependence on the balance of the contract",
                        severity="Medium",
                        bytecode=state.environment.code.bytecode,
                        description_head=(
                            "A control flow decision depends on "
                            "a strict check of the contract balance."
                        ),
                        description_tail=(
                            "A branch condition tests the exact balance of "
                            "the contract account. Since ether can be "
                            "forcibly sent to any account (e.g. via "
                            "selfdestruct or as a block reward recipient), "
                            "strict equality checks on the balance can be "
                            "broken by an attacker and should be avoided."
                        ),
                        transaction_sequence=transaction_sequence,
                    )
                )
        return issues

"""ExternalCalls — SWC-107 call to untrusted address with user gas
(reference analysis/module/modules/external_calls.py:122)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import REENTRANCY
from mythril_tpu.smt import UGT, symbol_factory
from mythril_tpu.smt.solver.frontend import UnsatError
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)

DESCRIPTION_HEAD = "A call to a user-supplied address is executed."
DESCRIPTION_TAIL_GAS = (
    "An external message call to an address specified by the caller is "
    "executed. Note that the callee account might contain arbitrary code "
    "and could re-enter any function within this contract. Reentering the "
    "contract in an intermediate state may lead to unexpected behaviour. "
    "Make sure that no state modifications are executed after this call "
    "and/or reentrancy guards are in place."
)


class ExternalCalls(DetectionModule):
    name = "external_calls"
    swc_id = REENTRANCY
    description = DESCRIPTION_HEAD
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL"]

    def _analyze_state(self, state):
        gas = state.mstate.stack[-1]
        to = state.mstate.stack[-2]
        if not to.symbolic:
            return []
        try:
            # enough gas forwarded for the callee to do damage (> stipend)
            constraints = [UGT(gas, symbol_factory.BitVecVal(2300, 256))]
            get_model(
                state.world_state.constraints.get_all_constraints() + constraints
            )
        except UnsatError:
            return []
        except Exception:
            return []
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            address=state.get_current_instruction().address,
            swc_id=REENTRANCY,
            title="External Call To User-Supplied Address",
            severity="Low",
            bytecode=state.environment.code.bytecode,
            description_head=DESCRIPTION_HEAD,
            description_tail=DESCRIPTION_TAIL_GAS,
            constraints=constraints,
            detector=self,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue
        )
        return []

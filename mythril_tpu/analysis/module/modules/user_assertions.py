"""UserAssertions — SWC-110 user-defined assertion signals
(reference analysis/module/modules/user_assertions.py:131).

Three signals, all deliberate assertion mechanisms (a plain
`require(cond, "reason")` revert is NOT one — flagging those would report
every guard clause in every contract):

* solidity >=0.8 `assert` — REVERT carrying `Panic(0x01)`;
* `emit AssertionFailed(string)` — LOG1 with the well-known topic;
* hevm-style property failure — MSTORE of the 0xcafecafe... marker word.
"""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import ASSERT_VIOLATION
from mythril_tpu.laser.instructions import concrete_or_none
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)

# Panic(uint256) selector; assertion failure is code 0x01
PANIC_SELECTOR = 0x4E487B71
# keccak("AssertionFailed(string)") — the MythX/hevm assertion event topic
ASSERTION_FAILED_TOPIC = (
    0xB42604CB105A16C8F6DB8A41E6B00C0C1B4826465E8BC504B3EB3E88B3E6A4A0
)
# hevm writes a word starting with this marker before failing a property
HEVM_MARKER_PREFIX = "0xcafecafecafecafecafecafecafecafecafecafe"


def _mstore_value_blocks(value: int) -> bool:
    """Conditional-transparency predicate for the MSTORE hook on batched
    frontier runs: the hook acts ONLY on a concretely-written hevm
    marker word (_hevm_marker_message — a symbolic value is already
    inert there), so a batched MSTORE of any other concrete value — the
    batch guarantees concreteness — may skip it. A row that DOES write
    the marker trips this predicate and bails to the per-state
    interpreter, where the hook fires exactly as before."""
    return hex(value).startswith(HEVM_MARKER_PREFIX)


class UserAssertions(DetectionModule):
    name = "user_assertions"
    swc_id = ASSERT_VIOLATION
    description = "A user-provided assertion failed."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["REVERT", "LOG1", "MSTORE"]
    # laser/frontier hook contract: MSTORE-bearing straight-line runs no
    # longer cut on this module — the hook is provably inert unless the
    # written word matches the hevm marker prefix (util.py copies this
    # onto the bound hook as frontier_transparent_unless)
    frontier_transparent_unless = {"MSTORE": _mstore_value_blocks}

    def _analyze_state(self, state):
        opcode = state.get_current_instruction().opcode
        if opcode == "REVERT":
            message = self._panic_message(state)
        elif opcode == "LOG1":
            message = self._assertion_event_message(state)
        else:
            message = self._hevm_marker_message(state)
        if message is None:
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints
            )
        except (UnsatError, SolverTimeOutException):
            return []
        except Exception:
            return []
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.environment.active_function_name,
                address=state.get_current_instruction().address,
                swc_id=ASSERT_VIOLATION,
                title="Exception State",
                severity="Medium",
                bytecode=state.environment.code.bytecode,
                description_head=message,
                description_tail=(
                    "Review the transaction trace to see under which "
                    "conditions the assertion can be violated."
                ),
                transaction_sequence=transaction_sequence,
            )
        ]

    @staticmethod
    def _panic_message(state):
        """solidity 0.8 assert: REVERT with Panic(0x01) calldata."""
        offset, length = state.mstate.stack[-1], state.mstate.stack[-2]
        offset_c = concrete_or_none(offset)
        length_c = concrete_or_none(length)
        if offset_c is None or length_c is None or length_c < 36:
            return None
        word = state.mstate.memory.get_word_at(offset_c)
        selector_bv = concrete_or_none(word)
        if selector_bv is None or (selector_bv >> 224) != PANIC_SELECTOR:
            return None
        code = concrete_or_none(state.mstate.memory.get_word_at(offset_c + 4))
        if code != 1:
            return None
        return "An assertion violation was triggered (Panic 0x01)."

    @staticmethod
    def _assertion_event_message(state):
        """emit AssertionFailed(string): LOG1 with the well-known topic."""
        if len(state.mstate.stack) < 3:
            return None
        topic = concrete_or_none(state.mstate.stack[-3])
        if topic != ASSERTION_FAILED_TOPIC:
            return None
        return "A user-provided assertion failed (AssertionFailed event)."

    @staticmethod
    def _hevm_marker_message(state):
        """hevm property failure: MSTORE of the cafecafe... marker word."""
        if len(state.mstate.stack) < 2:
            return None
        value = concrete_or_none(state.mstate.stack[-2])
        if value is None:
            return None
        if not hex(value).startswith(HEVM_MARKER_PREFIX):
            return None
        return f"Failed property id {value & 0xFFFF}"

"""UserAssertions — SWC-110 solidity 0.8 Panic / user-defined assert messages
(reference analysis/module/modules/user_assertions.py:131)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import ASSERT_VIOLATION
from mythril_tpu.laser.instructions import concrete_or_none
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)

# Panic(uint256) selector and assertion-failure code 0x01
PANIC_SELECTOR = 0x4E487B71
# Error(string) selector for revert reasons
ERROR_SELECTOR = 0x08C379A0


class UserAssertions(DetectionModule):
    name = "user_assertions"
    swc_id = ASSERT_VIOLATION
    description = "A user-provided assertion failed."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["REVERT"]

    def _analyze_state(self, state):
        offset, length = state.mstate.stack[-1], state.mstate.stack[-2]
        offset_c = concrete_or_none(offset)
        length_c = concrete_or_none(length)
        if offset_c is None or length_c is None or length_c < 4:
            return []
        word = state.mstate.memory.get_word_at(offset_c)
        selector_bv = concrete_or_none(word)
        if selector_bv is None:
            return []
        selector = selector_bv >> 224
        if selector == PANIC_SELECTOR:
            if length_c < 36:
                return []
            code_bv = concrete_or_none(
                state.mstate.memory.get_word_at(offset_c + 4)
            )
            if code_bv != 1:  # Panic(0x01) == assert failure
                return []
            message = "An assertion violation was triggered (Panic 0x01)."
        elif selector == ERROR_SELECTOR:
            message = "A user-provided string assertion failed."
        else:
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints
            )
        except (UnsatError, SolverTimeOutException):
            return []
        except Exception:
            return []
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.environment.active_function_name,
                address=state.get_current_instruction().address,
                swc_id=ASSERT_VIOLATION,
                title="Exception State",
                severity="Medium",
                bytecode=state.environment.code.bytecode,
                description_head=message,
                description_tail=(
                    "Review the transaction trace to see under which "
                    "conditions the assertion can be violated."
                ),
                transaction_sequence=transaction_sequence,
            )
        ]

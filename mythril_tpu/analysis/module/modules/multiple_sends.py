"""MultipleSends — SWC-113 several external calls in one transaction
(reference analysis/module/modules/multiple_sends.py:107)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import MULTIPLE_SENDS
from mythril_tpu.laser.state.annotation import StateAnnotation

log = logging.getLogger(__name__)


class MultipleSendsAnnotation(StateAnnotation):
    def __init__(self):
        self.call_offsets = []

    def clone(self):
        dup = MultipleSendsAnnotation()
        dup.call_offsets = list(self.call_offsets)
        return dup


def _get_annotation(state) -> MultipleSendsAnnotation:
    for annotation in state.annotations:
        if isinstance(annotation, MultipleSendsAnnotation):
            return annotation
    annotation = MultipleSendsAnnotation()
    state.annotate(annotation)
    return annotation


class MultipleSends(DetectionModule):
    name = "multiple_sends"
    swc_id = MULTIPLE_SENDS
    description = "Multiple external calls in the same transaction."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL", "DELEGATECALL", "STATICCALL", "CALLCODE",
                 "RETURN", "STOP"]
    # RETURN/STOP only report calls already recorded on the path
    trigger_opcodes = ["CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"]

    def _analyze_state(self, state):
        annotation = _get_annotation(state)
        opcode = self.current_opcode
        if opcode in ("CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"):
            annotation.call_offsets.append(
                state.get_current_instruction().address
            )
            return []
        # RETURN/STOP: report if more than one call happened on this path
        if len(annotation.call_offsets) < 2:
            return []
        offset = annotation.call_offsets[1]
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            address=offset,
            swc_id=MULTIPLE_SENDS,
            title="Multiple Calls in a Single Transaction",
            severity="Low",
            bytecode=state.environment.code.bytecode,
            description_head=(
                "Multiple calls are executed in the same transaction."
            ),
            description_tail=(
                "This call is executed following another call within the same "
                "transaction. It is possible that the call never gets executed "
                "if a prior call fails permanently. This might be caused "
                "intentionally by a malicious callee. If possible, refactor "
                "the code such that each transaction only executes one "
                "external call or make sure that all callees can be trusted "
                "(i.e. they're part of your own codebase)."
            ),
            constraints=[],
            detector=self,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue
        )
        return []

"""The built-in detection modules (reference analysis/module/modules/)."""

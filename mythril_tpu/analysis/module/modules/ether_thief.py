"""EtherThief — SWC-105 unprotected ether withdrawal
(reference analysis/module/modules/ether_thief.py:100)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import UNPROTECTED_ETHER_WITHDRAWAL
from mythril_tpu.laser.transaction.symbolic import ACTORS
from mythril_tpu.smt import UGT
from mythril_tpu.support.model import get_model
from mythril_tpu.smt.solver.frontend import UnsatError

log = logging.getLogger(__name__)

DESCRIPTION_HEAD = "Any sender can withdraw ETH from the contract account."
DESCRIPTION_TAIL = (
    "Arbitrary senders other than the contract creator can profitably "
    "extract ETH from the contract account. Verify the business logic "
    "carefully and make sure that appropriate security controls are in "
    "place to prevent unexpected loss of funds."
)


class EtherThief(DetectionModule):
    name = "ether_thief"
    swc_id = UNPROTECTED_ETHER_WITHDRAWAL
    description = DESCRIPTION_HEAD
    entry_point = EntryPoint.CALLBACK
    post_hooks = ["CALL", "STATICCALL"]

    def _analyze_state(self, state):
        instruction = state.get_current_instruction()
        if instruction is None:  # CALL was the last instruction of the code
            return []

        constraints = []
        world_state = state.world_state
        for tx in world_state.transaction_sequence:
            if not isinstance(tx.caller, int) and tx.caller.symbolic:
                constraints.append(tx.caller == ACTORS.attacker)
            # exploit must not rely on the attacker seeding the contract
            if tx.call_value is not None and tx.call_value.symbolic:
                constraints.append(tx.call_value == 0)
        constraints.append(
            UGT(
                world_state.balances[ACTORS.attacker],
                world_state.starting_balances[ACTORS.attacker],
            )
        )

        try:
            get_model(
                world_state.constraints.get_all_constraints() + constraints
            )
        except UnsatError:
            return []
        except Exception:
            return []

        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            # post-hook state: pc advanced past the 1-byte CALL opcode
            address=instruction.address - 1,
            swc_id=UNPROTECTED_ETHER_WITHDRAWAL,
            title="Unprotected Ether Withdrawal",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head=DESCRIPTION_HEAD,
            description_tail=DESCRIPTION_TAIL,
            constraints=constraints,
            detector=self,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue
        )
        return []

"""EtherThief — SWC-105 unprotected ether withdrawal
(reference analysis/module/modules/ether_thief.py:100)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import UNPROTECTED_ETHER_WITHDRAWAL
from mythril_tpu.laser.transaction.symbolic import ACTORS
from mythril_tpu.smt import UGT
from mythril_tpu.support.model import get_model
from mythril_tpu.smt.solver.frontend import UnsatError

log = logging.getLogger(__name__)

DESCRIPTION_HEAD = "Any sender can withdraw ETH from the contract account."
DESCRIPTION_TAIL = (
    "Arbitrary senders other than the contract creator can profitably "
    "extract ETH from the contract account. Verify the business logic "
    "carefully and make sure that appropriate security controls are in "
    "place to prevent unexpected loss of funds."
)


class EtherThief(DetectionModule):
    name = "ether_thief"
    swc_id = UNPROTECTED_ETHER_WITHDRAWAL
    description = DESCRIPTION_HEAD
    entry_point = EntryPoint.CALLBACK
    post_hooks = ["CALL", "STATICCALL"]

    def _analyze_state(self, state):
        instruction = state.get_current_instruction()
        if instruction is None:  # CALL was the last instruction of the code
            return []

        # the attacker sends the CURRENT tx (as an EOA: caller == origin)
        # and ends up richer than they started. Earlier txs stay
        # unconstrained — the contract may legitimately have been funded at
        # creation (reference ether_thief.py:65-72; constraining every tx's
        # value to 0 would rule out payable constructors like flag_array's).
        world_state = state.world_state
        current_tx = state.current_transaction
        constraints = [
            UGT(
                world_state.balances[ACTORS.attacker],
                world_state.starting_balances[ACTORS.attacker],
            ),
            state.environment.sender == ACTORS.attacker,
            current_tx.caller == current_tx.origin,
        ]

        try:
            get_model(
                world_state.constraints.get_all_constraints() + constraints
            )
        except UnsatError:
            return []
        except Exception:
            return []

        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            # post-hook state: pc advanced past the 1-byte CALL opcode
            address=instruction.address - 1,
            swc_id=UNPROTECTED_ETHER_WITHDRAWAL,
            title="Unprotected Ether Withdrawal",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head=DESCRIPTION_HEAD,
            description_tail=DESCRIPTION_TAIL,
            constraints=constraints,
            detector=self,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue
        )
        return []

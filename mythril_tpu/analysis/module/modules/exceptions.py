"""Exceptions — SWC-110 reachable assert violation
(reference analysis/module/modules/exceptions.py:152)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import ASSERT_VIOLATION
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)

DESCRIPTION_HEAD = "An assertion violation was triggered."
DESCRIPTION_TAIL = (
    "It is possible to trigger an assertion violation. Note that Solidity "
    "assert() statements should only be used to check invariants. Review "
    "the transaction trace generated for this issue and either make sure "
    "your program logic is correct, or use require() instead of assert() "
    "if your goal is to constrain user inputs or enforce preconditions."
)


class Exceptions(DetectionModule):
    name = "exceptions"
    swc_id = ASSERT_VIOLATION
    description = DESCRIPTION_HEAD
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["INVALID"]

    def _analyze_state(self, state):
        instruction = state.get_current_instruction()
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints
            )
        except (UnsatError, SolverTimeOutException):
            return []
        except Exception:
            return []
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.environment.active_function_name,
                address=instruction.address,
                swc_id=ASSERT_VIOLATION,
                title="Exception State",
                severity="Medium",
                bytecode=state.environment.code.bytecode,
                description_head=DESCRIPTION_HEAD,
                description_tail=DESCRIPTION_TAIL,
                transaction_sequence=transaction_sequence,
            )
        ]

"""Exceptions — SWC-110 reachable assert violation
(reference analysis/module/modules/exceptions.py:152).

Two assert encodings are recognized:
- pre-0.8 solc: `assert` compiles to the INVALID (0xfe) opcode;
- solc >= 0.8: assert failure REVERTs with `Panic(uint256)` code 0x01 —
  detected by matching the Panic ABI signature in the revert buffer
  (reference exceptions.py:139-151).

Issues are cached per (last JUMP address, code hash) so the same assert
body reached from different call sites still reports once per site
(reference exceptions.py:44-56,86-91)."""

import logging
from typing import List, Optional

from mythril_tpu.analysis.issue_annotation import IssueAnnotation
from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import ASSERT_VIOLATION
from mythril_tpu.laser.state.annotation import StateAnnotation
from mythril_tpu.smt import And
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)

# ABI signature of Panic(uint256)
PANIC_SIGNATURE = [78, 72, 123, 113]

DESCRIPTION_HEAD = "An assertion violation was triggered."
DESCRIPTION_TAIL = (
    "It is possible to trigger an assertion violation. Note that Solidity "
    "assert() statements should only be used to check invariants. Review "
    "the transaction trace generated for this issue and either make sure "
    "your program logic is correct, or use require() instead of assert() "
    "if your goal is to constrain user inputs or enforce preconditions."
)


class LastJumpAnnotation(StateAnnotation):
    """Tracks the address of the last JUMP taken on this path."""

    def __init__(self, last_jump: Optional[int] = None):
        self.last_jump = last_jump

    def __copy__(self):
        return LastJumpAnnotation(self.last_jump)

    def clone(self):
        return LastJumpAnnotation(self.last_jump)


def _concrete_or_none(value) -> Optional[int]:
    if isinstance(value, int):
        return value
    if getattr(value, "symbolic", True):
        return None
    return value.concrete_value


def is_assertion_failure(state) -> bool:
    """REVERT buffer starts with Panic(uint256) and the code is 0x01."""
    mstate = state.mstate
    offset, length = mstate.stack[-1], mstate.stack[-2]
    offset_c = _concrete_or_none(offset)
    length_c = _concrete_or_none(length)
    if offset_c is None or length_c is None or not 4 < length_c <= 0x1000:
        return False
    data = [
        _concrete_or_none(mstate.memory.get_byte(offset_c + i))
        for i in range(length_c)
    ]
    if any(b is None for b in data):
        return False
    return data[:4] == PANIC_SIGNATURE and data[-1] == 1


class Exceptions(DetectionModule):
    name = "exceptions"
    swc_id = ASSERT_VIOLATION
    description = DESCRIPTION_HEAD
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["INVALID", "JUMP", "REVERT"]
    # JUMP only records the last-jump cache key; issues fire at
    # INVALID (0.4-style assert) or panic-data REVERT (0.8 assert)
    trigger_opcodes = ["INVALID", "REVERT"]

    def __init__(self):
        super().__init__()
        self.auto_cache = False

    def _analyze_state(self, state) -> List[Issue]:
        instruction = state.get_current_instruction()
        opcode, address = instruction.opcode, instruction.address

        annotations = list(state.get_annotations(LastJumpAnnotation))
        if not annotations:
            annotation = LastJumpAnnotation()
            state.annotate(annotation)
            annotations = [annotation]

        if opcode == "JUMP":
            annotations[0].last_jump = address
            return []

        if opcode == "REVERT" and not is_assertion_failure(state):
            return []

        cache_address = annotations[0].last_jump
        code_hash = "0x" + state.environment.code.bytecode_hash.hex()
        if (cache_address, code_hash) in self.cache:
            return []

        log.debug(
            "ASSERT_FAIL/REVERT in function %s",
            state.environment.active_function_name,
        )
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints
            )
        except (UnsatError, SolverTimeOutException):
            return []
        except Exception:
            return []

        self.cache.add((cache_address, code_hash))
        issue = Issue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            address=address,
            swc_id=ASSERT_VIOLATION,
            title="Exception State",
            severity="Medium",
            bytecode=state.environment.code.bytecode,
            description_head=DESCRIPTION_HEAD,
            description_tail=DESCRIPTION_TAIL,
            transaction_sequence=transaction_sequence,
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
        )
        state.annotate(
            IssueAnnotation(
                conditions=[And(*state.world_state.constraints)],
                issue=issue,
                detector=self,
            )
        )
        return [issue]

"""IntegerArithmetics — SWC-101 overflow/underflow reaching a sink
(reference analysis/module/modules/integer.py:350).

Mechanism (mirrors the reference flow):
- pre-hooks on ADD/SUB/MUL/EXP annotate the first operand with the overflow
  predicate; the SMT layer propagates annotations through the arithmetic op
  so the *result* carries the marker.
- sink hooks (SSTORE/JUMPI/CALL/RETURN) collect markers whose value reached
  the sink into a state-level annotation.
- at transaction end (STOP/RETURN) each collected marker is re-solved under
  the *current* path constraints and confirmed into a direct Issue via
  get_transaction_sequence — NOT the two-phase PotentialIssue flow, so
  overflows found during creation-tx interpretation are still reported
  (reference integer.py:_handle_transaction_end)."""

import logging
from copy import copy
from math import ceil, log2
from typing import List, Set

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.issue_annotation import IssueAnnotation
from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.swc_data import INTEGER_OVERFLOW_AND_UNDERFLOW
from mythril_tpu.laser.state.annotation import StateAnnotation
from mythril_tpu.smt import (
    And,
    BitVec,
    BVAddNoOverflow,
    BVMulNoOverflow,
    BVSubNoUnderflow,
    Bool,
    If,
    Not,
    symbol_factory,
)
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError
from mythril_tpu.support.args import args
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)


class OverUnderflowAnnotation:
    """Attached to the possibly-overflowing value.

    The reference stores the whole GlobalState (its StateTransition
    decorator copies states, so the hooked object stays frozen at the op);
    this engine mutates states in place, so the origin is snapshotted here:
    address/function/constraints as they were AT the arithmetic op."""

    __slots__ = ("address", "function_name", "contract_name", "bytecode",
                 "origin_constraints", "operator", "constraint")

    def __init__(self, state, operator: str, constraint: Bool):
        instruction = state.get_current_instruction()
        self.address = instruction.address
        self.function_name = state.environment.active_function_name
        self.contract_name = state.environment.active_account.contract_name
        self.bytecode = state.environment.code.bytecode
        self.origin_constraints = list(
            state.world_state.constraints.get_all_constraints()
        )
        self.operator = operator
        self.constraint = constraint

    def __deepcopy__(self, memodict={}):
        # markers are immutable snapshots; share across forks
        # (reference integer.py:46-48)
        return copy(self)

    # value semantics so per-fork copies dedupe inside the sink bucket
    def __hash__(self):
        return hash((self.address, self.operator, hash(self.constraint)))

    def __eq__(self, other):
        if not isinstance(other, OverUnderflowAnnotation):
            return NotImplemented
        return (
            self.address == other.address
            and self.operator == other.operator
            and hash(self.constraint) == hash(other.constraint)
        )


class OverUnderflowStateAnnotation(StateAnnotation):
    """State-level bucket of markers whose value reached a sink."""

    def __init__(self):
        self.overflowing_state_annotations: Set[OverUnderflowAnnotation] = set()

    def __copy__(self):
        new = OverUnderflowStateAnnotation()
        new.overflowing_state_annotations = copy(
            self.overflowing_state_annotations
        )
        return new


def _get_overflowunderflow_state_annotation(state) -> OverUnderflowStateAnnotation:
    existing = list(state.get_annotations(OverUnderflowStateAnnotation))
    if existing:
        return existing[0]
    annotation = OverUnderflowStateAnnotation()
    state.annotate(annotation)
    return annotation


class IntegerArithmetics(DetectionModule):
    name = "integer_overflow_and_underflow"
    swc_id = INTEGER_OVERFLOW_AND_UNDERFLOW
    description = "Integer overflow or underflow reaching a sink."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = [
        "ADD",
        "MUL",
        "EXP",
        "SUB",
        "SSTORE",
        "JUMPI",
        "STOP",
        "RETURN",
        "CALL",
    ]
    # JUMPI/STOP/RETURN/CALL/SSTORE are sinks for already-tainted values;
    # no issue without an arithmetic source executing
    trigger_opcodes = ["ADD", "MUL", "EXP", "SUB"]

    def __init__(self):
        super().__init__()
        # satisfiability cache of overflow predicates at their origin state
        self._ostates_satisfiable: Set[int] = set()
        self._ostates_unsatisfiable: Set[int] = set()

    def reset_module(self):
        super().reset_module()
        self._ostates_satisfiable = set()
        self._ostates_unsatisfiable = set()

    def _analyze_state(self, state) -> List[Issue]:
        if not args.use_integer_module:
            return []
        handlers = {
            "ADD": [self._handle_add],
            "SUB": [self._handle_sub],
            "MUL": [self._handle_mul],
            "EXP": [self._handle_exp],
            "SSTORE": [self._handle_sstore],
            "JUMPI": [self._handle_jumpi],
            "CALL": [self._handle_call],
            "RETURN": [self._handle_return, self._handle_transaction_end],
            "STOP": [self._handle_transaction_end],
        }
        issues: List[Issue] = []
        for handler in handlers.get(self.current_opcode, []):
            result = handler(state)
            if result:
                issues += result
        return issues

    # -- arithmetic-op marking ----------------------------------------------

    @staticmethod
    def _make_bitvec_if_not(stack, index):
        value = stack[index]
        if isinstance(value, BitVec):
            return value
        if isinstance(value, Bool):
            return If(value, 1, 0)
        stack[index] = symbol_factory.BitVecVal(value, 256)
        return stack[index]

    def _get_args(self, state):
        stack = state.mstate.stack
        return (
            self._make_bitvec_if_not(stack, -1),
            self._make_bitvec_if_not(stack, -2),
        )

    def _handle_add(self, state):
        op0, op1 = self._get_args(state)
        constraint = Not(BVAddNoOverflow(op0, op1, False))
        op0.annotate(OverUnderflowAnnotation(state, "addition", constraint))

    def _handle_sub(self, state):
        op0, op1 = self._get_args(state)
        constraint = Not(BVSubNoUnderflow(op0, op1, False))
        op0.annotate(OverUnderflowAnnotation(state, "subtraction", constraint))

    def _handle_mul(self, state):
        op0, op1 = self._get_args(state)
        constraint = Not(BVMulNoOverflow(op0, op1, False))
        op0.annotate(
            OverUnderflowAnnotation(state, "multiplication", constraint)
        )

    def _handle_exp(self, state):
        op0, op1 = self._get_args(state)
        if (not op1.symbolic and op1.concrete_value == 0) or (
            not op0.symbolic and op0.concrete_value < 2
        ):
            return
        if op0.symbolic and op1.symbolic:
            constraint = And(
                op1 > symbol_factory.BitVecVal(256, 256),
                op0 > symbol_factory.BitVecVal(1, 256),
            )
        elif op0.symbolic:
            constraint = op0 >= symbol_factory.BitVecVal(
                2 ** ceil(256 / op1.concrete_value), 256
            )
        else:
            constraint = op1 >= symbol_factory.BitVecVal(
                ceil(256 / log2(op0.concrete_value)), 256
            )
        op0.annotate(
            OverUnderflowAnnotation(state, "exponentiation", constraint)
        )

    # -- sink collection -----------------------------------------------------

    @staticmethod
    def _collect(state, value) -> None:
        if not isinstance(value, BitVec):
            return
        bucket = _get_overflowunderflow_state_annotation(state)
        for annotation in value.annotations:
            if isinstance(annotation, OverUnderflowAnnotation):
                bucket.overflowing_state_annotations.add(annotation)

    def _handle_sstore(self, state):
        self._collect(state, state.mstate.stack[-2])

    def _handle_jumpi(self, state):
        self._collect(state, state.mstate.stack[-2])

    def _handle_call(self, state):
        self._collect(state, state.mstate.stack[-3])

    def _handle_return(self, state):
        """Values flowing out via RETURN memory are sinks too
        (reference integer.py:_handle_return)."""
        stack = state.mstate.stack
        offset, length = stack[-1], stack[-2]
        if offset.symbolic or length.symbolic:
            return
        start = offset.concrete_value
        count = min(length.concrete_value, 0x1000)
        for i in range(count):
            self._collect(state, state.mstate.memory.get_byte(start + i))

    # -- transaction-end confirmation ---------------------------------------

    def _handle_transaction_end(self, state) -> List[Issue]:
        issues: List[Issue] = []
        bucket = _get_overflowunderflow_state_annotation(state)
        for annotation in bucket.overflowing_state_annotations:
            okey = (annotation.address, hash(annotation.constraint))
            if okey in self._ostates_unsatisfiable:
                continue
            if okey not in self._ostates_satisfiable:
                # quick pre-check at the origin state before the expensive
                # sequence concretization (reference integer.py:268-277)
                try:
                    get_model(
                        annotation.origin_constraints + [annotation.constraint]
                    )
                    self._ostates_satisfiable.add(okey)
                except Exception:
                    self._ostates_unsatisfiable.add(okey)
                    continue
            try:
                constraints = list(state.world_state.constraints) + [
                    annotation.constraint
                ]
                transaction_sequence = solver.get_transaction_sequence(
                    state, constraints
                )
            except (UnsatError, SolverTimeOutException):
                continue
            description_head = "The arithmetic operator can {}.".format(
                "underflow"
                if annotation.operator == "subtraction"
                else "overflow"
            )
            description_tail = (
                "It is possible to cause an integer overflow or underflow "
                "in the arithmetic operation. Prevent this by constraining "
                "inputs using the require() statement or use the "
                "OpenZeppelin SafeMath library for integer arithmetic "
                "operations. Refer to the transaction trace generated for "
                "this issue to reproduce the issue."
            )
            issue = Issue(
                contract=annotation.contract_name,
                function_name=annotation.function_name,
                address=annotation.address,
                swc_id=INTEGER_OVERFLOW_AND_UNDERFLOW,
                bytecode=annotation.bytecode,
                title="Integer Arithmetic Bugs",
                severity="High",
                description_head=description_head,
                description_tail=description_tail,
                gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
                transaction_sequence=transaction_sequence,
            )
            state.annotate(
                IssueAnnotation(
                    issue=issue, detector=self, conditions=[And(*constraints)]
                )
            )
            issues.append(issue)
        return issues

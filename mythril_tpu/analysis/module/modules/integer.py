"""IntegerArithmetics — SWC-101 overflow/underflow reaching a sink
(reference analysis/module/modules/integer.py:350).

Mechanism: pre-hooks on ADD/SUB/MUL/EXP capture the operands; the matching
post-hook annotates the pushed result with the overflow predicate. Sink
hooks (SSTORE/JUMPI/CALL) promote annotated values whose predicate is
satisfiable into PotentialIssues."""

import logging
from typing import List, Optional, Tuple

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import INTEGER_OVERFLOW_AND_UNDERFLOW
from mythril_tpu.smt import (
    BVAddNoOverflow,
    BVMulNoOverflow,
    BVSubNoUnderflow,
    Bool,
    Not,
)
from mythril_tpu.support.args import args

log = logging.getLogger(__name__)


class OverUnderflowAnnotation:
    __slots__ = ("overflowing_state_address", "operator", "constraint")

    def __init__(self, address: int, operator: str, constraint: Bool):
        self.overflowing_state_address = address
        self.operator = operator
        self.constraint = constraint


class IntegerArithmetics(DetectionModule):
    name = "integer_overflow_and_underflow"
    swc_id = INTEGER_OVERFLOW_AND_UNDERFLOW
    description = "Integer overflow or underflow reaching a sink."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["ADD", "SUB", "MUL", "SSTORE", "JUMPI", "CALL"]
    post_hooks = ["ADD", "SUB", "MUL"]

    def __init__(self):
        super().__init__()
        self._pending: Optional[Tuple[str, int, Bool]] = None

    def _analyze_state(self, state) -> List:
        if not args.use_integer_module:
            return []
        opcode = self.current_opcode
        if opcode in ("ADD", "SUB", "MUL"):
            if self.is_prehook:
                self._capture_operands(state, opcode)
            else:
                self._annotate_result(state)
            return []
        return self._check_sink(state, opcode)

    def _capture_operands(self, state, opcode: str) -> None:
        self._pending = None
        stack = state.mstate.stack
        a, b = stack[-1], stack[-2]
        if not a.symbolic and not b.symbolic:
            return
        address = state.get_current_instruction().address
        if opcode == "ADD":
            constraint = Not(BVAddNoOverflow(a, b, False))
            operator = "addition"
        elif opcode == "SUB":
            constraint = Not(BVSubNoUnderflow(a, b, False))
            operator = "subtraction"
        else:
            constraint = Not(BVMulNoOverflow(a, b, False))
            operator = "multiplication"
        self._pending = (operator, address, constraint)

    def _annotate_result(self, state) -> None:
        if self._pending is None:
            return
        operator, address, constraint = self._pending
        self._pending = None
        if state.mstate.stack:
            state.mstate.stack[-1].annotate(
                OverUnderflowAnnotation(address, operator, constraint)
            )

    def _sink_values(self, state, opcode: str) -> List:
        stack = state.mstate.stack
        if opcode == "SSTORE":
            return [stack[-1], stack[-2]]
        if opcode == "JUMPI":
            return [stack[-2]]
        if opcode == "CALL":
            return [stack[-3]]
        return []

    def _check_sink(self, state, opcode: str) -> List:
        issues = []
        annotation_bucket = get_potential_issues_annotation(state)
        for value in self._sink_values(state, opcode):
            for marker in value.get_annotations(OverUnderflowAnnotation):
                title = (
                    "Integer Arithmetic Bugs"
                )
                potential_issue = PotentialIssue(
                    contract=state.environment.active_account.contract_name,
                    function_name=state.environment.active_function_name,
                    address=marker.overflowing_state_address,
                    swc_id=INTEGER_OVERFLOW_AND_UNDERFLOW,
                    title=title,
                    severity="High",
                    bytecode=state.environment.code.bytecode,
                    description_head=(
                        "The arithmetic operator can "
                        + ("underflow." if marker.operator == "subtraction"
                           else "overflow.")
                    ),
                    description_tail=(
                        f"It is possible to cause an integer overflow or "
                        f"underflow in the arithmetic operation "
                        f"({marker.operator}). Prevent this by constraining "
                        f"inputs using the require() statement or use the "
                        f"OpenZeppelin SafeMath library for integer "
                        f"arithmetic operations."
                    ),
                    constraints=[marker.constraint],
                    detector=self,
                )
                if not self._already_recorded(annotation_bucket, potential_issue):
                    annotation_bucket.potential_issues.append(potential_issue)
        return issues

    @staticmethod
    def _already_recorded(annotation_bucket, candidate) -> bool:
        # dedup must include the predicate: the same ADD address is reached
        # in every transaction, each with a different overflow constraint
        candidate_key = tuple(hash(c) for c in candidate.constraints)
        for issue in annotation_bucket.potential_issues:
            if (
                issue.address == candidate.address
                and issue.swc_id == candidate.swc_id
                and issue.detector is candidate.detector
                and tuple(hash(c) for c in issue.constraints) == candidate_key
            ):
                return True
        return False

"""ArbitraryDelegateCall — SWC-112 delegatecall to attacker-controlled callee
(reference analysis/module/modules/delegatecall.py:100)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import DELEGATECALL_TO_UNTRUSTED_CONTRACT
from mythril_tpu.laser.transaction.symbolic import ACTORS
from mythril_tpu.smt.solver.frontend import UnsatError
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)


class ArbitraryDelegateCall(DetectionModule):
    name = "arbitrary_delegatecall"
    swc_id = DELEGATECALL_TO_UNTRUSTED_CONTRACT
    description = "Delegatecall to a user-specified address."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["DELEGATECALL"]

    def _analyze_state(self, state):
        gas = state.mstate.stack[-1]
        to = state.mstate.stack[-2]
        if not to.symbolic:
            return []
        constraints = [
            to == ACTORS.attacker,
        ]
        for tx in state.world_state.transaction_sequence:
            if not isinstance(tx.caller, int) and tx.caller.symbolic:
                constraints.append(tx.caller == ACTORS.attacker)
        try:
            get_model(
                state.world_state.constraints.get_all_constraints() + constraints
            )
        except UnsatError:
            return []
        except Exception:
            return []
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            address=state.get_current_instruction().address,
            swc_id=DELEGATECALL_TO_UNTRUSTED_CONTRACT,
            title="Delegatecall to user-specified address",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head="The contract delegates execution to another contract with a user-supplied address.",
            description_tail=(
                "The smart contract delegates execution to a user-supplied "
                "address. This could allow an attacker to execute arbitrary "
                "code in the context of this contract account and manipulate "
                "the state of the contract account or execute actions on its "
                "behalf."
            ),
            constraints=constraints,
            detector=self,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue
        )
        return []
